"""wire checker: per-site protocol conformance (TP/TN per rule) and
the emitter/handler cross-check, against inline fixture packages;
the real serving/ tree must be clean."""

import os
import textwrap

import pytest

from realhf_tpu.analysis.wire import WireChecker
from realhf_tpu.serving import protocol

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def run_wire(tmp_path, files, with_declaration=False):
    """Write ``files`` into a fixture package and run the checker.

    ``with_declaration`` drops a marker ``protocol.py`` into the tree
    so the project-wide cross-check runs (it is suppressed on fixture
    trees that lack the declaration file).
    """
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    if with_declaration:
        (pkg / "protocol.py").write_text("# declaration marker\n")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return WireChecker(package="pkg").check_project(str(tmp_path))


# ----------------------------------------------------------------------
# per-site rules
# ----------------------------------------------------------------------
def test_literal_kind_flagged(tmp_path, codes_of):
    findings = run_wire(tmp_path, {"shard.py": """
        class S:
            def go(self, ident, rid):
                self._send_ident(ident, "accepted", rid, {})
    """})
    assert "wire-literal-kind" in codes_of(findings)


def test_protocol_constant_clean(tmp_path):
    findings = run_wire(tmp_path, {"shard.py": """
        from realhf_tpu.serving import protocol

        class S:
            def go(self, ident, rid, depth):
                self._send_ident(ident, protocol.ACCEPTED, rid,
                                 dict(queue_depth=depth))
    """})
    assert findings == []


def test_from_imported_constant_clean(tmp_path):
    findings = run_wire(tmp_path, {"shard.py": """
        from realhf_tpu.serving.protocol import ACCEPTED

        class S:
            def go(self, ident, rid):
                self._send_ident(ident, ACCEPTED, rid, {})
    """})
    assert findings == []


def test_dynamic_kind_out_of_scope(tmp_path):
    findings = run_wire(tmp_path, {"shard.py": """
        class S:
            def fwd(self, ident, ev, rid):
                self._send_ident(ident, ev.kind, rid, ev.data)
    """})
    assert findings == []


def test_undeclared_kind_flagged(tmp_path, codes_of):
    findings = run_wire(tmp_path, {"shard.py": """
        from realhf_tpu.serving import protocol

        class S:
            def go(self, ident, rid):
                self._send(ident, "bogus_event", {})
    """})
    assert "wire-undeclared-kind" in codes_of(findings)


def test_undeclared_field_flagged(tmp_path, codes_of):
    findings = run_wire(tmp_path, {"shard.py": """
        from realhf_tpu.serving import protocol

        class S:
            def go(self, ident, rid):
                self._send_ident(ident, protocol.ACCEPTED, rid,
                                 dict(queue_depth=1, typo_field=2))
    """})
    assert codes_of(findings) == ["wire-undeclared-field"]


def test_internal_envelope_whitelisted(tmp_path):
    # scheduler -> server internal envelope: `result` is not a done
    # frame field, but _deliver unpacks it before the wire
    findings = run_wire(tmp_path, {"sched.py": """
        from realhf_tpu.serving import protocol

        def emit(out):
            return ServeEvent(protocol.DONE, out.rid,
                              dict(result=out))
    """})
    assert findings == []


def test_undeclared_reason_flagged(tmp_path, codes_of):
    findings = run_wire(tmp_path, {"shard.py": """
        from realhf_tpu.serving import protocol

        class S:
            def go(self, ident, rid):
                self._send_ident(ident, protocol.REJECTED, rid,
                                 dict(reason="not_a_real_reason"))
    """})
    assert codes_of(findings) == ["wire-undeclared-reason"]


def test_declared_reason_clean(tmp_path):
    findings = run_wire(tmp_path, {"shard.py": """
        from realhf_tpu.serving import protocol

        class S:
            def go(self, ident, rid):
                self._send_ident(
                    ident, protocol.REJECTED, rid,
                    dict(reason=protocol.REASON_BACKPRESSURE))
    """})
    assert findings == []


def test_request_arity_flagged(tmp_path, codes_of):
    findings = run_wire(tmp_path, {"client.py": """
        from realhf_tpu.serving import protocol

        class C:
            def cancel(self, rid):
                self._send_to(self.target,
                              (protocol.CANCEL, rid, "extra"))
    """})
    assert codes_of(findings) == ["wire-request-arity"]


def test_request_arity_clean(tmp_path):
    findings = run_wire(tmp_path, {"client.py": """
        from realhf_tpu.serving import protocol

        class C:
            def cancel(self, rid):
                self._send_to(self.target, (protocol.CANCEL, rid))
    """})
    assert findings == []


def test_slots_tuple_not_flagged(tmp_path):
    # a literal-headed tuple that is NOT a call argument (e.g.
    # __slots__) must not trip the literal-kind rule even when its
    # first element collides with a kind name
    findings = run_wire(tmp_path, {"state.py": """
        class R:
            __slots__ = ("done", "stale", "tokens")
    """})
    assert findings == []


def test_literal_in_kind_compare_flagged(tmp_path, codes_of):
    findings = run_wire(tmp_path, {"pump.py": """
        def on_msg(kind, data):
            if kind == "done":
                return True
    """})
    assert codes_of(findings) == ["wire-literal-kind"]


def test_unrelated_string_compare_clean(tmp_path):
    findings = run_wire(tmp_path, {"cfg.py": """
        def pick(mode):
            if mode == "done":
                return 1
    """})
    assert findings == []


# ----------------------------------------------------------------------
# project-wide cross-check
# ----------------------------------------------------------------------
def test_cross_check_fires_on_empty_tree(tmp_path, codes_of):
    # declaration present but nothing emits/handles anything: every
    # FSM-ridden kind is site-less and every dispatchable kind is
    # unhandled
    findings = run_wire(tmp_path, {"empty.py": "x = 1\n"},
                        with_declaration=True)
    codes = set(codes_of(findings))
    assert "wire-fsm-no-site" in codes
    assert "wire-unhandled-kind" in codes
    by_symbol = {f.symbol for f in findings
                 if f.code == "wire-fsm-no-site"}
    assert protocol.DONE in by_symbol


def test_cross_check_suppressed_without_declaration(tmp_path):
    findings = run_wire(tmp_path, {"empty.py": "x = 1\n"})
    assert findings == []


def test_terminal_membership_handles_all_terminals(tmp_path,
                                                   codes_of):
    # `kind in TERMINAL_KINDS` must count as handling every terminal:
    # no wire-unhandled-kind for done/rejected/... from this tree
    findings = run_wire(tmp_path, {"pump.py": """
        from realhf_tpu.serving.protocol import TERMINAL_KINDS

        def on_msg(kind, data):
            if kind in TERMINAL_KINDS:
                return "closed"
    """}, with_declaration=True)
    unhandled = {f.symbol for f in findings
                 if f.code == "wire-unhandled-kind"}
    assert protocol.DONE not in unhandled
    assert protocol.REJECTED not in unhandled


def test_real_serving_tree_is_clean():
    assert WireChecker().check_project(REPO_ROOT) == []


# ----------------------------------------------------------------------
# --diff integration
# ----------------------------------------------------------------------
@pytest.mark.parametrize("changed,expect", [
    (["realhf_tpu/serving/router_shard.py"], True),
    (["realhf_tpu/serving/protocol.py"], True),
    (["realhf_tpu/system/rollout.py"], False),
    ([], False),
])
def test_diff_relevant_scope(changed, expect):
    assert WireChecker().diff_relevant(changed) is expect
