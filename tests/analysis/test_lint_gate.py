"""Finding model, baseline diffing, suppression, CLI, and the
repo-clean acceptance gate."""

import json
import os
import textwrap

import pytest

from realhf_tpu.analysis import all_checkers, run_analysis
from realhf_tpu.analysis.__main__ import main as lint_main
from realhf_tpu.analysis.baseline import (
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
from realhf_tpu.analysis.finding import Finding

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))

BAD_PURITY = textwrap.dedent("""
    import jax

    @jax.jit
    def step(x):
        return x + x.sum().item()
""")
BAD_CONC = textwrap.dedent("""
    import threading

    def send_locked(lock, sock, payload):
        with lock:
            sock.send_multipart(payload)
""")
BAD_DET = textwrap.dedent("""
    from jax.sharding import PartitionSpec

    def build(layouts):
        return [PartitionSpec(*a) for _, a in layouts.items()]
""")


def _seed_bad_tree(root):
    (root / "purity_mod.py").write_text(BAD_PURITY)
    (root / "conc_mod.py").write_text(BAD_CONC)
    (root / "det_mod.py").write_text(BAD_DET)


# ----------------------------------------------------------------------
def test_fingerprint_ignores_line_numbers():
    a = Finding("jax-purity", "purity-host-sync", "m.py", 10, 4,
                "msg", symbol="f")
    b = Finding("jax-purity", "purity-host-sync", "m.py", 99, 0,
                "msg", symbol="f")
    c = Finding("jax-purity", "purity-host-sync", "m.py", 10, 4,
                "other msg", symbol="f")
    assert a.fingerprint == b.fingerprint != c.fingerprint


def test_baseline_roundtrip_and_diff(tmp_path):
    f1 = Finding("jax-purity", "purity-host-sync", "m.py", 3, 0,
                 "msg", symbol="f")
    f2 = Finding("concurrency", "conc-lock-blocking", "n.py", 7, 0,
                 "msg2", symbol="g")
    path = str(tmp_path / "baseline.json")
    write_baseline(path, [f1, f2])
    baseline = load_baseline(path)
    assert baseline == {f1.fingerprint: 1, f2.fingerprint: 1}

    # same findings: nothing new; f2 missing: reported fixed
    new, fixed = diff_against_baseline([f1, f2], baseline)
    assert new == [] and fixed == []
    new, fixed = diff_against_baseline([f1], baseline)
    assert new == [] and fixed == [f2.fingerprint]
    # a SECOND occurrence of a baselined fingerprint is new
    new, fixed = diff_against_baseline([f1, f1, f2], baseline)
    assert new == [f1] and fixed == []


def test_missing_baseline_means_everything_is_new(tmp_path):
    f1 = Finding("jax-purity", "purity-host-sync", "m.py", 3, 0,
                 "msg", symbol="f")
    baseline = load_baseline(str(tmp_path / "nope.json"))
    new, fixed = diff_against_baseline([f1], baseline)
    assert new == [f1]


def test_file_level_suppression(tmp_path):
    src = ("# graft-lint: disable-file=jax-purity\n" + BAD_PURITY
           + BAD_DET)
    (tmp_path / "mod.py").write_text(src)
    fs = run_analysis([str(tmp_path)], all_checkers(
        ["jax-purity", "collective-determinism"]), root=str(tmp_path))
    assert sorted(f.code for f in fs) == ["det-unsorted-iter"]


# ----------------------------------------------------------------------
def test_cli_fails_on_seeded_bad_tree(tmp_path, capsys,
                                      monkeypatch):
    """Acceptance: nonzero exit on a seeded-bad fixture tree, naming
    file:line and checker id for every family."""
    _seed_bad_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    rc = lint_main([str(tmp_path), "--no-dfg", "--fail-on-new",
                    "--baseline", str(tmp_path / "baseline.json")])
    out = capsys.readouterr().out
    assert rc == 1
    for fname, code in (("purity_mod.py", "purity-host-sync"),
                        ("conc_mod.py", "conc-lock-blocking"),
                        ("det_mod.py", "det-unsorted-iter")):
        line = next(ln for ln in out.splitlines()
                    if fname in ln and code in ln)
        # "NEW path:line:col: code ..." -- file:line coordinates
        assert line.startswith("NEW ")
        assert int(line.split(":")[1]) > 0


def test_cli_baseline_ratchet(tmp_path, capsys, monkeypatch):
    """Accepted findings stay accepted; a NEW violation still fails."""
    _seed_bad_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    baseline = str(tmp_path / "baseline.json")
    rc = lint_main([str(tmp_path), "--no-dfg", "--write-baseline",
                    "--baseline", baseline])
    assert rc == 0
    capsys.readouterr()
    rc = lint_main([str(tmp_path), "--no-dfg", "--fail-on-new",
                    "--baseline", baseline])
    assert rc == 0, capsys.readouterr().out
    capsys.readouterr()
    (tmp_path / "fresh_mod.py").write_text(BAD_PURITY)
    rc = lint_main([str(tmp_path), "--no-dfg", "--fail-on-new",
                    "--baseline", baseline])
    out = capsys.readouterr().out
    assert rc == 1
    assert "fresh_mod.py" in out and "purity-host-sync" in out
    # the old accepted findings are not re-reported as new
    assert "purity_mod.py" not in out


def test_cli_json_format(tmp_path, capsys, monkeypatch):
    _seed_bad_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    rc = lint_main([str(tmp_path), "--no-dfg", "--format", "json"])
    assert rc == 0  # informational mode always exits 0
    data = json.loads(capsys.readouterr().out)
    assert {d["checker"] for d in data} == {
        "jax-purity", "concurrency", "collective-determinism"}
    assert all(d["fingerprint"] for d in data)


def test_cli_unknown_checker_is_usage_error(tmp_path):
    with pytest.raises(SystemExit) as ei:  # argparse choices
        lint_main([str(tmp_path), "--checker", "nope",
                   "--fail-on-new"])
    assert ei.value.code == 2


BAD_LIFECYCLE = textwrap.dedent("""
    def serve(ctx):
        sock = ctx.socket(1)
        sock.bind("tcp://*:0")
""")
BAD_TERMINAL = textwrap.dedent("""
    class S:
        def forget(self, rid):
            self._routes.pop(rid, None)
""")
BAD_LOCKORDER = textwrap.dedent("""
    class C:
        def f(self):
            with self.lock_a:
                with self.lock_b:
                    pass

        def g(self):
            with self.lock_b:
                with self.lock_a:
                    pass
""")


def test_cli_fails_on_v2_families_naming_file_line_code(
        tmp_path, capsys, monkeypatch):
    """Acceptance for the CFG-engine families: seeded-bad fixtures
    make the CLI exit 1, naming file:line and rule code -- and an
    obs-catalog fixture package does the same for the drift pass."""
    fix = tmp_path / "fix"
    fix.mkdir()
    (fix / "life_mod.py").write_text(BAD_LIFECYCLE)
    (fix / "term_mod.py").write_text(BAD_TERMINAL)
    (fix / "lock_mod.py").write_text(BAD_LOCKORDER)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(
        "### Catalog\n\n| Metric | Type |\n|---|---|\n"
        "| `stale_total` | counter |\n")
    (tmp_path / "realhf_tpu").mkdir()
    (tmp_path / "realhf_tpu" / "mod.py").write_text(
        'def f(metrics):\n    metrics.inc("undocumented_total")\n')
    monkeypatch.chdir(tmp_path)
    rc = lint_main([str(fix), "--no-dfg", "--fail-on-new",
                    "--no-cache",
                    "--baseline", str(tmp_path / "baseline.json")])
    out = capsys.readouterr().out
    assert rc == 1
    for fname, code in (
            ("life_mod.py", "lifecycle-unreleased"),
            ("term_mod.py", "proto-missing-terminal"),
            ("lock_mod.py", "conc-lock-cycle"),
            ("mod.py", "obs-catalog-drift"),
            ("observability.md", "obs-catalog-drift")):
        line = next((ln for ln in out.splitlines()
                     if fname in ln and code in ln), None)
        assert line is not None, (fname, code, out)
        assert line.startswith("NEW ")
        assert int(line.split(":")[1]) > 0, line


def test_cfg_finding_fingerprints_survive_line_shifts(tmp_path):
    """Baseline-ratchet stability: CFG-derived findings move lines
    when unrelated code is inserted above, but their fingerprints
    (code+path+symbol+message) must not churn."""
    from realhf_tpu.analysis import all_checkers as mk

    def findings_of(prefix):
        for name, src in (("life_mod.py", BAD_LIFECYCLE),
                          ("term_mod.py", BAD_TERMINAL),
                          ("lock_mod.py", BAD_LOCKORDER)):
            (tmp_path / name).write_text(prefix + src)
        return run_analysis(
            [str(tmp_path)],
            mk(["lifecycle", "terminal", "lockorder"]),
            root=str(tmp_path))

    before = findings_of("")
    after = findings_of("# shifted\n" * 7 + "\n")
    assert len(before) == len(after) == 3
    for a, b in zip(before, after):
        assert b.line == a.line + 8
        assert a.fingerprint == b.fingerprint


def test_family_name_suppresses_v2_codes(tmp_path):
    from realhf_tpu.analysis import all_checkers as mk
    (tmp_path / "mod.py").write_text(
        "# graft-lint: disable-file=terminal\n" + BAD_TERMINAL)
    assert run_analysis([str(tmp_path)], mk(["terminal"]),
                        root=str(tmp_path)) == []


# ----------------------------------------------------------------------
def test_repo_is_lint_clean(monkeypatch, capsys):
    """THE tier-1 acceptance gate: the analyzer runs clean (zero new
    findings vs scripts/lint_baseline.json) on the repo itself,
    including the import-time dfg-invariants pass over every
    registered experiment."""
    monkeypatch.chdir(REPO_ROOT)
    rc = lint_main(["--fail-on-new"])
    out = capsys.readouterr().out
    assert rc == 0, out


def test_check_collect_lint_gate_skips_without_baseline(tmp_path):
    import importlib.util

    path = os.path.join(REPO_ROOT, "scripts", "check_collect.py")
    spec = importlib.util.spec_from_file_location("cc_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ok, report = mod.run_lint_gate(cwd=str(tmp_path))
    assert ok and "skipped" in report.lower()
