"""Result cache (content-hash keyed) and the --diff pre-commit mode,
plus the warm-cache gate-runtime bound the tier-1 budget relies on."""

import os
import subprocess
import textwrap
import time

from realhf_tpu.analysis import ENGINE_VERSION, all_checkers
from realhf_tpu.analysis.__main__ import main as lint_main
from realhf_tpu.analysis.cache import AnalysisCache
from realhf_tpu.analysis.core import run_analysis
from realhf_tpu.analysis.explore import ModelChecker
from realhf_tpu.analysis.wire import WireChecker

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))

BAD_PURITY = textwrap.dedent("""
    import jax

    @jax.jit
    def step(x):
        return x + x.sum().item()
""")
BAD_LIFECYCLE = textwrap.dedent("""
    def serve(ctx):
        sock = ctx.socket(1)
        sock.bind("tcp://*:0")
""")

FIXTURE_FAMILIES = ["jax-purity", "lifecycle", "terminal", "lockorder"]


def seed(tmp_path):
    (tmp_path / "purity_mod.py").write_text(BAD_PURITY)
    (tmp_path / "life_mod.py").write_text(BAD_LIFECYCLE)


def run_cached(tmp_path, cache):
    return run_analysis([str(tmp_path)],
                        all_checkers(FIXTURE_FAMILIES),
                        root=str(tmp_path), cache=cache)


# ----------------------------------------------------------------------
def test_warm_cache_hits_everything(tmp_path):
    seed(tmp_path)
    cdir = str(tmp_path / ".cache")
    cold = run_cached(tmp_path, AnalysisCache(cdir, ENGINE_VERSION))
    warm_cache = AnalysisCache(cdir, ENGINE_VERSION)
    warm = run_cached(tmp_path, warm_cache)
    assert [f.to_json() for f in warm] == [f.to_json() for f in cold]
    assert warm_cache.stats["loaded"]
    assert warm_cache.stats["file_misses"] == 0
    assert warm_cache.stats["file_hits"] > 0
    assert warm_cache.stats["project_hit"] is True


def test_edit_invalidates_only_that_file_locally(tmp_path):
    seed(tmp_path)
    cdir = str(tmp_path / ".cache")
    run_cached(tmp_path, AnalysisCache(cdir, ENGINE_VERSION))
    (tmp_path / "life_mod.py").write_text(
        BAD_LIFECYCLE + "\n# trailing comment\n")
    cache = AnalysisCache(cdir, ENGINE_VERSION)
    findings = run_cached(tmp_path, cache)
    # the unchanged file's per-file results are reused; the edited
    # file re-runs; the whole-tree stamp changed so graph families
    # re-ran too
    assert cache.stats["project_hit"] is False
    assert cache.stats["file_hits"] > 0
    assert cache.stats["file_misses"] > 0
    assert {f.code for f in findings} == {"purity-host-sync",
                                          "lifecycle-unreleased"}


def test_engine_version_bump_discards_cache(tmp_path):
    seed(tmp_path)
    cdir = str(tmp_path / ".cache")
    run_cached(tmp_path, AnalysisCache(cdir, ENGINE_VERSION))
    newer = AnalysisCache(cdir, ENGINE_VERSION + 1)
    assert not newer.stats["loaded"]


def test_corrupt_cache_degrades_to_cold(tmp_path):
    seed(tmp_path)
    cdir = tmp_path / ".cache"
    run_cached(tmp_path, AnalysisCache(str(cdir), ENGINE_VERSION))
    (cdir / "results.pkl").write_bytes(b"not a pickle")
    cache = AnalysisCache(str(cdir), ENGINE_VERSION)
    findings = run_cached(tmp_path, cache)
    assert not cache.stats["loaded"]
    assert {f.code for f in findings} == {"purity-host-sync",
                                          "lifecycle-unreleased"}


# ----------------------------------------------------------------------
def _git(tmp_path, *args):
    return subprocess.run(
        ["git", "-C", str(tmp_path), "-c", "user.email=t@t",
         "-c", "user.name=t", *args],
        capture_output=True, text=True, check=True)


def test_diff_mode_reports_only_changed_files(tmp_path, monkeypatch,
                                              capsys):
    pkg = tmp_path / "realhf_tpu" / "serving"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "old.py").write_text(BAD_PURITY)
    (pkg / "fresh.py").write_text("x = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    # fresh.py gains a violation AFTER the commit; old.py unchanged
    (pkg / "fresh.py").write_text(BAD_LIFECYCLE)
    monkeypatch.chdir(tmp_path)
    rc = lint_main(["--diff", "HEAD", "--no-cache"])
    out = capsys.readouterr().out
    assert rc == 0  # informational mode
    assert "fresh.py" in out and "lifecycle-unreleased" in out
    assert "old.py" not in out  # unchanged file not re-reported


def test_diff_mode_retains_wire_checker(tmp_path, monkeypatch,
                                        capsys):
    # project-wide passes are normally skipped in --diff mode, but
    # wire declares serving/ edits relevant: a literal wire kind
    # introduced after the commit must still be reported
    pkg = tmp_path / "realhf_tpu" / "serving"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "shard.py").write_text("x = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "shard.py").write_text(textwrap.dedent("""
        class S:
            def go(self, ident, rid):
                self._send_ident(ident, "accepted", rid, {})
    """))
    monkeypatch.chdir(tmp_path)
    rc = lint_main(["--diff", "HEAD", "--no-cache"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "wire-literal-kind" in out


def test_wire_and_model_project_results_cached(tmp_path):
    pkg = tmp_path / "realhf_tpu" / "serving"
    pkg.mkdir(parents=True)
    (pkg / "shard.py").write_text(textwrap.dedent("""
        class S:
            def go(self, ident, rid):
                self._send_ident(ident, "accepted", rid, {})
    """))
    cdir = str(tmp_path / ".cache")

    def run(cache):
        return run_analysis([str(tmp_path / "realhf_tpu")],
                            [WireChecker(), ModelChecker()],
                            root=str(tmp_path), cache=cache)

    cold = run(AnalysisCache(cdir, ENGINE_VERSION))
    assert [f.code for f in cold] == ["wire-literal-kind"]
    warm_cache = AnalysisCache(cdir, ENGINE_VERSION)
    warm = run(warm_cache)
    assert [f.to_json() for f in warm] == [f.to_json() for f in cold]
    assert warm_cache.stats["project_hit"] is True
    # editing the scanned tree invalidates the stamp
    (pkg / "shard.py").write_text("x = 2\n")
    edited_cache = AnalysisCache(cdir, ENGINE_VERSION)
    edited = run(edited_cache)
    assert edited_cache.stats["project_hit"] is False
    assert edited == []


def test_diff_mode_clean_when_nothing_changed(tmp_path, monkeypatch,
                                              capsys):
    pkg = tmp_path / "realhf_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text("x = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    monkeypatch.chdir(tmp_path)
    rc = lint_main(["--diff", "--no-cache"])
    assert rc == 0
    assert "no changed .py files" in capsys.readouterr().out


# ----------------------------------------------------------------------
def test_warm_cache_full_gate_runtime(tmp_path, monkeypatch):
    """The tier-1 budget guard: with a warm cache, the full AST+graph
    sweep of the real package must stay bounded on this 1-vCPU box
    (ROADMAP budget note). The dfg/obs project passes are exercised
    by test_repo_is_lint_clean; here we pin the cached sweep."""
    monkeypatch.chdir(REPO_ROOT)
    cdir = str(tmp_path / "gate_cache")
    families = [c.name for c in all_checkers()
                if c.name != "dfg-invariants"]
    run_analysis(["realhf_tpu"], all_checkers(families),
                 root=REPO_ROOT,
                 cache=AnalysisCache(cdir, ENGINE_VERSION))
    cache = AnalysisCache(cdir, ENGINE_VERSION)
    t0 = time.monotonic()
    findings = run_analysis(["realhf_tpu"], all_checkers(families),
                            root=REPO_ROOT, cache=cache)
    warm_secs = time.monotonic() - t0
    assert cache.stats["file_misses"] == 0
    assert cache.stats["project_hit"] is True
    assert findings == []  # the committed baseline is EMPTY
    assert warm_secs < 30.0, (
        f"warm-cache gate took {warm_secs:.1f}s -- the cache layer "
        "regressed; tier-1 cannot afford a full re-analysis per run")
