"""terminal checker: exactly-once terminal delivery fixtures."""

import textwrap

from realhf_tpu.analysis.terminal import TerminalChecker


def check(make_module, src, relpath="fixtures/server.py"):
    module = make_module(textwrap.dedent(src), relpath)
    return TerminalChecker().check(module)


# ----------------------------------------------------------------------
# true positives
# ----------------------------------------------------------------------
def test_retire_without_terminal(make_module, codes_of):
    fs = check(make_module, """
        class S:
            def forget(self, rid):
                self._routes.pop(rid, None)
    """)
    assert codes_of(fs) == ["proto-missing-terminal"]
    assert fs[0].symbol == "S.forget" and "_routes" in fs[0].message


def test_clear_without_terminal(make_module, codes_of):
    fs = check(make_module, """
        class S:
            def flush(self):
                self._requests.clear()
    """)
    assert codes_of(fs) == ["proto-missing-terminal"]


def test_drop_before_send(make_module, codes_of):
    fs = check(make_module, """
        class S:
            def bad(self, rid, ident, payload):
                self._routes.pop(rid, None)
                self._sock.send_multipart([ident, payload])
    """)
    assert codes_of(fs) == ["proto-drop-before-send"]


def test_retire_without_terminal_on_one_branch(make_module, codes_of):
    fs = check(make_module, """
        class S:
            def finish(self, rid, ok):
                if ok:
                    self._send(rid, "done", {})
                    self._requests.pop(rid, None)
                else:
                    self._requests.pop(rid, None)
    """)
    assert codes_of(fs) == ["proto-missing-terminal"]


# ----------------------------------------------------------------------
# true negatives
# ----------------------------------------------------------------------
def test_send_then_drop_is_the_good_shape(make_module):
    assert check(make_module, """
        class S:
            def deliver(self, rid, ident, payload):
                self._sock.send_multipart([ident, payload])
                self._routes.pop(rid, None)
    """) == []


def test_helper_name_counts_as_terminal(make_module):
    assert check(make_module, """
        class S:
            def finish(self, rid):
                self._send(rid, "done", {})
                self._requests.pop(rid, None)
                if rid in self._pending:
                    self._pending.remove(rid)
    """) == []


def test_interprocedural_send_resolution(make_module):
    """`emit` is NOT in the helper-name registry -- it only counts
    because the call graph resolves it to a raw socket send."""
    assert check(make_module, """
        class S:
            def emit(self, ident, kind, rid, data):
                self._front.send_multipart([ident])

            def finish(self, rid, ident):
                self.emit(ident, "done", rid, {})
                self._requests.pop(rid, None)
    """) == []


def test_unrelated_tables_not_tracked(make_module):
    assert check(make_module, """
        class S:
            def bookkeeping(self, rid, rep):
                self._done.pop(rid, None)
                rep.inflight.discard(rid)
                self._events.pop(rid, None)
    """) == []


def test_suppression_with_justification(make_module):
    src = textwrap.dedent("""
        class S:
            def fence(self):
                # deliberate: failover owns the terminals
                self._routes.clear()  # graft-lint: disable=proto-missing-terminal
    """)
    module = make_module(src, "fixtures/server.py")
    checker = TerminalChecker()
    raw = checker.check(module)
    assert [f.code for f in raw] == ["proto-missing-terminal"]
    assert module.suppressions.filter(raw) == []


def test_package_scope_is_limited_to_protocol_files(make_module):
    src = """
        class S:
            def forget(self, rid):
                self._routes.pop(rid, None)
    """
    checker = TerminalChecker()
    assert checker.applies_to("realhf_tpu/serving/router.py")
    assert checker.applies_to("realhf_tpu/serving/server.py")
    assert checker.applies_to("realhf_tpu/serving/scheduler.py")
    assert not checker.applies_to("realhf_tpu/serving/fleet.py")
    assert not checker.applies_to("realhf_tpu/system/buffer.py")
    # outside the package every file is fair game (fixture trees)
    fs = check(make_module, src, relpath="anywhere/mod.py")
    assert [f.code for f in fs] == ["proto-missing-terminal"]
