"""Flagship-scale compile checks: the LLaMA-7B-shaped config's forward
and full train step LOWER AND COMPILE with abstract inputs (AOT --
no 7B weights materialize; VERDICT round-1 weak item 10: 'the 7B path
has never been compiled anywhere'). The scanned-stack design keeps
compile time O(1) in depth, which this also guards."""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig

FLAGSHIP = TransformerConfig(
    n_layers=32, n_kv_heads=32, n_q_heads=32, hidden_dim=4096,
    intermediate_dim=11008, vocab_size=32000, n_positions=4096,
    apply_rotary=True, layer_norm_type="rms", mlp_type="llama",
    use_attention_bias=False, use_attn_proj_bias=False,
    use_mlp_bias=False, activation_function="silu",
    compute_dtype="bfloat16", gradient_checkpointing=True)


def _abstract_params(cfg):
    shapes = jax.eval_shape(
        lambda k: T.init_params(cfg, k), jax.random.PRNGKey(0))
    return shapes


def test_flagship_forward_compiles():
    cfg = FLAGSHIP
    params_shape = _abstract_params(cfg)
    ids = jax.ShapeDtypeStruct((1, 512), jnp.int32)
    seg = jax.ShapeDtypeStruct((1, 512), jnp.int32)

    def fwd(params, ids, seg):
        h, _ = T.forward(cfg, params, ids, seg)
        return T.lm_logits(cfg, params, h)

    t0 = time.monotonic()
    jax.jit(fwd).lower(params_shape, ids, seg).compile()
    dt = time.monotonic() - t0
    assert dt < 300, f"7B forward compile took {dt:.0f}s"


def test_flagship_train_step_compiles():
    """Full fwd+bwd+AdamW at 7B scale compiles abstractly."""
    import optax

    cfg = FLAGSHIP
    params_shape = _abstract_params(cfg)
    tx = optax.adamw(1e-5)
    opt_shape = jax.eval_shape(tx.init, params_shape)
    ids = jax.ShapeDtypeStruct((1, 512), jnp.int32)
    seg = jax.ShapeDtypeStruct((1, 512), jnp.int32)

    def step(params, opt_state, ids, seg):
        def loss_fn(p):
            h, _ = T.forward(cfg, p, ids, seg)
            logits = T.lm_logits(cfg, p, h)
            return jnp.mean(
                jax.nn.logsumexp(logits.astype(jnp.float32), -1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    t0 = time.monotonic()
    jax.jit(step, donate_argnums=(0, 1)).lower(
        params_shape, opt_shape, ids, seg).compile()
    dt = time.monotonic() - t0
    assert dt < 600, f"7B train-step compile took {dt:.0f}s"
