"""Sharded execution tests on the virtual 8-device CPU mesh: forward
under dp x tp (with and without sequence parallelism) must reproduce the
single-device result. Mirrors the role of reference
``tests/model/test_generate.py`` consistency-across-layouts tests.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from realhf_tpu.models import sharding as shard_rules
from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.parallel.mesh import ParallelismConfig, make_mesh


@pytest.fixture(scope="module")
def small_llama():
    cfg = TransformerConfig(
        n_layers=2, n_kv_heads=4, n_q_heads=8, hidden_dim=64,
        intermediate_dim=128, vocab_size=128, apply_rotary=True,
        layer_norm_type="rms", mlp_type="llama", use_attention_bias=False,
        use_attn_proj_bias=False, use_mlp_bias=False,
        activation_function="silu", compute_dtype="float32")
    params = T.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, size=(8, 32)), jnp.int32)
    seg = jnp.asarray(
        np.concatenate([np.full((8, 20), 1), np.full((8, 12), 2)], axis=1),
        jnp.int32)
    return cfg, params, ids, seg


def _reference_logits(cfg, params, ids, seg):
    h, _ = T.forward(cfg, params, ids, seg)
    return np.asarray(T.lm_logits(cfg, params, h))


@pytest.mark.parametrize("dp,tp,sp", [
    (8, 1, False), (1, 8, False), (1, 8, True), (2, 4, False),
    (4, 2, True),
])
def test_sharded_forward_matches_single_device(small_llama, dp, tp, sp):
    cfg, params, ids, seg = small_llama
    expect = _reference_logits(cfg, params, ids, seg)

    parallel = ParallelismConfig(
        data_parallel_size=dp, tensor_parallel_size=tp, sequence_parallel=sp)
    mesh = make_mesh(parallel)
    param_sh = shard_rules.param_shardings(cfg, mesh)
    sharded_params = jax.device_put(params, param_sh)
    batch_sh = NamedSharding(mesh, shard_rules.batch_pspec())
    ids_s = jax.device_put(ids, batch_sh)
    seg_s = jax.device_put(seg, batch_sh)

    constrain = shard_rules.activation_constraint(mesh, sp)

    @jax.jit
    def fwd(p, i, s):
        h, _ = T.forward(cfg, p, i, s, activation_constraint=constrain)
        return T.lm_logits(cfg, p, h)

    got = np.asarray(fwd(sharded_params, ids_s, seg_s))
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)


def test_param_shardings_cover_all_leaves(small_llama):
    cfg, params, _, _ = small_llama
    specs = shard_rules.param_pspecs(cfg)
    # identical tree structure
    assert jax.tree.structure(
        jax.tree.map(lambda _: 0, params)) == jax.tree.structure(
        jax.tree.map(lambda _: 0, specs,
                     is_leaf=lambda x: isinstance(x, P)))


def test_tp_actually_shards_params(small_llama):
    cfg, params, _, _ = small_llama
    mesh = make_mesh(ParallelismConfig(tensor_parallel_size=8))
    sharded = jax.device_put(params, shard_rules.param_shardings(cfg, mesh))
    wq = sharded["blocks"]["attn"]["wq"]
    # each device holds 1/8 of the output features
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert shard_shape == (wq.shape[0], wq.shape[1], wq.shape[2] // 8)


def test_sharded_decode_matches(small_llama):
    cfg, params, ids, _ = small_llama
    mesh = make_mesh(ParallelismConfig(data_parallel_size=2,
                                       tensor_parallel_size=4))
    sharded_params = jax.device_put(
        params, shard_rules.param_shardings(cfg, mesh))

    prompt = ids[:, :16]
    pseg = jnp.ones_like(prompt)

    # single-device reference
    _, cache = T.prefill(cfg, params, prompt, pseg)
    cache = T.extend_kv_cache(cache, 4)
    h_ref, _ = T.decode_step(cfg, params, cache, ids[:, 16],
                             jnp.full((8,), 16, jnp.int32))

    @jax.jit
    def run(p, prompt, pseg, tok):
        _, cache = T.prefill(cfg, p, prompt, pseg)
        cache = T.extend_kv_cache(cache, 4)
        return T.decode_step(cfg, p, cache, tok, jnp.full((8,), 16, jnp.int32))

    h_got, _ = run(sharded_params, prompt, pseg, ids[:, 16])
    np.testing.assert_allclose(np.asarray(h_got), np.asarray(h_ref),
                               rtol=1e-4, atol=1e-4)
