"""Golden-model tests: our transformer must reproduce HuggingFace
logits for randomly initialized models of each supported family, and
checkpoints must round-trip through the HF format.

Mirrors reference ``tests/model/test_cpu_inference.py:80``
(test_inference_cpu_consistency) and ``test_distributed_load_hf.py``.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from realhf_tpu.models import hf as hf_registry
from realhf_tpu.models import transformer as T

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _hf_model(family):
    if family == "llama":
        cfg = transformers.LlamaConfig(
            hidden_size=64, intermediate_size=128, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=2, vocab_size=200,
            max_position_embeddings=128, rms_norm_eps=1e-6, rope_theta=10000.0)
        return transformers.LlamaForCausalLM(cfg)
    if family == "qwen2":
        cfg = transformers.Qwen2Config(
            hidden_size=64, intermediate_size=128, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=2, vocab_size=200,
            max_position_embeddings=128, rms_norm_eps=1e-6)
        return transformers.Qwen2ForCausalLM(cfg)
    if family == "mistral":
        cfg = transformers.MistralConfig(
            hidden_size=64, intermediate_size=128, num_hidden_layers=3,
            num_attention_heads=4, num_key_value_heads=2, vocab_size=200,
            max_position_embeddings=128, sliding_window=None)
        return transformers.MistralForCausalLM(cfg)
    if family == "gpt2":
        cfg = transformers.GPT2Config(
            n_layer=3, n_head=4, n_embd=64, n_positions=128, vocab_size=200,
            embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)
        return transformers.GPT2LMHeadModel(cfg)
    raise NotImplementedError(family)


@pytest.fixture(scope="module", params=["llama", "qwen2", "mistral", "gpt2"])
def saved_hf_model(request, tmp_path_factory):
    family = request.param
    torch.manual_seed(5)
    model = _hf_model(family).eval()
    path = tmp_path_factory.mktemp(f"hf_{family}")
    model.save_pretrained(path, safe_serialization=True)
    return family, model, str(path)


def _hf_logits(model, ids_np):
    with torch.no_grad():
        out = model(input_ids=torch.from_numpy(ids_np).long())
    return out.logits.float().numpy()


class TestHFParity:

    def test_logits_match(self, saved_hf_model):
        family, model, path = saved_hf_model
        cfg, params = hf_registry.load_hf_checkpoint(path, family)
        cfg.compute_dtype = "float32"

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, size=(2, 24)).astype(np.int32)
        seg = np.ones_like(ids)

        hidden, _ = T.forward(cfg, params, jnp.asarray(ids), jnp.asarray(seg))
        ours = np.asarray(T.lm_logits(cfg, params, hidden))
        theirs = _hf_logits(model, ids)
        # fp32 XLA-vs-MKL round-off accumulates to ~3e-3 across layers;
        # structural equivalence is pinned by test_fp64_exact_parity
        # (subprocess, 3e-7). Here we guard against weight/shape bugs.
        np.testing.assert_allclose(ours, theirs, rtol=5e-2, atol=5e-3)
        # random-init models have near-tied logits; allow rare argmax flips
        assert (ours.argmax(-1) == theirs.argmax(-1)).mean() > 0.9

    def test_save_roundtrip_through_hf(self, saved_hf_model, tmp_path):
        family, model, path = saved_hf_model
        cfg, params = hf_registry.load_hf_checkpoint(path, family)
        out_dir = tmp_path / "resaved"
        hf_registry.save_hf_checkpoint(str(out_dir), family, cfg, params)

        reloaded = transformers.AutoModelForCausalLM.from_pretrained(
            str(out_dir)).eval()
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, size=(1, 16)).astype(np.int32)
        np.testing.assert_allclose(
            _hf_logits(reloaded, ids), _hf_logits(model, ids),
            rtol=1e-4, atol=1e-5)

    def test_packed_two_segments_match_separate(self, saved_hf_model):
        """Packing two sequences into one stream must give the same
        logits as running them separately (the packed-varlen contract,
        reference's flash-attn cu_seqlens semantics)."""
        family, model, path = saved_hf_model
        cfg, params = hf_registry.load_hf_checkpoint(path, family)
        cfg.compute_dtype = "float32"

        rng = np.random.default_rng(2)
        a = rng.integers(0, cfg.vocab_size, size=(10,)).astype(np.int32)
        b = rng.integers(0, cfg.vocab_size, size=(14,)).astype(np.int32)
        packed = np.concatenate([a, b])[None]
        seg = np.concatenate([np.full(10, 1), np.full(14, 2)])[None].astype(np.int32)

        hidden, _ = T.forward(cfg, params, jnp.asarray(packed), jnp.asarray(seg))
        ours = np.asarray(T.lm_logits(cfg, params, hidden))[0]
        ha = _hf_logits(model, a[None])[0]
        hb = _hf_logits(model, b[None])[0]
        np.testing.assert_allclose(ours[:10], ha, rtol=5e-2, atol=5e-3)
        np.testing.assert_allclose(ours[10:], hb, rtol=5e-2, atol=5e-3)

    def test_critic_checkpoint_roundtrip(self, saved_hf_model, tmp_path):
        family, _, path = saved_hf_model
        cfg, params = hf_registry.load_hf_checkpoint(path, family,
                                                     is_critic=True)
        assert params["head"]["w"].shape == (cfg.hidden_dim, 1)
        out_dir = tmp_path / "critic"
        hf_registry.save_hf_checkpoint(str(out_dir), family, cfg, params)
        cfg2, params2 = hf_registry.load_hf_checkpoint(str(out_dir), family,
                                                       is_critic=True)
        np.testing.assert_array_equal(params["head"]["w"], params2["head"]["w"])
        rng = np.random.default_rng(3)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, 8)),
                          dtype=jnp.int32)
        hidden, _ = T.forward(cfg2, params2, ids, jnp.ones_like(ids))
        vals = T.critic_values(cfg2, params2, hidden)
        assert vals.shape == (1, 8)


def test_fp64_exact_parity(saved_hf_model):
    """Run the llama comparison in a subprocess with x64 enabled: fp64
    logits must match HF to float-noise level, pinning structural
    equivalence (x64 is a process-global jax flag, hence subprocess)."""
    import subprocess
    import sys

    family, _, path = saved_hf_model
    if family != "llama":
        pytest.skip("fp64 pinning uses llama only")
    code = f"""
from realhf_tpu.base.backend import force_cpu_backend
force_cpu_backend()
import jax
jax.config.update("jax_enable_x64", True)
import numpy as np, torch, transformers, jax.numpy as jnp
from realhf_tpu.models import hf as hfreg
from realhf_tpu.models import transformer as T
model = transformers.AutoModelForCausalLM.from_pretrained({path!r}).eval().double()
cfg, params = hfreg.load_hf_checkpoint({path!r}, "llama")
cfg.compute_dtype = cfg.param_dtype = "float64"
params = jax.tree.map(lambda a: jnp.asarray(a, jnp.float64), params)
rng = np.random.default_rng(0)
ids = rng.integers(0, cfg.vocab_size, size=(2, 24)).astype(np.int32)
with torch.no_grad():
    theirs = model(input_ids=torch.from_numpy(ids).long()).logits.numpy()
h, _ = T.forward(cfg, params, jnp.asarray(ids), jnp.ones((2, 24), jnp.int32))
ours = np.asarray(T.lm_logits(cfg, params, h))
assert np.abs(ours - theirs).max() < 1e-5, np.abs(ours - theirs).max()
print("FP64 PARITY OK")
"""
    res = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, cwd="/root/repo", timeout=600)
    assert "FP64 PARITY OK" in res.stdout, res.stdout + res.stderr
