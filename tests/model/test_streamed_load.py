"""Streamed (host-RAM-bounded) checkpoint loading: for every family,
``load_hf_checkpoint_streamed`` must place EXACTLY the weights the
eager loader reads -- sharded on the mesh, vocab-padded for its tp --
while only ever holding one layer (plus embeddings) on host."""

import numpy as np
import pytest

import jax

from realhf_tpu.models import sharding as shard_rules
from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import MoEConfig, TransformerConfig
from realhf_tpu.models.hf import (
    load_hf_checkpoint,
    load_hf_checkpoint_streamed,
    save_hf_checkpoint,
    save_hf_checkpoint_streamed,
)
from realhf_tpu.parallel.mesh import ParallelismConfig, make_mesh


def _cfg(family, vocab=96):
    base = dict(n_layers=3, n_kv_heads=2, n_q_heads=4, hidden_dim=32,
                intermediate_dim=64, vocab_size=vocab, n_positions=128,
                compute_dtype="float32")
    if family == "gpt2":
        g = dict(base, n_kv_heads=4)  # gpt2 fused c_attn has no GQA
        return TransformerConfig(
            layer_norm_type=None, mlp_type=None,
            activation_function="gelu_new", apply_rotary=False,
            use_attention_bias=True, use_attn_proj_bias=True,
            use_mlp_bias=True, tied_embedding=True, **g)
    if family == "mixtral":
        return TransformerConfig(
            layer_norm_type="rms", mlp_type="moe",
            activation_function="silu", apply_rotary=True,
            use_attention_bias=False, use_attn_proj_bias=False,
            use_mlp_bias=False,
            moe=MoEConfig(num_experts=4, top_k=2), **base)
    if family == "gemma":
        return TransformerConfig(
            layer_norm_type="gemma", mlp_type="llama",
            activation_function="gelu_new", apply_rotary=True,
            use_attention_bias=False, use_attn_proj_bias=False,
            use_mlp_bias=False, normalize_embed=True,
            tied_embedding=True, **base)
    return TransformerConfig(
        layer_norm_type="rms", mlp_type="llama",
        activation_function="silu", apply_rotary=True,
        use_attention_bias=False, use_attn_proj_bias=False,
        use_mlp_bias=False, **base)


@pytest.mark.parametrize("family", ["llama", "gpt2", "mixtral", "gemma"])
def test_streamed_matches_eager(family, tmp_path):
    cfg = _cfg(family)
    params = T.init_params(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / family)
    save_hf_checkpoint(path, family, cfg,
                       jax.tree.map(np.asarray, params))

    par = ParallelismConfig(data_parallel_size=4, tensor_parallel_size=2)
    mesh = make_mesh(par)
    cfg_s, streamed = load_hf_checkpoint_streamed(path, mesh,
                                                  family=family)
    cfg_e, eager = load_hf_checkpoint(path, family=family)
    assert cfg_s.n_layers == cfg_e.n_layers == cfg.n_layers

    host = shard_rules.unpad_vocab(
        cfg_s, jax.tree.map(np.asarray, streamed))
    e_flat = jax.tree_util.tree_flatten_with_path(eager)[0]
    s_flat = jax.tree_util.tree_flatten_with_path(host)[0]
    assert [k for k, _ in e_flat] == [k for k, _ in s_flat]
    for (kp, a), (_, b) in zip(e_flat, s_flat):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6, atol=1e-7, err_msg=str(kp))

    # leaves really landed sharded on the mesh
    wq = streamed["blocks"]["attn"]["wq"]
    assert wq.sharding.mesh.shape == mesh.shape


def test_streamed_critic_value_head(tmp_path):
    cfg = _cfg("llama")
    params = T.init_params(cfg, jax.random.PRNGKey(1))
    path = str(tmp_path / "actor")
    save_hf_checkpoint(path, "llama", cfg,
                       jax.tree.map(np.asarray, params))

    par = ParallelismConfig(data_parallel_size=4, tensor_parallel_size=2)
    mesh = make_mesh(par)
    cfg_s, streamed = load_hf_checkpoint_streamed(
        path, mesh, family="llama", is_critic=True)
    cfg_e, eager = load_hf_checkpoint(path, family="llama",
                                      is_critic=True)
    assert cfg_s.is_critic
    np.testing.assert_allclose(
        np.asarray(streamed["head"]["w"], np.float32),
        np.asarray(eager["head"]["w"], np.float32), rtol=1e-6)


def test_streamed_bare_gpt2_naming(tmp_path):
    """Bare GPT2Model exports (no ``transformer.`` container prefix)
    load through the lazy PrefixedStateView on the streamed path just
    as the eager loader's dict-rename fallback does."""
    import json
    import os

    import safetensors.numpy

    cfg = _cfg("gpt2")
    params = T.init_params(cfg, jax.random.PRNGKey(4))
    src = str(tmp_path / "full")
    save_hf_checkpoint(src, "gpt2", cfg, jax.tree.map(np.asarray, params))

    bare = str(tmp_path / "bare")
    os.makedirs(bare)
    state = {}
    for f in os.listdir(src):
        if f.endswith(".safetensors"):
            state.update(safetensors.numpy.load_file(os.path.join(src, f)))
    stripped = {
        (k[len("transformer."):] if k.startswith("transformer.") else k): v
        for k, v in state.items() if k != "lm_head.weight"}
    safetensors.numpy.save_file(
        stripped, os.path.join(bare, "model.safetensors"))
    with open(os.path.join(src, "config.json")) as f:
        conf = json.load(f)
    with open(os.path.join(bare, "config.json"), "w") as f:
        json.dump(conf, f)

    mesh = make_mesh(ParallelismConfig(data_parallel_size=4,
                                       tensor_parallel_size=2))
    _, streamed = load_hf_checkpoint_streamed(bare, mesh, family="gpt2")
    _, eager = load_hf_checkpoint(bare, family="gpt2")
    host = shard_rules.unpad_vocab(cfg, jax.tree.map(np.asarray, streamed))
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(eager)[0],
            jax.tree_util.tree_flatten_with_path(host)[0]):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-6, err_msg=str(kp))


def test_build_model_streamed_flag(tmp_path):
    """ModelSpec.streamed_load routes build_model through the
    streaming loader and yields the same weights as the eager path."""
    from realhf_tpu.api.experiment import ModelSpec
    from realhf_tpu.system.model_host import build_model

    cfg = _cfg("llama")
    params = T.init_params(cfg, jax.random.PRNGKey(3))
    path = str(tmp_path / "m")
    save_hf_checkpoint(path, "llama", cfg,
                       jax.tree.map(np.asarray, params))

    par = ParallelismConfig(data_parallel_size=4, tensor_parallel_size=2)
    kw = dict(path=path, hf_family="llama", parallel=par, bf16=False)
    m_s = build_model("actor", ModelSpec(streamed_load=True, **kw),
                      None, 10)
    m_e = build_model("actor", ModelSpec(**kw), None, 10)
    for a, b in zip(jax.tree.leaves(m_s.engine.params),
                    jax.tree.leaves(m_e.engine.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=1e-6)


def test_streamed_load_auto_threshold(tmp_path, monkeypatch):
    """streamed_load=None streams automatically for checkpoints whose
    safetensors total exceeds the cutoff, stays eager below it, and
    False forces eager regardless."""
    from realhf_tpu.api.experiment import ModelSpec
    from realhf_tpu.system import model_host

    cfg = _cfg("llama")
    params = T.init_params(cfg, jax.random.PRNGKey(7))
    path = str(tmp_path / "m")
    save_hf_checkpoint(path, "llama", cfg,
                       jax.tree.map(np.asarray, params))

    spec = ModelSpec(path=path, hf_family="llama")
    assert not model_host._use_streamed_load(spec)  # tiny -> eager
    monkeypatch.setattr(model_host, "STREAMED_LOAD_AUTO_BYTES", 1)
    assert model_host._use_streamed_load(spec)      # auto-streams
    # auto streams on process-spanning meshes too: every member sizes
    # the same spec.path, so the collective schedule agrees (r5: the
    # multiproc -> eager restriction is lifted)
    assert model_host._use_streamed_load(spec, multiproc=True)
    assert model_host._use_streamed_load(
        ModelSpec(path=path, hf_family="llama", streamed_load=True),
        multiproc=True)
    spec_off = ModelSpec(path=path, hf_family="llama",
                         streamed_load=False)
    assert not model_host._use_streamed_load(spec_off)  # forced eager


def test_streamed_vocab_padding_roundtrip(tmp_path):
    """vocab_size NOT divisible by tp: the streamed loader must pad
    wte/head for the mesh's tp and the streamed saver must strip that
    padding back to the true vocab (the early-return paths hide both
    when vocab % tp == 0)."""
    import jax.numpy as jnp

    cfg = _cfg("llama", vocab=97)  # 97 % 2 != 0 -> real padding
    host = jax.tree.map(np.asarray, T.init_params(cfg,
                                                  jax.random.PRNGKey(6)))
    path = str(tmp_path / "m")
    save_hf_checkpoint(path, "llama", cfg, host)

    mesh = make_mesh(ParallelismConfig(data_parallel_size=4,
                                       tensor_parallel_size=2))
    _, streamed = load_hf_checkpoint_streamed(path, mesh, family="llama")
    assert streamed["embed"]["wte"].shape[0] == 98  # padded to tp mult
    assert streamed["head"]["w"].shape[1] == 98
    back = shard_rules.unpad_vocab(cfg, jax.tree.map(np.asarray, streamed))
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(host)[0],
            jax.tree_util.tree_flatten_with_path(back)[0]):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-6, atol=1e-7, err_msg=str(kp))

    # streamed SAVE from the padded device params strips the padding
    out = str(tmp_path / "out")
    save_hf_checkpoint_streamed(out, "llama", cfg, streamed)
    _, loaded = load_hf_checkpoint(out, family="llama")
    assert loaded["embed"]["wte"].shape[0] == 97
    np.testing.assert_allclose(
        np.asarray(loaded["head"]["w"], np.float32),
        np.asarray(host["head"]["w"], np.float32), rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("family", ["llama", "mixtral"])
def test_streamed_save_roundtrip(family, tmp_path):
    """save_hf_checkpoint_streamed (one shard per layer, sliced from
    sharded device arrays) produces a directory the EAGER loader reads
    back to the exact original weights."""
    import jax.numpy as jnp

    cfg = _cfg(family)
    host = jax.tree.map(np.asarray, T.init_params(cfg,
                                                  jax.random.PRNGKey(5)))
    mesh = make_mesh(ParallelismConfig(data_parallel_size=4,
                                       tensor_parallel_size=2))
    padded = shard_rules.pad_vocab(cfg, host, 2)
    dev = jax.tree_util.tree_map_with_path(
        lambda kp, leaf: jax.device_put(
            jnp.asarray(leaf),
            _sharding_at(shard_rules.param_shardings(cfg, mesh), kp)),
        padded)
    path = str(tmp_path / "out")
    save_hf_checkpoint_streamed(path, family, cfg, dev)

    import os
    shard_files = [f for f in os.listdir(path)
                   if f.endswith(".safetensors")]
    assert len(shard_files) == cfg.n_layers + 1  # one per layer + rest

    _, loaded = load_hf_checkpoint(path, family=family)
    e_flat = jax.tree_util.tree_flatten_with_path(host)[0]
    l_flat = jax.tree_util.tree_flatten_with_path(loaded)[0]
    assert [k for k, _ in e_flat] == [k for k, _ in l_flat]
    for (kp, a), (_, b) in zip(e_flat, l_flat):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-6, atol=1e-7, err_msg=str(kp))


def _sharding_at(shardings, kp):
    node = shardings
    for entry in kp:
        node = node[entry.key]
    return node


def test_streamed_bf16_cast(tmp_path):
    cfg = _cfg("llama")
    params = T.init_params(cfg, jax.random.PRNGKey(2))
    path = str(tmp_path / "m")
    save_hf_checkpoint(path, "llama", cfg,
                       jax.tree.map(np.asarray, params))
    mesh = make_mesh(ParallelismConfig(data_parallel_size=8))
    cfg_s, streamed = load_hf_checkpoint_streamed(
        path, mesh, family="llama", param_dtype="bfloat16")
    import jax.numpy as jnp
    for leaf in jax.tree.leaves(streamed):
        assert leaf.dtype == jnp.bfloat16


def test_agreed_streamed_load_follows_leader(tmp_path, monkeypatch):
    """On a process-spanning mesh the auto verdict is GROUP-AGREED:
    the lowest-rank process publishes via name_resolve and members
    adopt it even when their own filesystem view would disagree
    (stale-NFS divergence would otherwise hang mismatched collective
    load schedules)."""
    import collections

    import jax as _jax

    from realhf_tpu.api.experiment import ModelSpec
    from realhf_tpu.base import constants
    from realhf_tpu.system import model_host

    cfg = _cfg("llama")
    params = T.init_params(cfg, jax.random.PRNGKey(9))
    path = str(tmp_path / "m")
    save_hf_checkpoint(path, "llama", cfg,
                       jax.tree.map(np.asarray, params))
    spec = ModelSpec(path=path, hf_family="llama")

    monkeypatch.setattr(constants, "_experiment_name", "agreetest")
    monkeypatch.setattr(constants, "_trial_name", "t0")

    Dev = collections.namedtuple("Dev", "process_index")

    class FakeMesh:
        class devices:
            flat = [Dev(0), Dev(1)]

    # leader (process 0): sizes the checkpoint -> streams (cutoff 1)
    monkeypatch.setattr(model_host, "STREAMED_LOAD_AUTO_BYTES", 1)
    monkeypatch.setattr(_jax, "process_index", lambda: 0)
    assert model_host._agreed_streamed_load(spec, FakeMesh, "roleA")

    # member (process 1) with a DIVERGENT local view (cutoff back to
    # huge -> its own verdict would be eager): adopts the leader's
    monkeypatch.setattr(model_host, "STREAMED_LOAD_AUTO_BYTES", 1e18)
    monkeypatch.setattr(_jax, "process_index", lambda: 1)
    assert model_host._agreed_streamed_load(spec, FakeMesh, "roleA")

    # explicit flag short-circuits the rendezvous entirely (patch
    # back to the leader so a regression fails fast instead of
    # stalling in the member's 300s name_resolve wait)
    monkeypatch.setattr(_jax, "process_index", lambda: 0)
    spec_off = ModelSpec(path=path, hf_family="llama",
                         streamed_load=False)
    assert not model_host._agreed_streamed_load(spec_off, FakeMesh,
                                                "roleB")
