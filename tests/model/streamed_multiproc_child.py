"""Child process body for the 2-process streamed-checkpoint test
(no ``test_`` prefix: pytest must not collect this; it is spawned by
``test_streamed_multiproc.py`` with a fixed rank).

Protocol: argv = [rank, n_procs, coordinator, ckpt_dir, out_dir].
Joins a 2-process jax.distributed world (2 virtual CPU devices per
process), streams the checkpoint onto a d2t2 mesh spanning both
processes, asserts host RSS stayed layer-bounded (never full-model),
streams a SAVE back out (leader writes, member joins the collective
gathers), and rank 0 verifies the round-trip bit-exactly.
"""

import resource
import sys

import numpy as np


def rss_bytes() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def main():
    rank, n_procs = int(sys.argv[1]), int(sys.argv[2])
    coordinator, ckpt_dir, out_dir = sys.argv[3], sys.argv[4], sys.argv[5]

    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n_procs, process_id=rank,
                               local_device_ids=[0, 1])
    assert jax.device_count() == 4, jax.devices()

    from realhf_tpu.models.hf import (
        load_hf_checkpoint_streamed,
        save_hf_checkpoint_streamed,
    )
    from realhf_tpu.parallel.mesh import ParallelismConfig, make_mesh

    mesh = make_mesh(ParallelismConfig(data_parallel_size=2,
                                       tensor_parallel_size=2))
    procs = {d.process_index for d in mesh.devices.flat}
    assert procs == {0, 1}, procs

    # warm up the runtime so baseline RSS includes jax/XLA overhead
    jax.block_until_ready(
        jax.jit(lambda x: x * 2)(np.ones((4, 4), np.float32)))

    rss0 = rss_bytes()
    cfg, params = load_hf_checkpoint_streamed(ckpt_dir, mesh,
                                              family="llama")
    jax.block_until_ready(params)
    load_delta = rss_bytes() - rss0

    model_bytes = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(params))
    # Host-RAM bound: the streamed load holds one layer (+ embeddings
    # + this process's device shards, which live in RSS on the CPU
    # backend) -- materializing the full model host-side even once
    # would push the delta past model_bytes.
    assert load_delta < model_bytes, (load_delta, model_bytes)

    save_hf_checkpoint_streamed(out_dir, "llama", cfg, params,
                                writer=(rank == 0))

    if rank == 0:
        import os

        from realhf_tpu.models.hf import load_hf_checkpoint

        _, orig = load_hf_checkpoint(ckpt_dir, family="llama")
        _, rt = load_hf_checkpoint(out_dir, family="llama")
        o_flat = jax.tree_util.tree_flatten_with_path(orig)[0]
        r_flat = jax.tree_util.tree_flatten_with_path(rt)[0]
        assert [k for k, _ in o_flat] == [k for k, _ in r_flat]
        for (kp, a), (_, b) in zip(o_flat, r_flat):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=str(kp))
        # per-layer shards really exist (streamed layout, not one blob)
        shards = [f for f in os.listdir(out_dir)
                  if f.endswith(".safetensors")]
        assert len(shards) == cfg.n_layers + 1, shards
    print(f"CHILD{rank} OK load_delta_mb={load_delta / 1e6:.1f} "
          f"model_mb={model_bytes / 1e6:.1f}", flush=True)


if __name__ == "__main__":
    main()
