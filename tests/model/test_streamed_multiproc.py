"""Streamed checkpoint IO across PROCESS boundaries (VERDICT r4 #5):
a d2t2 mesh spanning two OS processes streams a load (per-layer
collective placement) and a save (per-layer collective gathers,
leader-only writes) with host RSS bounded well under the full model.
Reference analog: per-rank shard reads, ``conversion/hf_registry.py``.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.models.hf import save_hf_checkpoint

CHILD = os.path.join(os.path.dirname(__file__),
                     "streamed_multiproc_child.py")


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_streamed_roundtrip_two_processes(tmp_path):
    # ~29M params (~115 MB fp32): big enough that a full-model host
    # materialization visibly breaks the child's RSS bound, small
    # enough to keep the test fast.
    cfg = TransformerConfig(
        n_layers=6, n_kv_heads=4, n_q_heads=8, hidden_dim=512,
        intermediate_dim=1536, vocab_size=8192, n_positions=256,
        layer_norm_type="rms", mlp_type="llama",
        activation_function="silu", apply_rotary=True,
        use_attention_bias=False, use_attn_proj_bias=False,
        use_mlp_bias=False, compute_dtype="float32")
    params = T.init_params(cfg, jax.random.PRNGKey(0))
    ckpt = str(tmp_path / "ckpt")
    save_hf_checkpoint(ckpt, "llama", cfg,
                       jax.tree.map(np.asarray, params))
    out = str(tmp_path / "saved")

    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(
        os.environ,
        PYTHONPATH="/root/repo",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
    )
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(rank), "2", coordinator,
             ckpt, out],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for rank in range(2)
    ]
    try:
        outs = []
        for rank, p in enumerate(procs):
            stdout, _ = p.communicate(timeout=600)
            outs.append(stdout)
            assert p.returncode == 0, (
                f"child {rank} failed:\n{stdout}")
        assert all(f"CHILD{r} OK" in outs[r] for r in range(2)), outs
    finally:
        for p in procs:  # a deadlocked child must not outlive the test
            if p.poll() is None:
                p.kill()
