"""Tier-1 coverage for the trace analyzer (obs/analyze.py) on the
committed synthetic trace fixture: attribution components sum to the
step wall, the critical path names the right MFC per step, straggler
ranking and goodput match hand-computed values, and the CLI writes
the same report. The fixture mirrors the runtime's real span shapes
(step -> dispatch:* -> mfc:* -> data_fetch/realloc/compute:* with
cross-process parentage in args)."""

import json
import os

import pytest

from realhf_tpu.obs import analyze

FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                       "synthetic_trace.json")


@pytest.fixture()
def report():
    return analyze.analyze_path(FIXTURE)


def test_attribution_components_sum_to_step_wall(report):
    assert report["n_steps"] == 2
    walls = [10.0, 8.0]
    for step, wall in zip(report["steps"], walls):
        assert step["wall_secs"] == pytest.approx(wall, abs=1e-6)
        assert sum(step["attribution"].values()) == pytest.approx(
            wall, abs=1e-6)
    # hand-computed step-1 decomposition (priority: compute >
    # data_fetch > realloc > dispatch > idle)
    a1 = report["steps"][0]["attribution"]
    assert a1["compute"] == pytest.approx(7.9, abs=1e-6)
    assert a1["data_fetch"] == pytest.approx(0.4, abs=1e-6)
    assert a1["realloc"] == pytest.approx(0.5, abs=1e-6)
    assert a1["dispatch"] == pytest.approx(1.0, abs=1e-6)
    assert a1["idle"] == pytest.approx(0.2, abs=1e-6)
    a2 = report["steps"][1]["attribution"]
    assert a2["compute"] == pytest.approx(7.0, abs=1e-6)
    assert a2["dispatch"] == pytest.approx(0.5, abs=1e-6)
    assert a2["idle"] == pytest.approx(0.5, abs=1e-6)
    assert report["wall_secs"] == pytest.approx(18.0, abs=1e-6)


def test_critical_path_names_bottleneck_mfc(report):
    s1, s2 = report["steps"]
    # step 1: actor_train's dispatch finishes last (9.8s vs 6.0s)
    assert s1["bottleneck_mfc"] == "actor_train"
    assert s1["critical_path"] == [
        "dispatch:actor_train", "mfc:actor_train",
        "compute:actor_train"]
    # step 2: actor_gen dominates (17.5s vs 13.0s)
    assert s2["bottleneck_mfc"] == "actor_gen"
    assert s2["critical_path"][0] == "dispatch:actor_gen"
    # modal bottleneck tie (1 step each) breaks on dispatch seconds:
    # actor_gen carries 13.5s vs actor_train's 6.8s
    assert report["bottleneck_mfc"] == "actor_gen"
    assert report["bottleneck_counts"] == {"actor_gen": 1,
                                           "actor_train": 1}
    assert report["mfc_secs"]["actor_gen"] == pytest.approx(
        13.5, abs=1e-6)
    assert report["mfc_secs"]["actor_train"] == pytest.approx(
        6.8, abs=1e-6)


def test_straggler_ranking_and_goodput(report):
    # busy time: worker 0 = 5.4 + 7.0 = 12.4s; worker 1 = 3.4 + 2.5
    # = 5.9s; median 9.15 -> skew +/-3.25
    stragglers = report["stragglers"]
    assert [s["worker"] for s in stragglers] == [
        "model_worker/0", "model_worker/1"]
    assert stragglers[0]["busy_secs"] == pytest.approx(12.4, abs=1e-6)
    assert stragglers[0]["skew_vs_median_secs"] == pytest.approx(
        3.25, abs=1e-6)
    assert stragglers[1]["skew_vs_median_secs"] == pytest.approx(
        -3.25, abs=1e-6)
    # goodput: compute-union 7.9 + 7.0 over 18s wall
    assert report["goodput"] == pytest.approx(14.9 / 18.0, abs=1e-3)
    # per-worker normalization: (8.8 + 9.5) / (10*2 + 8*2)
    assert report["goodput_per_worker"] == pytest.approx(
        18.3 / 36.0, abs=1e-3)
    # workers resolve via span attrs AND pid lanes (compute spans
    # carry no worker attr in the real runtime)
    assert report["steps"][0]["workers"]["model_worker/0"] == \
        pytest.approx(5.4, abs=1e-6)


def test_jsonl_shard_loading(tmp_path, report):
    """A per-process .trace.jsonl shard (one event per line, plus a
    corrupt line) analyzes identically to the merged JSON."""
    events = json.load(open(FIXTURE))["traceEvents"]
    shard = tmp_path / "proc.trace.jsonl"
    with open(shard, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        f.write("{corrupt half-written line\n")
    again = analyze.analyze_path(str(shard))
    assert again["n_steps"] == 2
    assert again["attribution"] == report["attribution"]
    # and a directory of shards loads the same way
    assert analyze.analyze_path(str(tmp_path))["n_steps"] == 2


def test_rendering_and_empty_trace(tmp_path):
    report = analyze.analyze_path(FIXTURE)
    text = analyze.format_report(report)
    assert "goodput" in text and "actor_gen" in text
    assert "model_worker/0" in text
    line = analyze.one_line_summary(report)
    assert line.startswith("trace report:")
    assert "bottleneck MFC actor_gen" in line
    assert "straggler model_worker/0" in line
    # step-less trace: a report, not a crash
    empty = tmp_path / "empty.json"
    empty.write_text('{"traceEvents": []}')
    rep = analyze.analyze_path(str(empty))
    assert rep["n_steps"] == 0 and "error" in rep
    assert analyze.one_line_summary(rep).startswith("trace report:")
    assert analyze.summarize_path(str(empty)) is not None
    assert analyze.summarize_path(str(tmp_path / "missing.json")) \
        is None


def test_cli_writes_json_report(tmp_path, capsys):
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "..",
                        "scripts", "analyze_trace.py")
    spec = importlib.util.spec_from_file_location("analyze_trace",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "report.json"
    rc = mod.main([FIXTURE, "--json", str(out), "--quiet"])
    assert rc == 0
    printed = capsys.readouterr().out
    assert printed.startswith("trace report:")
    doc = json.loads(out.read_text())
    assert doc["n_steps"] == 2
    assert doc["bottleneck_mfc"] == "actor_gen"
