"""Tier-1 coverage for the live HTTP telemetry plane (obs/http.py):
all four endpoints on an ephemeral port, content types, the
healthz drain flip, concurrent scrapes, the Prometheus text
parser round-trip, and the bucket->quantile estimator. All
single-process and sub-second -- the multi-process fleet scrape is
the slow-marked e2e in test_scrape_e2e.py."""

import json
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import pytest

from realhf_tpu.obs import flight, http, metrics


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, dict(r.headers), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


@pytest.fixture()
def server():
    state = {"state": "RUNNING", "worker": "tw/0",
             "heartbeat_age_secs": 0.1}
    srv = http.TelemetryServer("tw/0", health=lambda: dict(state))
    srv.start()
    yield srv, state
    srv.stop()


def test_metrics_endpoint_serves_prometheus_text(server):
    srv, _ = server
    metrics.inc("demo_requests_total", route="a")
    metrics.set_gauge("demo_queue_depth", 7)
    metrics.observe_hist("demo_latency_seconds", 0.2)
    code, headers, body = _get(srv.port, "/metrics")
    assert code == 200
    assert headers["Content-Type"].startswith("text/plain")
    assert "version=0.0.4" in headers["Content-Type"]
    assert 'demo_requests_total{route="a"} 1' in body
    assert "demo_queue_depth 7" in body
    assert "demo_latency_seconds_bucket" in body
    assert "# TYPE demo_latency_seconds histogram" in body


def test_healthz_flips_state_on_drain(server):
    srv, state = server
    code, headers, body = _get(srv.port, "/healthz")
    assert code == 200
    assert headers["Content-Type"].startswith("application/json")
    doc = json.loads(body)
    assert doc["state"] == "RUNNING" and doc["worker"] == "tw/0"
    # the drain flip: a non-healthy state answers 503 so probing LBs
    # stop routing the moment a drain starts
    state["state"] = "DRAINING"
    code, _, body = _get(srv.port, "/healthz")
    assert code == 503
    assert json.loads(body)["state"] == "DRAINING"
    # a broken provider degrades to an unhealthy answer, not a crash
    srv._health = lambda: 1 / 0
    code, _, body = _get(srv.port, "/healthz")
    assert code == 503
    assert json.loads(body)["state"] == "error"


def test_flight_and_statusz(server):
    srv, _ = server
    flight.record("request", handle="train_step")
    flight.record("reply", handle="train_step")
    code, _, body = _get(srv.port, "/flight")
    assert code == 200
    doc = json.loads(body)
    assert doc["n_events"] == 2
    assert doc["events"][0]["kind"] == "request"

    metrics.inc("demo_requests_total")
    code, _, body = _get(srv.port, "/statusz")
    assert code == 200
    doc = json.loads(body)
    assert doc["process"] == "tw/0"
    assert doc["flight_events"] == 2
    assert doc["trace"]["enabled"] is False
    assert "demo_requests_total" in doc["metrics"]
    assert doc["health"]["state"] == "RUNNING"


def test_unknown_path_is_404(server):
    srv, _ = server
    code, _, _ = _get(srv.port, "/nope")
    assert code == 404


def test_concurrent_scrapes(server):
    srv, _ = server
    metrics.inc("demo_requests_total")
    with ThreadPoolExecutor(max_workers=8) as ex:
        results = list(ex.map(
            lambda _: _get(srv.port, "/metrics"), range(16)))
    assert all(code == 200 for code, _, _ in results)
    assert all("demo_requests_total 1" in body
               for _, _, body in results)


def test_parse_prometheus_roundtrip():
    metrics.inc("rt_requests_total", route="a", code="200")
    metrics.inc("rt_requests_total", 2, route="b", code="500")
    metrics.set_gauge("rt_depth", 3.5)
    metrics.observe_hist("rt_latency_seconds", 0.01)
    metrics.observe_hist("rt_latency_seconds", 0.3)
    fams = http.parse_prometheus_text(metrics.to_prometheus())
    assert http.prom_scalar(fams, "rt_requests_total") == 3
    assert http.prom_scalar(fams, "rt_depth", agg="last") == 3.5
    series = dict()
    for labels, value in fams["rt_requests_total"]:
        series[(labels["route"], labels["code"])] = value
    assert series == {("a", "200"): 1.0, ("b", "500"): 2.0}
    # histogram family: bucket counts survive, quantile computable
    q95 = http.prom_histogram_quantile(fams, "rt_latency_seconds",
                                       0.95)
    assert q95 is not None and 0.01 < q95 <= 0.5
    # unknowns and garbage degrade, never raise
    assert http.prom_scalar(fams, "missing", default=-1) == -1
    assert http.parse_prometheus_text("garbage {{{\n# ok\n") == {}


def test_quantile_from_buckets():
    # 3 observations, one per finite bucket
    assert metrics.quantile_from_buckets(
        [1.0, 2.0, 4.0], [1, 1, 1, 0], 0.5) == pytest.approx(1.5)
    assert metrics.quantile_from_buckets(
        [1.0, 2.0, 4.0], [1, 1, 1, 0], 1.0) == pytest.approx(4.0)
    # overflow bucket: the observed max wins when known
    assert metrics.quantile_from_buckets(
        [1.0], [0, 3], 0.9, observed_max=7.5) == pytest.approx(7.5)
    assert metrics.quantile_from_buckets([1.0], [0, 0], 0.5) is None
    # Histogram.quantile end-to-end
    h = metrics.default_registry().histogram("q_seconds")
    for v in (0.02, 0.02, 0.3, 0.3):
        h.observe(v)
    q50 = h.quantile(0.5)
    assert 0.005 < q50 <= 0.1
    assert h.quantile(0.99) <= 0.5
    assert metrics.default_registry().histogram("empty_h") \
        .quantile(0.5) is None


def test_start_from_env_opt_out(monkeypatch):
    monkeypatch.setenv(http.TELEMETRY_ENV, "0")
    assert http.start_from_env("tw/1") is None
    monkeypatch.setenv(http.TELEMETRY_ENV, "1")
    srv = http.start_from_env("tw/1")
    try:
        assert srv is not None and srv.port > 0
        assert http.default_server() is srv
        code, _, _ = _get(srv.port, "/healthz")
        assert code == 200  # default provider reports RUNNING
    finally:
        http.stop_default()


class TestBoundedRequestHandler:
    """Hardening regression tests (docs/serving.md "Front door"):
    the telemetry/gateway HTTP plane is exposed to arbitrary
    clients, so a stalled, oversized, or malformed connection must
    cost one bounded handler thread, never a wedged server."""

    def test_stalled_connection_is_closed_on_timeout(self, server,
                                                     monkeypatch):
        import socket
        import time as _time

        monkeypatch.setattr(http.BoundedRequestHandler, "timeout",
                            0.3)
        srv, _ = server
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=5) as s:
            # half a request line, then silence: the per-connection
            # socket timeout must close it, not hold the thread for
            # the default 30s
            s.sendall(b"GET /metr")
            start = _time.monotonic()
            s.settimeout(5)
            assert s.recv(1024) == b""  # server-side close
            assert _time.monotonic() - start < 4.0
        # the server still answers fresh requests afterwards
        code, _, _ = _get(srv.port, "/healthz")
        assert code == 200

    def test_oversized_request_line_is_414(self, server):
        import socket

        srv, _ = server
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=5) as s:
            s.sendall(b"GET /" + b"a" * (
                http.MAX_REQUEST_LINE_BYTES + 64)
                + b" HTTP/1.1\r\n\r\n")
            s.settimeout(5)
            reply = s.recv(4096).decode("latin-1")
        assert " 414 " in reply.splitlines()[0]

    def test_oversized_headers_are_431(self, server):
        import socket

        srv, _ = server
        blob = b"X-Flood: " + b"z" * 4000 + b"\r\n"
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=5) as s:
            s.sendall(b"GET /healthz HTTP/1.1\r\n"
                      b"Host: x\r\n" + blob * 5 + b"\r\n")
            s.settimeout(5)
            reply = s.recv(4096).decode("latin-1")
        assert " 431 " in reply.splitlines()[0]

    def test_normal_requests_unaffected_by_bounds(self, server):
        srv, _ = server
        metrics.inc("bounded_demo_total")
        code, _, body = _get(srv.port, "/metrics")
        assert code == 200 and "bounded_demo_total" in body


def test_worker_publishes_telemetry_and_healthz_tracks_status():
    """The worker_base wiring: constructing a Worker starts the
    telemetry endpoints and publishes host:port under
    names.telemetry; /healthz mirrors the worker's published status
    and flips to 503 on preemption (the drain path)."""
    from realhf_tpu.base import name_resolve, names
    from realhf_tpu.system.worker_base import Worker

    w = Worker("texp", "t0", "tw/2")
    try:
        assert w.telemetry is not None
        addr = name_resolve.get(names.telemetry("texp", "t0", "tw/2"))
        assert addr.endswith(f":{w.telemetry.port}")
        code, _, body = _get(w.telemetry.port, "/healthz")
        doc = json.loads(body)
        assert code == 200 and doc["state"] == "READY"
        assert doc["boot_id"] == w.server.boot_id
        assert doc["heartbeat_age_secs"] is not None
        # preemption (the drain entry point) flips the endpoint
        w.notice_preemption(grace=30.0, reason="test")
        code, _, body = _get(w.telemetry.port, "/healthz")
        assert code == 503
        assert json.loads(body)["state"] == "PREEMPTED"
    finally:
        w.server.stop_heartbeat()
        if w.telemetry is not None:
            w.telemetry.stop()
