"""Slow acceptance e2e (ISSUE 13): a Prometheus-shaped scrape of a
live ``run_serve`` fleet. The launcher writes ``scrape_targets.json``
resolved from the ``names.telemetry`` registry (NOT the manifest's
dead per-host ports); an HTTP GET to EVERY listed target returns
valid Prometheus text -- ``serving_*_total`` counters on the
replicas, ``router_*`` series (including the new latency histogram)
on the router -- and a replica's ``/healthz`` flips from 200 to 503
the moment a drain starts.

Run directly: ``pytest -m slow tests/telemetry/test_scrape_e2e.py``.
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

TINY = dict(n_layers=2, n_kv_heads=2, n_q_heads=4, hidden_dim=32,
            intermediate_dim=64, vocab_size=97, apply_rotary=True,
            layer_norm_type="rms", mlp_type="llama",
            use_attention_bias=False, use_attn_proj_bias=False,
            use_mlp_bias=False, activation_function="silu")

WORKER_ENV = {
    "REALHF_TPU_BACKEND": "cpu",
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..")),
}


def _make_spec(exp, trial):
    from realhf_tpu.api.experiment import (
        ExperimentSpec,
        ModelSpec,
        ServingSpec,
    )
    return ExperimentSpec(
        experiment_name=exp, trial_name=trial,
        models={"default": ModelSpec(
            path=None, random_init_config=dict(TINY),
            optimizer=None, gradient_checkpointing=False, bf16=False)},
        mfcs=[], dataset=None, seed=1,
        serving=ServingSpec(
            model_role="default", n_servers=2, n_slots=2, chunk_size=2,
            max_prompt_len=64, max_queue_depth=16,
            eos_token_id=None, pad_token_id=0,
            drain_timeout_secs=20.0,
            fleet_router=True, lease_ttl_secs=6.0,
            router_dispatch_timeout_secs=60.0,
            router_response_timeout_secs=None,
            gconfig=dict(max_new_tokens=8, min_new_tokens=1,
                         greedy=True)))


def _get(address, path, timeout=15.0):
    try:
        with urllib.request.urlopen(f"http://{address}{path}",
                                    timeout=timeout) as r:
            return r.status, dict(r.headers), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


@pytest.mark.slow
def test_run_serve_fleet_scrape_and_drain_flip():
    from realhf_tpu.apps.main import run_serve
    from realhf_tpu.base import constants
    from realhf_tpu.obs import http as obs_http
    from realhf_tpu.serving.server import RolloutClient
    from realhf_tpu.system.worker_base import WorkerControlPanel

    exp, trial = "scrapee2e", "t0"
    spec = _make_spec(exp, trial)
    result = {}

    def _serve():
        try:
            # duration counts from AFTER bring-up: it only needs to
            # cover the traffic + scrape + drain checks below
            result["stats"] = run_serve(spec, env=dict(WORKER_ENV),
                                        duration=180.0, timeout=900.0)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            result["error"] = e

    t = threading.Thread(target=_serve, daemon=True)
    t.start()
    client = None
    try:
        # -- the launcher wrote registry-resolved scrape targets -----
        constants.set_experiment_trial_names(exp, trial)
        sd_path = os.path.join(constants.run_log_path(), "obs",
                               "scrape_targets.json")
        deadline = time.monotonic() + 300
        while not os.path.exists(sd_path):
            assert "error" not in result, result["error"]
            assert time.monotonic() < deadline, \
                f"scrape targets never written to {sd_path}"
            time.sleep(0.5)
        entries = json.load(open(sd_path))
        by_worker = {e["labels"]["worker"]: e for e in entries}
        assert set(by_worker) == {"gen_server/0", "gen_server/1",
                                  "router/0"}, entries
        for e in entries:
            assert len(e["targets"]) == 1
            assert re.match(r"^[\d.]+:\d+$", e["targets"][0]), e
            assert e["labels"]["experiment"] == exp

        # -- real traffic through the router -------------------------
        client = RolloutClient(experiment_name=exp, trial_name=trial,
                               server_name="router")
        rng = np.random.default_rng(0)
        rids = [client.submit(
            rng.integers(2, 97, size=6).astype(np.int32), ttl=170.0)
            for _ in range(4)]
        results = [client.result(r, timeout=170.0) for r in rids]
        assert all(r.ok and len(r.tokens) == 8 for r in results)

        # -- every listed target answers valid Prometheus text -------
        texts = {}
        for worker, entry in by_worker.items():
            code, headers, body = _get(entry["targets"][0],
                                       "/metrics")
            assert code == 200, (worker, code)
            assert headers["Content-Type"].startswith("text/plain")
            fams = obs_http.parse_prometheus_text(body)
            assert fams, (worker, body[:200])
            texts[worker] = (body, fams)
        router_fams = texts["router/0"][1]
        assert obs_http.prom_scalar(
            router_fams, "router_requests_total") >= 4
        # satellite: the latency histogram is scrapable and yields a
        # quantile (what a real Prometheus histogram_quantile sees)
        assert obs_http.prom_histogram_quantile(
            router_fams, "router_latency_seconds", 0.95) is not None
        gen_counters = set()
        for worker in ("gen_server/0", "gen_server/1"):
            for name in texts[worker][1]:
                m = re.match(r"^(serving_[a-z0-9_]+_total)$", name)
                if m:
                    gen_counters.add(m.group(1))
        assert gen_counters, {w: sorted(texts[w][1])
                              for w in texts}

        # -- /healthz flips state on drain ---------------------------
        g0 = by_worker["gen_server/0"]["targets"][0]
        code, _, body = _get(g0, "/healthz")
        doc = json.loads(body)
        assert code == 200 and doc["state"] == "RUNNING", doc
        assert doc["fencing_epoch"] is not None  # lease state surfaced
        panel = WorkerControlPanel(exp, trial)
        panel.connect(["gen_server/0"], timeout=60)
        panel.group_request("drain", worker_names=["gen_server/0"],
                            timeout=120)
        code, _, body = _get(g0, "/healthz")
        doc = json.loads(body)
        assert code == 503 and doc["state"] == "DRAINING", doc
    finally:
        if client is not None:
            client.close()
        t.join(timeout=600)
    assert not t.is_alive(), "run_serve did not finish"
    assert "error" not in result, result.get("error")
    stats = result["stats"]
    # the ZMQ stats path carries the new histogram quantiles too
    assert stats["router/0"]["latency_p50"] is not None
    assert stats["router/0"]["latency_p95"] is not None
