"""Telemetry suite fixtures: fresh process-default obs singletons per
test (tracer / metrics registry / flight recorder / telemetry HTTP
server) -- the telemetry plane is process-global by design, so state
must never leak between tests."""

import pytest

from realhf_tpu.obs import flight, http, metrics, tracing


@pytest.fixture(autouse=True)
def _fresh_obs_defaults():
    tracing.reset_default()
    metrics.reset_default()
    flight.reset_default()
    yield
    http.stop_default()
    tracing.reset_default()
    metrics.reset_default()
    flight.reset_default()
