"""Flight recorder: bounded ring semantics and the postmortem dump."""

import json

from realhf_tpu.obs import flight
from realhf_tpu.obs.flight import FlightRecorder


def test_ring_is_bounded_and_ordered():
    r = FlightRecorder("w", capacity=10)
    for i in range(25):
        r.record("request", seq=i)
    evs = r.events()
    assert len(r) == 10
    assert [e["seq"] for e in evs] == list(range(15, 25))
    assert all(e["kind"] == "request" and "ts" in e for e in evs)


def test_dump_writes_postmortem_json(tmp_path):
    r = FlightRecorder("model_worker/3", capacity=64)
    for i in range(12):
        r.record("request", handle="train_step", seq=i)
    r.record("fault", fault_kind="crash", fault_id="f0")
    path = str(tmp_path / "flight" / "w.flight.json")
    out = r.dump(reason="injected crash (f0)", path=path)
    assert out == path
    doc = json.load(open(path))
    assert doc["worker"] == "model_worker/3"
    assert doc["reason"] == "injected crash (f0)"
    assert doc["n_events"] == 13 and len(doc["events"]) == 13
    # the acceptance bar: a dump names the last >= 10 events
    assert doc["n_events"] >= 10
    assert doc["events"][-1]["kind"] == "fault"


def test_dump_failure_returns_none_never_raises(tmp_path):
    r = FlightRecorder("w")
    r.record("x")
    bad = str(tmp_path / "f")  # parent "f" created as a FILE below
    open(bad, "w").close()
    assert r.dump("r", path=bad + "/sub/x.json") is None


def test_module_default_configure_and_clear(tmp_path):
    flight.configure("gen_server/0")
    flight.record("preempted", grace=5.0)
    rec = flight.default_recorder()
    assert rec.name == "gen_server/0" and len(rec) == 1
    p = flight.dump("test", path=str(tmp_path / "d.json"))
    assert json.load(open(p))["worker"] == "gen_server/0"
    rec.clear()
    assert len(rec) == 0
