"""Metrics registry: the four metric types, label handling, Prometheus
text rendering, snapshots, and the JSONL sink."""

import json

import pytest

from realhf_tpu.obs import metrics
from realhf_tpu.obs.metrics import Accum, MetricsRegistry


# ----------------------------------------------------------------------
# Accum
# ----------------------------------------------------------------------
def test_accum_count_min_max_mean():
    a = Accum()
    for v in (3.0, -1.0, 10.0):
        a.add(v)
    assert a.as_dict() == dict(count=3, sum=12.0, min=-1.0, max=10.0,
                               mean=4.0)
    assert Accum().as_dict()["count"] == 0  # empty: all-zero, no inf


# ----------------------------------------------------------------------
# counters / gauges
# ----------------------------------------------------------------------
def test_counter_labels_and_values():
    r = MetricsRegistry()
    r.inc("requests_total", handle="train_step")
    r.inc("requests_total", 2, handle="train_step")
    r.inc("requests_total", handle="generate")
    c = r.counter("requests_total")
    assert c.value(handle="train_step") == 3
    assert c.value(handle="generate") == 1
    assert c.value(handle="missing") == 0


def test_gauge_set_and_inc():
    r = MetricsRegistry()
    r.set_gauge("queue_depth", 7, server="s0")
    r.set_gauge("queue_depth", 4, server="s0")  # last write wins
    g = r.gauge("queue_depth")
    g.inc(2, server="s0")
    assert g.value(server="s0") == 6


def test_metric_type_conflict_raises():
    r = MetricsRegistry()
    r.counter("x_total")
    with pytest.raises(TypeError):
        r.gauge("x_total")


# ----------------------------------------------------------------------
# summary / histogram
# ----------------------------------------------------------------------
def test_summary_accumulates_per_label_set():
    r = MetricsRegistry()
    for v in (0.1, 0.3):
        r.observe("exec_secs", v, mfc="actor_gen")
    r.observe("exec_secs", 5.0, mfc="actor_train")
    s = r.summary("exec_secs")
    a = s.accum(mfc="actor_gen")
    assert a.count == 2 and a.min == 0.1 and a.max == 0.3
    assert s.accum(mfc="missing").count == 0


def test_histogram_buckets_cumulative():
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = "\n".join(h.prometheus_lines())
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 3' in text
    assert 'lat_bucket{le="10"} 4' in text
    assert 'lat_bucket{le="+Inf"} 5' in text
    assert "lat_count 5" in text
    assert "lat_sum 56.05" in text


# ----------------------------------------------------------------------
# Prometheus text exposition
# ----------------------------------------------------------------------
def test_prometheus_text_format():
    r = MetricsRegistry("w0")
    r.counter("reqs_total", help="requests").inc(3, handle="save")
    r.set_gauge("depth", 2)
    text = r.to_prometheus()
    assert "# HELP reqs_total requests" in text
    assert "# TYPE reqs_total counter" in text
    assert 'reqs_total{handle="save"} 3' in text
    assert "# TYPE depth gauge" in text
    assert "depth 2" in text.splitlines()
    assert text.endswith("\n")


def test_prometheus_summary_lines():
    r = MetricsRegistry()
    r.observe("secs", 1.0, role="actor")
    r.observe("secs", 3.0, role="actor")
    text = r.to_prometheus()
    assert 'secs_count{role="actor"} 2' in text
    assert 'secs_sum{role="actor"} 4' in text
    assert 'secs_min{role="actor"} 1' in text
    assert 'secs_max{role="actor"} 3' in text


# ----------------------------------------------------------------------
# snapshot + JSONL sink
# ----------------------------------------------------------------------
def test_snapshot_structure():
    r = MetricsRegistry()
    r.inc("a_total")
    r.observe("b_secs", 2.0, mfc="x")
    snap = r.snapshot()
    assert snap["a_total"]["type"] == "counter"
    assert snap["a_total"]["values"][""] == 1
    key = json.dumps({"mfc": "x"})
    assert snap["b_secs"]["values"][key]["mean"] == 2.0


def test_event_and_periodic_jsonl(tmp_path):
    path = str(tmp_path / "m" / "w.metrics.jsonl")
    r = MetricsRegistry("w0")
    r.attach_jsonl(path, interval=10.0)
    rec = r.event("mfc_stats", mfc="actor_gen", batch_id=1,
                  stats={"loss": 0.5})
    assert rec["event"] == "mfc_stats" and rec["process"] == "w0"
    r.inc("steps_total")
    r.maybe_flush(now=0.0)      # interval not elapsed: no snapshot
    r._last_snapshot = -100.0
    r.maybe_flush(now=0.0)      # elapsed: snapshot line lands
    lines = [json.loads(x) for x in open(path)]
    kinds = [x["kind"] for x in lines]
    assert kinds == ["event", "snapshot"]
    assert lines[1]["metrics"]["steps_total"]["values"][""] == 1


def test_event_without_sink_still_returns_record():
    r = MetricsRegistry("p")
    rec = r.event("elastic_degrade", node="actor_train")
    assert rec["node"] == "actor_train"


def test_module_default_convenience_and_reset():
    metrics.inc("x_total", 2)
    metrics.observe("y_secs", 1.5)
    metrics.set_gauge("z", 9)
    text = metrics.to_prometheus()
    assert "x_total 2" in text and "z 9" in text
    metrics.reset_default()
    assert metrics.to_prometheus() == ""
