"""Cross-process trace-context propagation (ISSUE 5 satellite): a span
opened in the master is the ancestor of spans recorded in a model
worker over ``request_reply_stream``, and of serving spans over the
ZMQ ROUTER/DEALER path. Processes are emulated with separate Tracer
instances (pid derives from the process NAME, so the merged Chrome
trace keeps one lane per 'process' even in-process)."""

import json

import numpy as np
import pytest

from realhf_tpu.obs import metrics, tracing
from realhf_tpu.obs.tracing import Tracer


# ----------------------------------------------------------------------
# request_reply_stream: master -> model worker
# ----------------------------------------------------------------------
@pytest.fixture
def stream_pair():
    from realhf_tpu.system.request_reply_stream import (
        NameResolvingReplyServer,
        NameResolvingRequestClient,
    )

    exp, trial = "obsprop", "t0"
    master = NameResolvingRequestClient(exp, trial)
    worker = NameResolvingReplyServer(exp, trial, "mw/0")
    # SUB connection is asynchronous: ping until the subscription is
    # live, then drain the queued pings.
    for _ in range(200):
        master.request(["mw/0"], "ping")
        try:
            worker.poll(timeout=0.05)
            break
        except TimeoutError:
            continue
    else:
        pytest.fail("subscription never became live")
    try:
        while True:
            worker.poll(timeout=0.2)
    except TimeoutError:
        pass
    yield master, worker
    worker.close()
    master.close()


def test_master_span_is_ancestor_over_request_reply(stream_pair):
    master, worker = stream_pair
    # master process: the default tracer (what stream.request injects)
    tracing.configure(process_name="master", enabled=True)
    worker_tracer = Tracer("model_worker/0", enabled=True)

    with tracing.span("step", batch_id=7) as step:
        with tracing.span("dispatch:actor_gen") as dispatch:
            master.request(["mw/0"], "generate",
                           datas=[{"node": "actor_gen"}])

    req = worker.poll(timeout=5)
    assert req.trace == dispatch.context.to_dict()
    # worker side: parent the MFC span on the extracted context, the
    # compute span nests inside it (model_worker._handle_request)
    ctx = tracing.extract(req.trace)
    with worker_tracer.span("mfc:actor_gen", parent=ctx) as mfc:
        with worker_tracer.span("compute:actor_gen") as comp:
            pass

    assert mfc.trace_id == step.trace_id == comp.trace_id
    assert mfc.parent_id == dispatch.span_id
    assert dispatch.parent_id == step.span_id
    assert comp.parent_id == mfc.span_id


def test_explicit_trace_ctx_overrides_injection(stream_pair):
    master, worker = stream_pair
    tracing.configure(process_name="master", enabled=True)
    ctx = {"trace_id": "t" * 16, "span_id": "s" * 16}
    with tracing.span("unrelated"):
        master.request(["mw/0"], "save", trace_ctx=ctx)
    req = worker.poll(timeout=5)
    assert req.trace == ctx


def test_no_trace_rides_when_tracing_off(stream_pair):
    master, worker = stream_pair
    assert not tracing.enabled()
    master.request(["mw/0"], "ping")
    assert worker.poll(timeout=5).trace is None


def test_merged_trace_has_one_lane_per_process(stream_pair, tmp_path):
    """The acceptance shape in tier-1 form: master + worker tracers
    flush to per-process files; the merged Chrome trace shows >= 2
    pids with the worker span parented under the master's."""
    master, worker = stream_pair
    d = str(tmp_path / "trace")
    tracing.configure(process_name="master", enabled=True,
                      path=f"{d}/master.trace.jsonl")
    worker_tracer = Tracer("model_worker/0", enabled=True,
                           path=f"{d}/model_worker-0.trace.jsonl")

    with tracing.span("step", batch_id=0):
        with tracing.span("dispatch:actor_train"):
            master.request(["mw/0"], "train_step",
                           datas=[{"node": "actor_train"}])
    req = worker.poll(timeout=5)
    with worker_tracer.span("mfc:actor_train",
                            parent=tracing.extract(req.trace)):
        with worker_tracer.span("realloc"):
            pass
        with worker_tracer.span("data_fetch"):
            pass
        with worker_tracer.span("compute:actor_train"):
            pass
    tracing.flush()
    worker_tracer.flush()

    merged = tracing.merge_traces(directory=d)
    events = json.load(open(merged))["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    by_name = {e["name"]: e for e in spans}
    assert len({e["pid"] for e in spans}) == 2
    assert {"step", "dispatch:actor_train", "mfc:actor_train",
            "realloc", "data_fetch",
            "compute:actor_train"} <= set(by_name)
    # one trace id across both processes; worker nests under dispatch
    assert len({e["args"]["trace_id"] for e in spans}) == 1
    assert (by_name["mfc:actor_train"]["args"]["parent_id"]
            == by_name["dispatch:actor_train"]["args"]["span_id"])
    assert (by_name["compute:actor_train"]["args"]["parent_id"]
            == by_name["mfc:actor_train"]["args"]["span_id"])


# ----------------------------------------------------------------------
# serving ZMQ ROUTER/DEALER path
# ----------------------------------------------------------------------
class FakeBackend:
    """prompt[0] = tokens the sequence needs; each decode_chunk
    advances every live slot by up to ``chunk``."""

    def __init__(self, n_slots=2, chunk=4):
        self.n_slots = n_slots
        self.chunk = chunk
        self.params = "v0"
        self._slots = {}

    def free_slots(self):
        return [s for s in range(self.n_slots) if s not in self._slots]

    def fill_slot(self, slot, int_id, prompt):
        self._slots[slot] = [int_id, int(prompt[0]), 0]

    def decode_chunk(self, key):
        for v in self._slots.values():
            v[2] = min(v[1], v[2] + self.chunk)

    def harvest(self):
        from realhf_tpu.engine.inflight import FinishedSequence
        out = []
        for slot, (i, need, got) in list(self._slots.items()):
            if got >= need:
                out.append(FinishedSequence(
                    request_id=i, tokens=np.arange(got),
                    logprobs=np.zeros(got), no_eos=True))
                del self._slots[slot]
        return out

    def release_slot(self, slot):
        self._slots.pop(slot, None)

    def swap_params(self, p):
        self.params = p

    def snapshot_slot(self, slot):
        _, _, got = self._slots[slot]
        return np.arange(got), np.zeros(got)

    @property
    def n_live(self):
        return len(self._slots)


def test_client_span_is_ancestor_over_serving_zmq():
    from realhf_tpu.serving.server import (
        TERMINAL_KINDS,
        RolloutClient,
        RolloutServer,
    )

    tracing.configure(process_name="serve_test", enabled=True)
    server = RolloutServer(FakeBackend(), server_name="obs/0")
    client = RolloutClient(server.address)
    try:
        with tracing.span("client:rollout") as root:
            rid = client.submit(np.array([6, 1, 2], np.int32))
        for _ in range(200):
            server.serve_step(poll_timeout=0.02)
            try:
                kind, _ = client.next_event(rid, timeout=0.02)
            except TimeoutError:
                continue
            if kind in TERMINAL_KINDS:
                assert kind == "done"
                break
        else:
            pytest.fail("request never finished")

        spans = {s.name: s for s in tracing.default_tracer().drain()}
        req_span = spans["serve:request"]
        assert req_span.trace_id == root.trace_id
        assert req_span.parent_id == root.span_id
        assert req_span.attributes["rid"] == rid
        assert req_span.attributes["outcome"] == "done"
        # decode chunks traced too (one span covers all live slots)
        assert "serve:decode_chunk" in spans
    finally:
        client.close()
        server.close()


def test_serving_counters_reach_prometheus_export():
    """Acceptance: the Prometheus text export includes serving
    queue-depth and scheduler decode counters."""
    from realhf_tpu.serving.server import (
        TERMINAL_KINDS,
        RolloutClient,
        RolloutServer,
    )

    server = RolloutServer(FakeBackend(), server_name="obs/1")
    client = RolloutClient(server.address)
    try:
        rid = client.submit(np.array([6, 1, 2], np.int32))
        for _ in range(200):
            server.serve_step(poll_timeout=0.02)
            try:
                kind, _ = client.next_event(rid, timeout=0.02)
            except TimeoutError:
                continue
            if kind in TERMINAL_KINDS:
                break
        text = metrics.to_prometheus()
        assert 'serving_queue_depth{server="obs/1"}' in text
        assert "serving_decode_chunks_total" in text
        assert "serving_decode_steps_total" in text
        assert "serving_prefills_total" in text
        assert "serving_finished_total" in text
        # the scheduler's own dict and the registry mirror agree
        c = metrics.default_registry().counter(
            "serving_decode_chunks_total")
        assert c.value() == server.scheduler.stats["decode_chunks"]
    finally:
        client.close()
        server.close()
