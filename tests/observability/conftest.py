"""Observability suite fixtures: every test gets FRESH process-default
tracer / metrics registry / flight recorder singletons, so span
buffers and counters never leak between tests (the obs layer is
process-global by design)."""

import pytest

from realhf_tpu.obs import flight, metrics, tracing


@pytest.fixture(autouse=True)
def _fresh_obs_defaults():
    tracing.reset_default()
    metrics.reset_default()
    flight.reset_default()
    yield
    tracing.reset_default()
    metrics.reset_default()
    flight.reset_default()
