"""base/stats.py after absorption by the obs layer: per-key
count/min/max/mean export and no value loss under a concurrent
clearing export (ISSUE 5 satellite)."""

import threading

from realhf_tpu.base.stats import StatsTracker


def test_export_reports_full_accumulation():
    t = StatsTracker()
    t.record(aux_loss=1.0)
    t.record(aux_loss=3.0, z_loss=0.5)
    out = t.export()
    assert out["aux_loss"] == dict(count=2, sum=4.0, min=1.0, max=3.0,
                                   mean=2.0)
    assert out["z_loss"]["count"] == 1
    assert t.export() == {}  # cleared


def test_export_no_clear_keeps_values():
    t = StatsTracker()
    t.record(a=2.0)
    snap = t.export(clear=False)
    assert snap["a"]["mean"] == 2.0
    snap["a"]["mean"] = 999  # a COPY: mutating it must not leak back
    assert t.export()["a"]["mean"] == 2.0


def test_concurrent_records_never_dropped_by_clearing_export():
    """Every recorded value lands in exactly one export: a record
    racing the clear either makes this export or the next one."""
    t = StatsTracker()
    total = 5000
    done = threading.Event()

    def producer():
        for _ in range(total):
            t.record(v=1.0)
        done.set()

    counted = 0
    th = threading.Thread(target=producer)
    th.start()
    while not done.is_set():
        counted += t.export().get("v", {}).get("count", 0)
    th.join()
    counted += t.export().get("v", {}).get("count", 0)
    assert counted == total
