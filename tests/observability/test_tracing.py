"""Span tracer: nesting, ids, noop-off mode, thread buffers, Chrome
export, file flush + multi-process merge."""

import json
import threading

from realhf_tpu.obs import tracing
from realhf_tpu.obs.tracing import SpanContext, Tracer


# ----------------------------------------------------------------------
# off-by-default noop
# ----------------------------------------------------------------------
def test_disabled_tracer_is_noop():
    t = Tracer("p")
    assert not t.enabled
    with t.span("work") as sp:
        sp.set_attribute("k", 1)  # must not raise
        assert t.inject() is None
    assert t.start_span("x") is tracing.NOOP_SPAN
    assert t.drain() == []


def test_module_default_off_by_default():
    with tracing.span("anything"):
        assert tracing.inject() is None
    assert tracing.default_tracer().drain() == []


# ----------------------------------------------------------------------
# nesting + ids
# ----------------------------------------------------------------------
def test_nested_spans_share_trace_and_parent():
    t = Tracer("p", enabled=True)
    with t.span("outer") as outer:
        with t.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert t.current_span() is inner
        assert t.current_span() is outer
    assert t.current_span() is None
    names = {s.name: s for s in t.drain()}
    assert set(names) == {"outer", "inner"}
    assert names["inner"].end >= names["inner"].start


def test_start_span_explicit_lifetime_parents_to_current():
    t = Tracer("p", enabled=True)
    with t.span("request") as req:
        long_lived = t.start_span("background", rid="r1")
    # NOT on the stack: finishing the scoped span leaves it open
    assert {s.name for s in t.drain()} == {"request"}
    assert long_lived.parent_id == req.span_id
    long_lived.finish()
    assert [s.name for s in t.drain()] == ["background"]
    assert long_lived.attributes["rid"] == "r1"


def test_exception_recorded_as_error_attribute():
    t = Tracer("p", enabled=True)
    try:
        with t.span("boom"):
            raise ValueError("x")
    except ValueError:
        pass
    (sp,) = t.drain()
    assert "ValueError" in sp.attributes["error"]


# ----------------------------------------------------------------------
# context propagation carrier
# ----------------------------------------------------------------------
def test_inject_extract_roundtrip():
    t = Tracer("p", enabled=True)
    with t.span("root"):
        carrier = t.inject()
    ctx = Tracer.extract(carrier)
    assert isinstance(ctx, SpanContext)
    assert carrier == ctx.to_dict()
    assert Tracer.extract(None) is None
    assert Tracer.extract({"trace_id": "x"}) is None  # malformed


def test_extracted_context_parents_remote_span():
    master = Tracer("master", enabled=True)
    worker = Tracer("model_worker/0", enabled=True)
    with master.span("dispatch") as d:
        carrier = master.inject()
    with worker.span("mfc", parent=Tracer.extract(carrier)) as w:
        assert w.trace_id == d.trace_id
        assert w.parent_id == d.span_id


# ----------------------------------------------------------------------
# per-thread buffers
# ----------------------------------------------------------------------
def test_spans_from_many_threads_all_drain():
    t = Tracer("p", enabled=True)
    n_threads, per = 8, 50

    def work():
        for i in range(per):
            with t.span(f"s{i}"):
                pass

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(t.drain()) == n_threads * per
    assert t.drain() == []  # drained


def test_drain_while_recording_never_loses_spans():
    t = Tracer("p", enabled=True)
    total = 2000
    got = []
    done = threading.Event()

    def producer():
        for _ in range(total):
            with t.span("s"):
                pass
        done.set()

    th = threading.Thread(target=producer)
    th.start()
    while not done.is_set():
        got.extend(t.drain())
    th.join()
    got.extend(t.drain())
    assert len(got) == total


# ----------------------------------------------------------------------
# chrome export + merge
# ----------------------------------------------------------------------
def test_chrome_events_shape_and_stable_pid():
    t = Tracer("model_worker/0", enabled=True)
    with t.span("step", batch_id=3):
        pass
    events = t.to_events(t.drain())
    meta, ev = events[0], events[1]
    assert meta["ph"] == "M" and meta["args"]["name"] == "model_worker/0"
    assert ev["ph"] == "X" and ev["name"] == "step"
    assert ev["dur"] >= 0 and ev["args"]["batch_id"] == 3
    assert ev["pid"] == meta["pid"]
    # pid derives from the NAME: same-named tracers share a lane
    assert Tracer("model_worker/0").pid == t.pid
    assert Tracer("model_worker/1").pid != t.pid


def test_flush_to_file_and_merge(tmp_path):
    d = str(tmp_path / "trace")
    tracers = [
        Tracer("master", enabled=True, path=f"{d}/master.trace.jsonl"),
        Tracer("model_worker/0", enabled=True,
               path=f"{d}/worker0.trace.jsonl"),
    ]
    for t in tracers:
        with t.span("step"):
            with t.span("compute"):
                pass
        t.flush()
        t.flush()  # second flush with nothing buffered: no-op
    merged = tracing.merge_traces(directory=d)
    assert merged.endswith("merged_trace.json")
    doc = json.load(open(merged))
    events = doc["traceEvents"]
    pids = {e["pid"] for e in events if e["ph"] == "X"}
    assert len(pids) == 2  # one lane per process
    assert sum(1 for e in events if e["ph"] == "X") == 4
    names = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert names == {"master", "model_worker/0"}


def test_merge_skips_corrupt_lines(tmp_path):
    d = tmp_path / "trace"
    d.mkdir()
    good = Tracer("ok", enabled=True,
                  path=str(d / "ok.trace.jsonl"))
    with good.span("s"):
        pass
    good.flush()
    # a worker killed mid-write leaves a torn line
    (d / "dead.trace.jsonl").write_text('{"name": "torn', )
    merged = tracing.merge_traces(directory=str(d))
    events = json.load(open(merged))["traceEvents"]
    assert any(e.get("name") == "s" for e in events)


def test_merge_empty_dir_returns_none(tmp_path):
    assert tracing.merge_traces(directory=str(tmp_path)) is None
    assert tracing.merge_traces(
        directory=str(tmp_path / "missing")) is None


# ----------------------------------------------------------------------
# env switch
# ----------------------------------------------------------------------
def test_trace_env_enabled():
    assert not tracing.trace_env_enabled(env={})
    assert not tracing.trace_env_enabled(env={"REALHF_TPU_TRACE": "0"})
    assert not tracing.trace_env_enabled(env={"REALHF_TPU_TRACE": ""})
    assert tracing.trace_env_enabled(env={"REALHF_TPU_TRACE": "1"})


def test_configure_from_env_labels_and_enables(tmp_path, monkeypatch):
    import realhf_tpu.base.constants as constants
    from realhf_tpu import obs
    monkeypatch.setenv("REALHF_TPU_TRACE", "1")
    constants.set_experiment_trial_names("obst", "t0")
    obs.configure_from_env("model_worker/0", experiment="obst",
                           trial="t0")
    t = tracing.default_tracer()
    assert t.enabled
    assert t.process_name == "model_worker/0"
    assert t.path.endswith("model_worker-0.trace.jsonl")
