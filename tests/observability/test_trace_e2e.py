"""End-to-end observability over real OS worker processes (ISSUE 5
acceptance): ``REALHF_TPU_TRACE=1`` yields ONE merged Chrome trace
with one lane per process and cross-process span ancestry, and a
crashing worker leaves a flight-recorder dump naming its last events.

The dummy-fleet test is tier-1 (seconds). The full PPO trial trace is
``slow``-marked like the other whole-trial e2es (run directly:
``pytest -m slow tests/observability/test_trace_e2e.py``)."""

import json
import multiprocessing as mp
import os

import numpy as np
import pytest

# tests/system/tiny_model.py's canonical tiny llama config, inlined so
# this suite stays importable on its own sys.path
TINY = dict(n_layers=2, n_kv_heads=2, n_q_heads=4, hidden_dim=32,
            intermediate_dim=64, vocab_size=1100, apply_rotary=True,
            layer_norm_type="rms", mlp_type="llama",
            use_attention_bias=False, use_attn_proj_bias=False,
            use_mlp_bias=False, activation_function="silu")

WORKER_ENV = {
    "REALHF_TPU_BACKEND": "cpu",
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PYTHONPATH": "/root/repo",
}


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def _traced_worker_proc(record_root, root_dir, exp, trial, widx):
    """A worker_base.Worker that exercises the REAL obs wiring: the
    base class configures tracing from REALHF_TPU_TRACE, the poll loop
    flushes span buffers, and the ERROR exit path dumps the flight
    ring."""
    os.environ["REALHF_TPU_NAME_RESOLVE"] = "nfs"
    os.environ["REALHF_TPU_HEARTBEAT_INTERVAL"] = "0.2"
    os.environ["REALHF_TPU_ROOT"] = root_dir
    os.environ["REALHF_TPU_TRACE"] = "1"
    import realhf_tpu.base.constants as constants
    constants.ROOT_DIR = root_dir  # env read happens at import time
    # real workers do this in _configure; the default flight-dump path
    # resolves through the run constants
    constants.set_experiment_trial_names(exp, trial)
    from realhf_tpu.base import name_resolve
    name_resolve.reconfigure("nfs", record_root=record_root)
    from realhf_tpu.obs import flight, tracing
    from realhf_tpu.system.request_reply_stream import (
        NameResolvingReplyServer,
    )
    from realhf_tpu.system.worker_base import PollResult, Worker

    name = f"mw/{widx}"

    class TracedWorker(Worker):

        def _configure(self, config):
            self.stream = NameResolvingReplyServer(exp, trial, name)
            return "ok"

        def _poll(self):
            try:
                req = self.stream.poll(timeout=0.05)
            except TimeoutError:
                return PollResult(0, 0)
            flight.record("request", handle=req.handle_name)
            if req.handle_name == "explode":
                raise RuntimeError("injected crash")
            with tracing.span(f"mfc:{req.data}",
                              parent=tracing.extract(req.trace),
                              worker=name):
                with tracing.span(f"compute:{req.data}"):
                    pass
            self.stream.respond(req, data="ok")
            flight.record("reply", handle=req.handle_name)
            return PollResult(1, 1)

    TracedWorker(exp, trial, name).run()


def test_merged_trace_and_crash_dump_across_processes(
        tmp_path, monkeypatch):
    """Two real worker processes + the master: spans opened in the
    master are ancestors of worker spans in ONE merged Chrome trace
    with three process lanes; a crashing worker's ERROR exit leaves a
    flight dump naming its recent events."""
    import realhf_tpu.base.constants as constants
    from realhf_tpu.base import name_resolve
    from realhf_tpu.obs import tracing
    from realhf_tpu.system.request_reply_stream import (
        NameResolvingRequestClient,
    )
    from realhf_tpu.system.worker_base import (
        WorkerControlPanel,
        WorkerServerStatus,
    )

    exp, trial = "obse2e", "t0"
    record_root = str(tmp_path / "nr")
    root_dir = constants.ROOT_DIR  # conftest points this at tmp
    monkeypatch.setenv("REALHF_TPU_TRACE", "1")
    tracing.reset_default()
    constants.set_experiment_trial_names(exp, trial)

    ctx = mp.get_context("spawn")
    procs = [ctx.Process(
        target=_traced_worker_proc,
        args=(record_root, root_dir, exp, trial, i), daemon=True)
        for i in range(2)]
    for p in procs:
        p.start()
    try:
        name_resolve.reconfigure("nfs", record_root=record_root)
        master = NameResolvingRequestClient(exp, trial)
        panel = WorkerControlPanel(exp, trial)
        workers = ["mw/0", "mw/1"]
        panel.connect(workers, timeout=60)
        panel.group_request("configure", kwargs={"config": {}})
        panel.group_request("start")
        master.wait_subscribers(workers, timeout=30)

        tracing.configure(
            process_name="master", enabled=True,
            path=tracing.trace_file_path("master", exp, trial))
        with tracing.span("step", batch_id=0):
            for i, mfc in enumerate(("actor_gen", "actor_train")):
                with tracing.span(f"dispatch:{mfc}"):
                    rid = master.request([f"mw/{i}"], "compute",
                                         datas=[mfc])[0]
                    master.gather_replies([rid], timeout=30)
        tracing.flush()

        # the events the flight dump must name (>= 10)
        for _ in range(5):
            rid = master.request(["mw/0"], "compute",
                                 datas=["filler"])[0]
            master.gather_replies([rid], timeout=30)
        master.request(["mw/0"], "explode")
        procs[0].join(timeout=30)
        assert panel.get_worker_status("mw/0") == \
            WorkerServerStatus.ERROR
        panel.group_request("exit", worker_names=["mw/1"])
        procs[1].join(timeout=30)
        master.close()
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)

    merged = tracing.merge_traces(experiment=exp, trial=trial)
    assert merged is not None
    spans = [e for e in json.load(open(merged))["traceEvents"]
             if e["ph"] == "X"]
    by_name = {e["name"]: e for e in spans}
    assert len({e["pid"] for e in spans}) == 3  # master + 2 workers
    assert by_name["mfc:actor_gen"]["pid"] != by_name["step"]["pid"]
    # cross-process ancestry: worker spans nest under the master's
    for mfc in ("actor_gen", "actor_train"):
        assert (by_name[f"mfc:{mfc}"]["args"]["parent_id"]
                == by_name[f"dispatch:{mfc}"]["args"]["span_id"])
        assert (by_name[f"compute:{mfc}"]["args"]["trace_id"]
                == by_name["step"]["args"]["trace_id"])

    from realhf_tpu.obs import flight
    dump = flight.dump_path("mw/0", exp, trial)
    assert os.path.exists(dump)
    doc = json.load(open(dump))
    assert doc["worker"] == "mw/0"
    assert doc["n_events"] >= 10
    assert "injected crash" in doc["reason"]
    assert doc["events"][-1]["kind"] == "request"
    assert doc["events"][-1]["handle"] == "explode"


@pytest.mark.slow
def test_quickstart_ppo_trace_e2e(tmp_path, monkeypatch):
    """The full acceptance run: the quickstart PPO example with
    ``REALHF_TPU_TRACE=1`` produces a single merged Chrome trace with
    >= 2 processes in which per-MFC compute, data-transfer, and
    realloc spans nest under the step span; an injected ``crash``
    fault leaves a flight-recorder dump naming the last >= 10
    events."""
    import realhf_tpu.base.constants as constants
    from realhf_tpu.api.experiment import (
        FaultToleranceConfig,
        MFCAllocation,
    )
    from realhf_tpu.apps.main import main_start
    from realhf_tpu.base.testing import IntegerTokenizer
    from realhf_tpu.engine.optim import OptimizerConfig
    from realhf_tpu.experiments.common import apply_overrides
    from realhf_tpu.experiments.ppo_exp import PPOConfig
    from realhf_tpu.obs import flight, tracing
    from realhf_tpu.parallel.mesh import ParallelismConfig

    rng = np.random.default_rng(1)
    prompt_data = tmp_path / "prompts.jsonl"
    _write_jsonl(prompt_data, [
        {"id": i,
         "prompt": " ".join(f"w{int(x)}"
                            for x in rng.integers(0, 50, 4))}
        for i in range(32)])

    monkeypatch.setenv("REALHF_TPU_TRACE", "1")
    cfg = PPOConfig(experiment_name="obsppo", trial_name="t0",
                    total_train_epochs=1, benchmark_steps=2,
                    recover_mode="auto")
    apply_overrides(cfg, {
        "dataset.path": str(prompt_data),
        "dataset.train_bs_n_seqs": "8",
        "dataset.max_seqlen": "16",
        "ppo.max_new_tokens": "8",
        "ppo.min_new_tokens": "1",
        "ppo.top_k": "16",
        "ppo.ppo_n_minibatches": "2",
    })
    spec = cfg.build()
    for _role, mspec in spec.models.items():
        mspec.path = None
        mspec.random_init_config = dict(TINY)
        mspec.bf16 = False
        mspec.parallel = ParallelismConfig(data_parallel_size=2)
        if mspec.optimizer is not None:
            mspec.optimizer = OptimizerConfig(
                lr=1e-3, warmup_steps_proportion=0.0,
                lr_scheduler_type="constant")
    spec.tokenizer = IntegerTokenizer()
    spec.n_model_workers = 2
    spec.worker_assignment = {"actor": 0, "critic": 0, "ref": 0,
                              "reward": 0}
    # actor_gen on worker 1: forces cross-group realloc spans AND a
    # second process lane in the merged trace
    spec.allocations = dict(
        spec.allocations,
        actor_gen=MFCAllocation(
            ParallelismConfig(data_parallel_size=2), workers=[1]))
    spec.ft = FaultToleranceConfig(
        heartbeat_interval=0.5, heartbeat_timeout=30.0,
        gather_timeout_secs=600.0)

    state = tmp_path / "faults_state"
    env = dict(
        WORKER_ENV,
        REALHF_TPU_TRACE="1",
        REALHF_TPU_FAULTS="crash:model_worker/0:train_step:2",
        REALHF_TPU_FAULTS_STATE=str(state))
    out = main_start(spec, recover_mode="auto", recover_retries=2,
                     env=env, timeout=1800)
    assert out["complete"]
    assert "crash:model_worker/0:train_step:2" in state.read_text()

    # --- single merged Chrome trace, >= 2 processes ------------------
    constants.set_experiment_trial_names("obsppo", "t0")
    merged = os.path.join(tracing.trace_dir("obsppo", "t0"),
                          tracing.MERGED_TRACE_NAME)
    assert os.path.exists(merged)
    spans = [e for e in json.load(open(merged))["traceEvents"]
             if e["ph"] == "X"]
    names = {e["name"] for e in spans}
    assert len({e["pid"] for e in spans}) >= 2
    step_ids = {e["args"]["span_id"] for e in spans
                if e["name"] == "step"}
    assert step_ids
    # per-MFC compute, data-transfer, and realloc spans present...
    assert "compute:actor_gen" in names
    assert "compute:actor_train" in names
    assert "data_fetch" in names
    assert "realloc" in names  # cross-group actor_gen param sync
    # ...and nested under the step span: walk parents to a step root
    by_id = {e["args"]["span_id"]: e for e in spans}

    def has_step_ancestor(ev):
        seen = set()
        while ev is not None:
            pid = ev["args"].get("parent_id")
            if pid in step_ids:
                return True
            if pid is None or pid in seen:
                return False
            seen.add(pid)
            ev = by_id.get(pid)
        return False

    for nm in ("compute:actor_gen", "compute:actor_train",
               "data_fetch", "realloc"):
        assert any(has_step_ancestor(e) for e in spans
                   if e["name"] == nm), f"{nm} not nested under a step"

    # --- flight-recorder dump from the injected crash ----------------
    dump = flight.dump_path("model_worker/0", "obsppo", "t0")
    assert os.path.exists(dump)
    doc = json.load(open(dump))
    assert doc["n_events"] >= 10
    kinds = {e["kind"] for e in doc["events"]}
    assert "fault" in kinds and "request" in kinds

    # --- trace analytics on the real merged trace (ISSUE 13) ---------
    # the analyzer reconstructs the steps, the attribution components
    # sum to each step's wall, and a critical-path MFC is named
    from realhf_tpu.obs import analyze
    report = analyze.analyze_path(merged)
    assert report["n_steps"] >= 2
    for step in report["steps"]:
        assert sum(step["attribution"].values()) == pytest.approx(
            step["wall_secs"], abs=1e-6)
        assert step["attribution"]["compute"] > 0
    assert report["bottleneck_mfc"] is not None
    assert 0 < report["goodput"] <= 1.0
    assert report["stragglers"], report
    # the same report renders as the teardown one-liner
    assert analyze.one_line_summary(report).startswith(
        "trace report: ")
