"""Host-level failure domains: worker host publishing, watchdog
HOST_LOST aggregation (one event, not N worker losses), host-coalesced
exclusion backoff, buffer output invalidation, and the log-only
autoscale GrowAdvisor."""

import numpy as np
import pytest

from realhf_tpu.base import cluster, name_resolve, names
from realhf_tpu.obs import flight
from realhf_tpu.system.watchdog import (
    ALIVE,
    LOST,
    ExclusionBook,
    Watchdog,
)

EXP, TRIAL = "poddom", "t0"


def _beat(worker, ts):
    name_resolve.add(names.worker_heartbeat(EXP, TRIAL, worker),
                     f"{ts:.3f}", replace=True)


HOSTS = {"w/0": "host-A", "w/1": "host-A",
         "w/2": "host-B", "w/3": "host-B"}


def _dog(now, host_window=None, **kw):
    return Watchdog(EXP, TRIAL, list(HOSTS), timeout=10.0, grace=5.0,
                    poll_interval=0.0, clock=lambda: now[0],
                    host_of=HOSTS.get, host_window=host_window, **kw)


def _kinds():
    return [e["kind"] for e in flight.default_recorder().events()]


def test_whole_host_loss_is_one_attribution():
    flight.reset_default()
    now = [100.0]
    seen = []
    dog = _dog(now, on_host_lost=lambda h, ws: seen.append((h, ws)))
    for w in HOSTS:
        _beat(w, now[0])
    assert set(dog.check().values()) == {ALIVE}

    # host-A's workers both go silent; host-B keeps beating
    now[0] = 130.0
    for w in ("w/2", "w/3"):
        _beat(w, now[0])
    verdicts = dog.check()
    assert verdicts["w/0"] == verdicts["w/1"] == LOST
    # loss REPORTING is immediate (the master must requeue now) ...
    assert dog.lost_workers() == ["w/0", "w/1"]
    # ... but the attribution is ONE host event, zero worker events
    assert dog.lost_hosts() == ["host-A"]
    assert _kinds() == ["host_lost"]
    ev = flight.default_recorder().events()[0]
    assert ev["host"] == "host-A" and ev["workers"] == ["w/0", "w/1"]
    assert seen == [("host-A", ["w/0", "w/1"])]
    log = dog.host_lost_events()
    assert len(log) == 1 and log[0]["host"] == "host-A"
    # repeated checks do not re-emit
    dog.check()
    assert _kinds() == ["host_lost"]


def test_partial_host_loss_emits_individual_after_window():
    flight.reset_default()
    now = [100.0]
    dog = _dog(now, host_window=10.0)
    for w in HOSTS:
        _beat(w, now[0])
    dog.check()
    # only w/2 goes stale; w/3 keeps beating
    now[0] = 130.0
    for w in ("w/0", "w/1", "w/3"):
        _beat(w, now[0])
    dog.check()
    assert dog.lost_workers() == ["w/2"]
    assert _kinds() == []  # deferred while host-B's fate resolves
    # window passes without the host completing -> individual event
    now[0] = 141.0
    for w in ("w/0", "w/1", "w/3"):
        _beat(w, now[0])
    dog.check()
    assert _kinds() == ["worker_lost"]
    assert dog.lost_hosts() == []


def test_unmapped_worker_loss_is_immediate():
    flight.reset_default()
    now = [100.0]
    dog = Watchdog(EXP, TRIAL, ["solo/0"], timeout=10.0, grace=5.0,
                   poll_interval=0.0, clock=lambda: now[0],
                   host_of=lambda w: None)
    _beat("solo/0", now[0])
    dog.check()
    now[0] = 130.0
    dog.check()
    assert _kinds() == ["worker_lost"]


def test_host_flap_recovery_rearms_attribution():
    flight.reset_default()
    now = [100.0]
    dog = _dog(now)
    for w in HOSTS:
        _beat(w, now[0])
    dog.check()
    now[0] = 130.0
    for w in ("w/2", "w/3"):
        _beat(w, now[0])
    dog.check()
    assert dog.lost_hosts() == ["host-A"]
    # one member returns: the host is back in play
    now[0] = 135.0
    _beat("w/0", now[0])
    for w in ("w/2", "w/3"):
        _beat(w, now[0])
    dog.check()
    assert dog.lost_hosts() == []
    # history survives the flap
    assert len(dog.host_lost_events()) == 1


# ----------------------------------------------------------------------
def test_worker_server_publishes_host_id(monkeypatch):
    monkeypatch.setenv(cluster.HOST_ID_ENV, "host-0042")
    assert cluster.current_host_id() == "host-0042"
    from realhf_tpu.system.worker_base import WorkerServer

    srv = WorkerServer(EXP, TRIAL, "mw/7", heartbeat_interval=60.0)
    try:
        assert srv.host_id == "host-0042"
        assert name_resolve.get(
            names.worker_host(EXP, TRIAL, "mw/7")) == "host-0042"
        from realhf_tpu.system.pod import name_resolve_host_lookup
        lookup = name_resolve_host_lookup(EXP, TRIAL)
        assert lookup("mw/7") == "host-0042"
        assert lookup("mw/99") is None
    finally:
        srv.stop_heartbeat()


def test_worker_server_no_host_outside_pod(monkeypatch):
    monkeypatch.delenv(cluster.HOST_ID_ENV, raising=False)
    from realhf_tpu.system.worker_base import WorkerServer

    srv = WorkerServer(EXP, TRIAL, "mw/8", heartbeat_interval=60.0)
    try:
        assert srv.host_id is None
        with pytest.raises(name_resolve.NameEntryNotFoundError):
            name_resolve.get(names.worker_host(EXP, TRIAL, "mw/8"))
    finally:
        srv.stop_heartbeat()


# ----------------------------------------------------------------------
def test_exclusion_book_coalesces_host_losses():
    now = [0.0]
    book = ExclusionBook(base=10.0, jitter=0.0,
                         clock=lambda: now[0], host_of=HOSTS.get,
                         coalesce_secs=5.0)
    d0 = book.exclude("w/0")
    assert d0 == 10.0
    # sibling casualty of the same host within the coalesce window:
    # SAME failure event -- no loss-count bump, shared window
    now[0] = 1.0
    book.exclude("w/1")
    assert book.loss_count("w/0") == book.loss_count("w/1") == 1
    # every worker of the host shares the exclusion
    assert book.is_excluded("w/0") and book.is_excluded("w/1")
    assert not book.is_excluded("w/2")  # other host untouched
    assert book.excluded() == ["host-A"]
    # a SECOND failure past the coalesce window backs off exponentially
    now[0] = 20.0
    assert not book.is_excluded("w/0")
    assert book.exclude("w/1") == 20.0
    assert book.loss_count("w/0") == 2
    # forgiving any member forgives the host
    book.forgive("w/0")
    assert book.loss_count("w/1") == 0
    assert not book.is_excluded("w/1")


def test_exclusion_book_unmapped_workers_unchanged():
    now = [0.0]
    book = ExclusionBook(base=5.0, jitter=0.0, clock=lambda: now[0])
    book.exclude("x/0")
    book.exclude("x/0")
    assert book.loss_count("x/0") == 2  # no coalescing without hosts
    assert book.excluded() == ["x/0"]


# ----------------------------------------------------------------------
def test_buffer_invalidate_outputs_forces_recompute():
    from realhf_tpu.api.data import SequenceSample
    from realhf_tpu.system.buffer import SequenceBuffer

    def meta(keys, ids):
        return SequenceSample(
            keys=list(keys), trailing_shapes={k: () for k in keys},
            dtypes={k: np.int32 for k in keys}, ids=list(ids),
            seqlens={k: [[4] for _ in ids] for k in keys})

    buf = SequenceBuffer(["gen", "train"], capacity=2)
    bid = buf.put_batch(meta(["prompts"], ["a", "b"]), "mw/0", 0, False)
    buf.mark_dispatched(bid, "gen")
    buf.amend_batch(bid, meta(["tokens"], ["a", "b"]), "mw/1", "gen")
    # train is ready: gen's outputs are present
    assert (bid, "train") in [
        t for t in buf.ready_mfcs({"gen": ("prompts",),
                                   "train": ("tokens",)})]
    # mw/1 dies without grace: its outputs are gone
    buf.invalidate_outputs(bid, "gen", ["tokens"])
    e = buf.get(bid)
    assert "gen" not in e.completed and "gen" not in e.dispatched
    assert "tokens" not in e.key_owner and "tokens" not in e.meta.keys
    ready = buf.ready_mfcs({"gen": ("prompts",), "train": ("tokens",)})
    # the producer recomputes; the consumer waits for it
    assert (bid, "gen") in ready and (bid, "train") not in ready


# ----------------------------------------------------------------------
def test_grow_advisor_emits_after_streak_with_cooldown():
    from realhf_tpu.system.elastic import GrowAdvisor

    flight.reset_default()
    now = [0.0]
    adv = GrowAdvisor(threshold=2, consecutive=3, cooldown_secs=30.0,
                      clock=lambda: now[0])
    assert not adv.observe(5) and not adv.observe(5)
    assert adv.observe(5, server="s/0")  # third consecutive breach
    assert adv.suggestions == 1
    ev = [e for e in flight.default_recorder().events()
          if e["kind"] == "elastic_grow_suggestion"]
    assert len(ev) == 1 and ev[0]["queue_depth"] == 5 \
        and ev[0]["threshold"] == 2 and ev[0]["server"] == "s/0"
    # cooldown suppresses while the breach persists ...
    assert not (adv.observe(9) or adv.observe(9) or adv.observe(9))
    # ... and a sustained breach re-emits the moment it expires
    now[0] = 31.0
    assert adv.observe(9)
    assert adv.suggestions == 2
    # a dip resets the streak
    assert not adv.observe(1)
    assert adv._streak == 0


def test_grow_advisor_disabled_and_below_threshold():
    from realhf_tpu.system.elastic import GrowAdvisor

    off = GrowAdvisor(threshold=0)
    assert not any(off.observe(10 ** 6) for _ in range(10))
    adv = GrowAdvisor(threshold=8, consecutive=1)
    assert not adv.observe(8)  # boundary: depth must EXCEED
    assert adv.observe(9)


# ----------------------------------------------------------------------
def _beat_boot(worker, ts, boot):
    name_resolve.add(names.worker_heartbeat(EXP, TRIAL, worker),
                     f"{ts:.3f}:{boot}", replace=True)


def test_fast_relaunch_is_a_loss_edge_then_recovers():
    """Incarnation fencing: a worker relaunched FASTER than the
    staleness timeout (fresh beat, new boot id) is reported as a
    one-check loss edge -- its predecessor's in-flight work died with
    it -- and flap-recovers on the next check."""
    flight.reset_default()
    now = [100.0]
    dog = Watchdog(EXP, TRIAL, ["solo/0"], timeout=10.0, grace=5.0,
                   poll_interval=0.0, clock=lambda: now[0])
    _beat_boot("solo/0", now[0], "boot-a")
    assert dog.check()["solo/0"] == ALIVE
    # new incarnation beats BEFORE the old beat ever went stale
    now[0] = 103.0
    _beat_boot("solo/0", now[0], "boot-b")
    v = dog.check()
    assert v["solo/0"] == ALIVE          # the successor is healthy...
    assert dog.lost_workers() == ["solo/0"]  # ...but the edge fired
    ev = [e for e in flight.default_recorder().events()
          if e["kind"] == "worker_lost"]
    assert len(ev) == 1 and ev[0]["reason"] == "relaunched"
    # next check: flap recovery; same boot id never re-fires
    now[0] = 104.0
    dog.check()
    assert dog.lost_workers() == []
    now[0] = 105.0
    dog.check()
    assert dog.lost_workers() == []


def test_fast_host_relaunch_attributes_host_lost():
    """Both workers of a host relaunching under the staleness timeout
    (a preempted VM coming straight back) still yields ONE HOST_LOST
    attribution."""
    flight.reset_default()
    now = [100.0]
    dog = _dog(now)
    for w in HOSTS:
        _beat_boot(w, now[0], f"{w}-boot1")
    dog.check()
    now[0] = 102.0
    for w in ("w/0", "w/1"):
        _beat_boot(w, now[0], f"{w}-boot2")  # host-A came back fast
    for w in ("w/2", "w/3"):
        _beat_boot(w, now[0], f"{w}-boot1")
    dog.check()
    assert dog.lost_hosts() == ["host-A"]
    assert [e["kind"] for e in flight.default_recorder().events()] \
        == ["host_lost"]
    log = dog.host_lost_events()
    assert len(log) == 1 and log[0]["workers"] == ["w/0", "w/1"]
    # recovery on the next sweep
    now[0] = 103.0
    dog.check()
    assert dog.lost_workers() == [] and dog.lost_hosts() == []


def test_legacy_plain_ts_beats_never_fence():
    now = [100.0]
    dog = Watchdog(EXP, TRIAL, ["solo/1"], timeout=10.0, grace=5.0,
                   poll_interval=0.0, clock=lambda: now[0])
    _beat("solo/1", now[0])
    dog.check()
    now[0] = 105.0
    _beat("solo/1", now[0])  # still no boot id
    dog.check()
    assert dog.lost_workers() == []
