"""Pod manifest generation (system/pod.py): determinism, host
assignment, scheduler round-trip, scrape targets, CLI, and the
PodController's submit-retry / bring-up-deadline supervision."""

import json
import os
import subprocess

import pytest

from realhf_tpu.base import name_resolve, names
from realhf_tpu.base.cluster import HOST_ID_ENV
from realhf_tpu.base.retry import RetryPolicy
from realhf_tpu.system import pod
from realhf_tpu.system.scheduler import JobInfo, JobState, SchedulerClient


def _build(**kw):
    args = dict(n_hosts=2, n_model_workers=3)
    args.update(kw)
    return pod.build_pod_manifest("exp", "t0", **args)


def test_manifest_deterministic_bytes():
    a = _build(n_chips_per_host=4).to_json()
    b = _build(n_chips_per_host=4).to_json()
    assert a == b
    # byte-stable across a json round-trip too (sorted keys, no
    # timestamps): committable / diffable
    m = pod.PodManifest.from_json(a)
    assert m.to_json() == a


def test_manifest_contiguous_assignment_and_env():
    m = _build(n_hosts=2, n_model_workers=4, n_chips_per_host=8)
    # master controller-adjacent on host 0; model workers in
    # contiguous blocks (pod-slice shape)
    assert m.host_of("master_worker/0") == "host-0000"
    assert m.host_of("model_worker/0") == "host-0000"
    assert m.host_of("model_worker/1") == "host-0000"
    assert m.host_of("model_worker/2") == "host-0001"
    assert m.host_of("model_worker/3") == "host-0001"
    assert m.host_of("model_worker/99") is None
    for h in m.hosts:
        assert h.env[HOST_ID_ENV] == h.host_id
        assert h.env["REALHF_TPU_LOCAL_DEVICE_COUNT"] == "8"
    # distinct per-host scrape ports
    assert len({h.scrape_port for h in m.hosts}) == m.n_hosts


def test_manifest_assignment_override_and_validation():
    m = _build(assignment={"model_worker/1": "host-0001"})
    assert m.host_of("model_worker/1") == "host-0001"
    with pytest.raises(ValueError, match="unknown workers"):
        _build(assignment={"model_worker/77": "host-0000"})


def test_manifest_round_trips_through_scheduler(tmp_path):
    m = _build(n_hosts=3, n_model_workers=5)
    sched = pod.MultiHostLocalScheduler(manifest=m)
    try:
        # host mapping agrees with the manifest for every worker
        for w in m.workers:
            assert sched.host_of(w) == m.host_of(w)
        assert sched.hosts() == sorted(h.host_id for h in m.hosts)
        # submission injects the host env namespace
        sched.submit("model_worker/4", ["sleep", "0"])
        _cmd, env = sched._specs["model_worker/4"]
        assert env[HOST_ID_ENV] == m.host_of("model_worker/4")
        assert "model_worker/4" in sched.workers_on(
            m.host_of("model_worker/4"))
    finally:
        sched.stop_all(grace=0.5)


def test_scrape_targets_file(tmp_path):
    m = _build(n_hosts=2, n_model_workers=2)
    path = str(tmp_path / "targets.json")
    assert pod.write_scrape_targets(
        m.hosts, path, labels=dict(experiment="exp")) == path
    entries = json.loads(open(path).read())
    assert [e["labels"]["host"] for e in entries] == \
        ["host-0000", "host-0001"]
    assert entries[0]["targets"] == ["127.0.0.1:9100"]
    assert entries[1]["targets"] == ["127.0.0.1:9101"]
    assert all(e["labels"]["experiment"] == "exp" for e in entries)
    # deterministic output
    first = open(path).read()
    pod.write_scrape_targets(m.hosts, path, labels=dict(experiment="exp"))
    assert open(path).read() == first


def test_cli_round_trips_deterministically(tmp_path, capsys):
    from realhf_tpu.apps.main import pod_manifest_main

    out = str(tmp_path / "m.json")
    scrape = str(tmp_path / "s.json")
    argv = ["--experiment_name", "exp", "--trial_name", "t0",
            "--n_hosts", "2", "--n_model_workers", "3",
            "--n_chips_per_host", "4", "--out", out,
            "--scrape_out", scrape]
    assert pod_manifest_main(argv) == 0
    text1 = open(out).read()
    assert pod_manifest_main(argv) == 0
    assert open(out).read() == text1  # byte-identical rerun
    m = pod.PodManifest.from_json(text1)
    assert m.to_json() == _build(n_chips_per_host=4).to_json()
    assert len(json.loads(open(scrape).read())) == 2
    # '-' prints the same bytes to stdout
    assert pod_manifest_main(argv[:-4] + ["--out", "-"]) == 0
    assert capsys.readouterr().out == text1
    # round-trip into the emulator
    sched = pod.MultiHostLocalScheduler(manifest=m)
    assert sched.host_of("model_worker/2") == m.host_of("model_worker/2")


# ----------------------------------------------------------------------
class FlakySched(SchedulerClient):
    """Fails the first ``fail`` submits with OSError, then records."""

    def __init__(self, fail=0):
        self.fail = fail
        self.submitted = []

    def submit(self, name, cmd, env=None):
        if self.fail > 0:
            self.fail -= 1
            raise OSError("transient fork failure")
        self.submitted.append((name, list(cmd), dict(env or {})))

    def find(self, name):
        return JobInfo(name, JobState.RUNNING)

    def stop_all(self, grace=10.0):
        pass


def test_controller_submit_retries_transient_failures():
    sched = FlakySched(fail=2)
    ctl = pod.PodController(sched, submit_retry=RetryPolicy(
        max_attempts=3, base_delay=0.001, max_delay=0.01))
    ctl.submit("model_worker/0", ["x"], env={"A": "1"})
    assert [s[0] for s in sched.submitted] == ["model_worker/0"]

    sched2 = FlakySched(fail=3)
    ctl2 = pod.PodController(sched2, submit_retry=RetryPolicy(
        max_attempts=3, base_delay=0.001, max_delay=0.01))
    with pytest.raises(OSError):
        ctl2.submit("model_worker/0", ["x"])


def test_controller_bringup_deadline_names_missing_by_host():
    m = _build(n_hosts=2, n_model_workers=2)
    sched = pod.MultiHostLocalScheduler(manifest=m)
    ctl = pod.PodController(sched)
    # only host-0000's workers registered their endpoints
    for w in ("master_worker/0", "model_worker/0"):
        name_resolve.add(names.worker_key("exp", "t0", w), "tcp://x",
                         replace=True)
    with pytest.raises(pod.PodBringupError) as ei:
        ctl.wait_ready("exp", "t0", m.workers, deadline=0.05,
                       poll_interval=0.01)
    assert ei.value.missing_by_host == {
        "host-0001": ["model_worker/1"]}
    assert "host-0001" in str(ei.value)
    # once everyone registers, wait_ready returns
    name_resolve.add(names.worker_key("exp", "t0", "model_worker/1"),
                     "tcp://y", replace=True)
    ctl.wait_ready("exp", "t0", m.workers, deadline=1.0,
                   poll_interval=0.01)


def test_controller_single_host_fallback(tmp_path):
    """Over a plain scheduler the controller degrades to one synthetic
    host and still writes a scrape-target file."""
    sched = FlakySched()
    ctl = pod.PodController(sched)
    assert not ctl.multi_host
    ctl.submit("model_worker/0", ["x"])
    assert ctl.hosts() == ["host-0000"]
    assert ctl.host_of("model_worker/0") == "host-0000"
    path = ctl.write_scrape_targets(path=str(tmp_path / "s.json"))
    assert path and json.loads(open(path).read())[0]["labels"][
        "host"] == "host-0000"


def test_make_scheduler_multihost_mode():
    from realhf_tpu.system.scheduler import make_scheduler

    sched = make_scheduler("multihost_local", n_hosts=3)
    assert isinstance(sched, pod.MultiHostLocalScheduler)
    assert sched.n_hosts == 3
    # count-free fallback: round-robin by index, controller types on 0
    assert sched.host_of("master_worker/0") == "host-0000"
    assert sched.host_of("model_worker/4") == "host-0001"
