"""Pod-scale controller path, end-to-end over real OS processes.

Tier-1 keeps to the cheap process-group mechanics of the emulated
hosts (kill_host takes the whole host down at once; resubmit keeps a
worker's host). The full 2-host PPO drill -- SIGKILL one emulated
host mid-trial -> single HOST_LOST attribution -> elastic degrade
around the missing host -> rejoin -> re-expand -> merged obs
artifacts -- is ``slow``-marked (ISSUE 9 acceptance; run directly:
``pytest -m slow tests/pod/test_pod_e2e.py``)."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "system"))
from tiny_model import TINY, write_jsonl  # noqa: E402

from realhf_tpu.base.cluster import HOST_ID_ENV  # noqa: E402
from realhf_tpu.system import pod  # noqa: E402

WORKER_ENV = {
    "REALHF_TPU_BACKEND": "cpu",
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PYTHONPATH": "/root/repo",
    "REALHF_TPU_TRACE": "1",
}


def _wait_state(sched, name, states, timeout=10.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        info = sched.find(name)
        if info.state.value in states:
            return info
        time.sleep(0.05)
    raise AssertionError(
        f"{name} never reached {states}: {sched.find(name)}")


def test_kill_host_takes_down_whole_process_group():
    sched = pod.MultiHostLocalScheduler(
        n_hosts=2, assign={"a/0": "host-0000", "b/0": "host-0001",
                           "b/1": "host-0001"})
    try:
        for n in ("a/0", "b/0", "b/1"):
            sched.submit(n, ["sleep", "30"])
        for n in ("a/0", "b/0", "b/1"):
            assert sched.find(n).state.value == "RUNNING"
        killed = sched.kill_host("host-0001")
        assert killed == ["b/0", "b/1"]
        # the whole emulated VM dies at once; the other host survives
        for n in ("b/0", "b/1"):
            assert _wait_state(sched, n, ("FAILED",)).returncode != 0
        assert sched.find("a/0").state.value == "RUNNING"
        # resubmit (the launcher's elastic-rejoin primitive) keeps the
        # worker on its host, env included
        sched.resubmit("b/0")
        assert sched.find("b/0").state.value == "RUNNING"
        assert sched._specs["b/0"][1][HOST_ID_ENV] == "host-0001"
        assert sched.host_of("b/0") == "host-0001"
        # resubmit_host relaunches the remaining dead job only
        assert sched.resubmit_host("host-0001") == ["b/1"]
        assert sched.find("b/1").state.value == "RUNNING"
    finally:
        sched.stop_all(grace=0.5)


def test_kill_host_unknown_or_idle_host_is_noop():
    sched = pod.MultiHostLocalScheduler(n_hosts=2)
    assert sched.kill_host("host-0001") == []
    assert sched.kill_host("no-such-host") == []


# ----------------------------------------------------------------------
@pytest.fixture
def prompt_data(tmp_path):
    rng = np.random.default_rng(1)
    path = tmp_path / "prompts.jsonl"
    # 80 prompts / bs 8 = 10 batches per epoch: the 16-step trial now
    # CROSSES an epoch boundary with max_concurrent_batches=2 -- safe
    # since ISSUE 10 epoch-qualified the data ids (a finishing batch's
    # clear_data_cache can no longer delete a raw id an in-flight
    # next-epoch batch still needs)
    write_jsonl(path, [
        {"id": i,
         "prompt": " ".join(f"w{int(x)}" for x in rng.integers(0, 50, 4))}
        for i in range(80)])
    return str(path)


@pytest.mark.slow
def test_pod_host_loss_degrade_rejoin_e2e(prompt_data, tmp_path,
                                          monkeypatch):
    """ISSUE 9 acceptance: a 2-host emulated pod runs PPO with ref_inf
    and rew_inf placed on host-0001; SIGKILL that host mid-trial. The
    watchdog attributes ONE HOST_LOST for its two workers, the elastic
    planner degrades both MFCs onto the surviving host without
    re-consuming data (exact global_step), the relaunched host rejoins
    and re-expands to the original layout, and teardown leaves a
    merged trace spanning both hosts, a merged flight dump recording
    the host loss, and the per-host Prometheus scrape-target file."""
    from realhf_tpu.api.experiment import (
        FaultToleranceConfig,
        MFCAllocation,
    )
    from realhf_tpu.apps.main import run_trial
    from realhf_tpu.base import constants, name_resolve, names
    from realhf_tpu.base.testing import IntegerTokenizer
    from realhf_tpu.engine.optim import OptimizerConfig
    from realhf_tpu.experiments.common import apply_overrides
    from realhf_tpu.experiments.ppo_exp import PPOConfig
    from realhf_tpu.parallel.mesh import ParallelismConfig

    monkeypatch.setenv("REALHF_TPU_TRACE", "1")  # launcher-side merge
    exp, trial = "pode2e", "t0"
    cfg = PPOConfig(experiment_name=exp, trial_name=trial,
                    total_train_epochs=2, benchmark_steps=16)
    apply_overrides(cfg, {
        "dataset.path": prompt_data,
        "dataset.train_bs_n_seqs": "8",
        "dataset.max_seqlen": "16",
        "ppo.max_new_tokens": "8",
        "ppo.min_new_tokens": "1",
        "ppo.top_k": "16",
        "ppo.ppo_n_minibatches": "2",
    })
    spec = cfg.build()
    for _role, mspec in spec.models.items():
        mspec.path = None
        mspec.random_init_config = dict(TINY)
        mspec.bf16 = False
        mspec.parallel = ParallelismConfig(data_parallel_size=2)
        if mspec.optimizer is not None:
            mspec.optimizer = OptimizerConfig(
                lr=1e-3, warmup_steps_proportion=0.0,
                lr_scheduler_type="constant")
    spec.tokenizer = IntegerTokenizer()
    spec.n_model_workers = 3
    # every primary (and the data owner, actor_gen's leader) on
    # worker 0 / host-0000; the two migratable inference MFCs on the
    # doomed host-0001
    spec.worker_assignment = {"actor": 0, "critic": 0, "ref": 0,
                              "reward": 0}
    spec.allocations = dict(
        spec.allocations,
        ref_inf=MFCAllocation(ParallelismConfig(data_parallel_size=2),
                              workers=[1]),
        rew_inf=MFCAllocation(ParallelismConfig(data_parallel_size=2),
                              workers=[2]))
    spec.ft = FaultToleranceConfig(
        heartbeat_interval=0.5, heartbeat_timeout=8.0,
        watchdog_poll_secs=0.5, elastic_degrade=True,
        elastic_rejoin=True, worker_lost_fatal_secs=120.0,
        gather_timeout_secs=300.0, host_lost_window_secs=20.0)
    assert spec.is_cross_group("ref_inf", "ref")
    assert spec.is_cross_group("rew_inf", "reward")

    manifest = pod.build_pod_manifest(
        exp, trial, n_hosts=2, n_model_workers=3,
        assignment={"model_worker/1": "host-0001",
                    "model_worker/2": "host-0001"})
    assert manifest.host_of("model_worker/0") == "host-0000"
    assert manifest.host_of("master_worker/0") == "host-0000"
    sched = pod.MultiHostLocalScheduler(manifest=manifest)

    killed = {}

    def _killer():
        # SIGKILL the emulated host once training has made progress
        # (>= 2 finished batches: both doomed MFCs proved they run on
        # host-0001 first)
        end = time.monotonic() + 900
        while time.monotonic() < end:
            try:
                if int(name_resolve.get(names.train_progress(
                        exp, trial))) >= 2:
                    break
            except Exception:  # noqa: BLE001 - not published yet
                pass
            time.sleep(0.5)
        else:
            return
        killed["jobs"] = sched.kill_host("host-0001")
        killed["at_step"] = int(name_resolve.get(
            names.train_progress(exp, trial)))

    killer = threading.Thread(target=_killer, daemon=True)
    killer.start()
    out = run_trial(spec, env=dict(WORKER_ENV), timeout=1800,
                    sched=sched)
    killer.join(timeout=10)

    # the kill really happened, mid-trial
    assert sorted(killed["jobs"]) == ["model_worker/1",
                                      "model_worker/2"]
    assert 2 <= killed["at_step"] < 16
    # no data re-consumption across the host loss: exact step count
    assert out["complete"]
    assert out["global_step"] == 16
    assert np.isfinite(out["stats"]["actor_train"]["actor_loss"])

    # ONE HOST_LOST attribution for the host's two workers
    assert len(out["host_lost"]) == 1
    assert out["host_lost"][0]["host"] == "host-0001"
    assert out["host_lost"][0]["workers"] == ["model_worker/1",
                                              "model_worker/2"]

    # the doomed MFCs ran on host-0001 first, then on the survivor
    rows = {m: sorted((r["bid"], r["worker"]) for r in out["exec_log"]
                      if r["mfc"] == m)
            for m in ("ref_inf", "rew_inf")}
    assert rows["ref_inf"][0][1] == "model_worker/1"
    assert rows["rew_inf"][0][1] == "model_worker/2"
    assert "model_worker/0" in {w for _b, w in rows["ref_inf"]}
    assert "model_worker/0" in {w for _b, w in rows["rew_inf"]}
    # rejoin re-expanded to the original layout: the relaunched host
    # served its MFCs again for later batches
    reexpanded = [m for m in ("ref_inf", "rew_inf")
                  if rows[m][-1][1] != "model_worker/0"]
    assert reexpanded, (
        "no MFC returned to host-0001 after rejoin: "
        f"{rows}")

    # teardown obs artifacts
    log_dir = constants.run_log_path(exp, trial)
    merged_trace = os.path.join(log_dir, "obs", "trace",
                                "merged_trace.json")
    assert os.path.exists(merged_trace)
    pids = {e.get("pid") for e in
            json.load(open(merged_trace))["traceEvents"]}
    assert len(pids) >= 3  # master + workers from BOTH hosts
    merged_flight = os.path.join(log_dir, "obs", "flight",
                                 "merged_flight.json")
    assert os.path.exists(merged_flight)
    fl = json.load(open(merged_flight))
    assert any(e["kind"] == "host_lost" and e["host"] == "host-0001"
               for e in fl["events"])
    scrape = os.path.join(log_dir, "obs", "scrape_targets.json")
    entries = json.load(open(scrape))
    assert [e["labels"]["host"] for e in entries] == \
        ["host-0000", "host-0001"]
