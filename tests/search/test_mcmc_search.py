"""C++ MCMC allocation search (reference csrc/search/search.cpp
mdm_search): native module compiles, the simulator respects deps and
device contention, and the searched PPO allocation beats naive
everything-on-all-chips in simulated time."""

import numpy as np
import pytest

from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.experiments.common import apply_overrides
from realhf_tpu.experiments.ppo_exp import PPOConfig
from realhf_tpu.search.engine import (
    Candidate,
    MFCWorkload,
    TPUCostModel,
    apply_searched_allocations,
    enumerate_candidates,
    exec_time,
    load_native,
    search_rpc_allocations,
    workloads_from_spec,
)

LLAMA_7B = dict(n_layers=32, n_kv_heads=32, n_q_heads=32, hidden_dim=4096,
                intermediate_dim=11008, vocab_size=32000, n_positions=4096,
                apply_rotary=True, layer_norm_type="rms", mlp_type="llama",
                use_attention_bias=False, use_attn_proj_bias=False,
                use_mlp_bias=False, activation_function="silu")


def _ppo_spec():
    cfg = PPOConfig(experiment_name="search", trial_name="t0")
    apply_overrides(cfg, {"dataset.path": "/dev/null",
                          "dataset.train_bs_n_seqs": "128"})
    spec = cfg.build()
    for mspec in spec.models.values():
        mspec.path = None
        mspec.random_init_config = dict(LLAMA_7B)
    return spec


def test_native_module_builds():
    lib = load_native()
    assert hasattr(lib, "mcmc_search")
    assert hasattr(lib, "simulate_assignment")


def test_enumerate_respects_memory():
    cm = TPUCostModel()
    w = MFCWorkload(name="t", role="actor",
                    interface_type=ModelInterfaceType.TRAIN_STEP,
                    fwd_flops=1e15, param_bytes=13.5e9,
                    train_state_bytes=121e9)
    cands = enumerate_candidates(w, 8, cm)
    # 7B train state (121 GB) needs full TP on 8 v5e chips
    assert all(c.parallel.tensor_parallel_size == 8 for c in cands)
    w2 = MFCWorkload(name="g", role="actor",
                     interface_type=ModelInterfaceType.GENERATE,
                     fwd_flops=1e15, param_bytes=13.5e9, gen_tokens=256)
    cands2 = enumerate_candidates(w2, 8, cm)
    # bf16 weights fit from tp=2 up: dp-wide options must exist
    assert any(c.parallel.data_parallel_size > 1 for c in cands2)
    for c in cands2:
        assert 13.5e9 * 1.25 / c.parallel.tensor_parallel_size \
            <= cm.hbm_budget


def test_decode_is_bandwidth_bound():
    cm = TPUCostModel()
    w = MFCWorkload(name="g", role="actor",
                    interface_type=ModelInterfaceType.GENERATE,
                    fwd_flops=1e12, param_bytes=13.5e9, gen_tokens=256)
    # widening TP cuts decode time (smaller weight shard per chip);
    # widening DP alone does not
    t_tp2 = exec_time(w, tp=2, dp=1, cm=cm)
    t_tp8 = exec_time(w, tp=8, dp=1, cm=cm)
    t_dp8 = exec_time(w, tp=2, dp=4, cm=cm)
    assert t_tp8 < t_tp2
    assert abs(t_dp8 - t_tp2) / t_tp2 < 0.2  # decode dominated


def test_search_beats_naive_on_ppo():
    spec = _ppo_spec()
    workloads, deps = workloads_from_spec(spec, gen_tokens=256,
                                          avg_seqlen=512)
    assert deps["actor_train"], "train depends on inference outputs"
    res = search_rpc_allocations(workloads, deps, n_devices=8,
                                 n_steps=5000, seed=0)
    assert res.time < 1e29  # a feasible schedule exists
    for w in workloads:
        c = res.assignment[w.name]
        assert 0 <= c.dev_lo < c.dev_hi <= 8
        assert c.parallel.world_size == c.dev_hi - c.dev_lo

    # naive: every MFC on all 8 chips at its fastest full-fleet
    # candidate, scored by the SAME simulator (incl. realloc charges)
    from realhf_tpu.search.engine import simulate_named_assignment
    cm = TPUCostModel()
    naive = {}
    for w in workloads:
        cands = [c for c in enumerate_candidates(w, 8, cm)
                 if c.dev_hi - c.dev_lo == 8]
        naive[w.name] = min(cands, key=lambda c: c.time)
    naive_time = simulate_named_assignment(workloads, deps, 8, naive)
    assert res.time <= naive_time * 1.001, (res.time, naive_time)


def test_apply_to_spec():
    spec = _ppo_spec()
    res = apply_searched_allocations(spec, n_devices=8, n_steps=3000)
    assert spec.models["actor"].parallel.world_size >= 1
    # overrides only where layouts differ from the role primary
    for name, par in spec.allocations.items():
        node_role = next(n.role for n in spec.mfcs if n.name == name)
        assert not par.same_layout(spec.models[node_role].parallel)


def test_pipeline_candidates_enumerated_with_bubble_cost():
    """Training workloads too big for TP-only HBM get pipeline
    candidates; their time includes the GPipe bubble factor."""
    w = MFCWorkload(
        name="train", role="actor",
        interface_type=ModelInterfaceType.TRAIN_STEP,
        fwd_flops=1e15, param_bytes=140e9,
        train_state_bytes=70e9 * 18, n_layers=80)
    # 1.26 TB of training state: on 128 chips it only fits when layers
    # are also sharded over pipeline stages (tp capped at 16 here)
    cm = TPUCostModel(hbm_budget=16e9 * 0.65)
    cands = enumerate_candidates(w, 128, cm)
    pps = {c.parallel.pipeline_parallel_size for c in cands}
    assert any(p > 1 for p in pps), "no pipeline candidates"
    for c in cands:
        par = c.parallel
        assert w.n_layers % par.pipeline_parallel_size == 0
        state_per_chip = w.train_state_bytes / (
            par.tensor_parallel_size * par.pipeline_parallel_size)
        assert state_per_chip <= cm.hbm_budget
    t_pp2 = exec_time(w, tp=8, dp=1, cm=cm, pp=2)
    t_flat = exec_time(w, tp=8, dp=2, cm=cm, pp=1)
    # same 16 chips; pp=2 pays the 1F1B bubble (M+S-1)/M = 9/8 at the
    # schedule's default M = 4*pp (the old GPipe term at M = 2*pp was
    # 5/4 -- pp candidates re-rank cheaper under 1F1B)
    from realhf_tpu.parallel.schedule import train_bubble_factor
    assert train_bubble_factor(2) == pytest.approx(9 / 8)
    assert t_pp2 == pytest.approx(t_flat * 9 / 8, rel=1e-6)

    gen = MFCWorkload(
        name="gen", role="actor",
        interface_type=ModelInterfaceType.GENERATE,
        fwd_flops=1e15, param_bytes=14e9, gen_tokens=256, n_layers=80)
    assert all(c.parallel.pipeline_parallel_size == 1
               for c in enumerate_candidates(gen, 128, cm))


def test_calibrate_cost_model_probes_measured_efficiency():
    """calibrate_cost_model times real probe models on the current
    backend and folds the measured MFU / decode bandwidth into the
    model (reference profiled cost model, estimate.py:323)."""
    from realhf_tpu.search.engine import TPUCostModel, calibrate_cost_model
    from realhf_tpu.experiments.sft_exp import SFTConfig
    from realhf_tpu.experiments.common import apply_overrides

    cfg = SFTConfig(experiment_name="calib", trial_name="t0")
    spec = cfg.build()
    spec.models["default"].path = None
    spec.models["default"].random_init_config = dict(
        n_layers=2, n_kv_heads=2, n_q_heads=4, hidden_dim=32,
        intermediate_dim=64, vocab_size=128, apply_rotary=True,
        layer_norm_type="rms", mlp_type="llama",
        use_attention_bias=False, use_attn_proj_bias=False,
        use_mlp_bias=False, activation_function="silu",
        compute_dtype="float32")
    base = TPUCostModel(peak_flops=1e12, hbm_bandwidth=100e9)
    cm = calibrate_cost_model(spec, base=base, probe_seqs=2,
                              probe_len=32, probe_gen_tokens=4)
    # measured values replaced the defaults and are sane fractions
    assert 0.0 < cm.mxu_efficiency <= 1.0
    assert cm.mxu_efficiency != base.mxu_efficiency or \
        cm.hbm_bandwidth != base.hbm_bandwidth
    assert 0.0 < cm.hbm_bandwidth <= base.hbm_bandwidth
