"""Persisted-calibration plumbing: scripts/calibrate.py writes the
artifact; TPUCostModel auto-loads it so allocation searches price
candidates with measured numbers (ROADMAP weak #5)."""

import dataclasses
import json
import os
import sys

import pytest

from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.search.engine import (
    CALIBRATION_ENV,
    MFCWorkload,
    TPUCostModel,
    default_cost_model,
    exec_time,
    load_cost_model,
)


def _write(path, payload):
    with open(path, "w") as f:
        json.dump(payload, f)


def test_load_cost_model_artifact_and_flat_layouts(tmp_path):
    p = tmp_path / "cal.json"
    _write(p, {"backend": "tpu",
               "calibrated": {"mxu_efficiency": 0.55,
                              "hbm_bandwidth": 700e9,
                              "not_a_field": 1}})
    cm = load_cost_model(str(p))
    assert cm.mxu_efficiency == pytest.approx(0.55)
    assert cm.hbm_bandwidth == pytest.approx(700e9)
    # unspecified fields keep defaults
    assert cm.peak_flops == TPUCostModel().peak_flops

    _write(p, {"mxu_efficiency": 0.33})
    assert load_cost_model(str(p)).mxu_efficiency == pytest.approx(0.33)


def test_load_cost_model_tolerates_missing_and_corrupt(tmp_path):
    assert load_cost_model(str(tmp_path / "absent.json")) is None
    p = tmp_path / "bad.json"
    p.write_text("{truncated")
    assert load_cost_model(str(p)) is None
    _write(p, ["not", "a", "dict"])
    assert load_cost_model(str(p)) is None


def test_default_cost_model_env_pickup_changes_exec_time(
        tmp_path, monkeypatch):
    p = tmp_path / "cal.json"
    _write(p, {"calibrated": {"mxu_efficiency": 0.8}})
    monkeypatch.setenv(CALIBRATION_ENV, str(p))
    cm = default_cost_model()
    assert cm.mxu_efficiency == pytest.approx(0.8)

    w = MFCWorkload(name="t", role="actor",
                    interface_type=ModelInterfaceType.TRAIN_STEP,
                    fwd_flops=1e15, param_bytes=1e9,
                    train_state_bytes=9e9, n_layers=8)
    # doubled efficiency halves the modeled train time
    assert exec_time(w, 1, 1, cm) == pytest.approx(
        exec_time(w, 1, 1, TPUCostModel()) * 0.4 / 0.8)

    monkeypatch.setenv(CALIBRATION_ENV, str(tmp_path / "absent.json"))
    assert default_cost_model().mxu_efficiency == pytest.approx(0.4)


def test_calibrate_entry_persists_loadable_artifact(
        tmp_path, monkeypatch, capsys):
    """scripts/calibrate.py writes the artifact atomically in the
    exact layout default_cost_model() loads (the measurement itself is
    covered by test_calibrate_script_pipeline; here it is stubbed so
    the persistence contract stays fast to check)."""
    monkeypatch.syspath_prepend(os.path.join(
        os.path.dirname(__file__), "..", "..", "scripts"))
    import calibrate as calibrate_entry

    import realhf_tpu.search.engine as se

    fake = dataclasses.replace(TPUCostModel(), mxu_efficiency=0.61,
                               hbm_bandwidth=555e9)
    monkeypatch.setattr(se, "calibrate_cost_model",
                        lambda spec, base=None: fake)
    out = str(tmp_path / "calibration_tpu.json")
    monkeypatch.setattr(sys, "argv", ["calibrate.py", "--out", out])
    assert calibrate_entry.main(["--out", out]) == 0

    with open(out) as f:
        artifact = json.load(f)
    assert artifact["base"]["mxu_efficiency"] == 0.4
    assert artifact["calibrated"]["mxu_efficiency"] == 0.61

    monkeypatch.setenv(CALIBRATION_ENV, out)
    cm = default_cost_model()
    assert cm.mxu_efficiency == pytest.approx(0.61)
    assert cm.hbm_bandwidth == pytest.approx(555e9)
