"""The on-chip calibration driver (scripts/calibrate_tpu.py) runs end
to end on the CPU backend: measures a (CPU-meaningless but real)
calibration, writes the artifact, and prints the searched-vs-heuristic
comparison under the calibrated model."""

import json
import os
import sys

import pytest


@pytest.mark.slow
def test_calibrate_script_pipeline(tmp_path, capsys, monkeypatch):
    # slow-marked: this compiles real matmul/transfer probes (~2 min
    # on the 1-vCPU CI box) and alone ate ~15% of the 870 s tier-1
    # budget; the calibration units stay tier-1 via tests/search's
    # cost-model tests, and this e2e still runs under -m slow
    monkeypatch.syspath_prepend(os.path.join(
        os.path.dirname(__file__), "..", "..", "scripts"))
    import calibrate_tpu

    out = str(tmp_path / "calib.json")
    monkeypatch.setattr(sys, "argv",
                        ["calibrate_tpu.py", "--out", out,
                         "--devices", "8"])
    calibrate_tpu.main()

    with open(out) as f:
        artifact = json.load(f)
    assert artifact["backend"] == "cpu"
    cal = artifact["calibrated"]
    # a real measurement replaced the defaults
    assert 0 < cal["mxu_efficiency"] <= 1.0
    assert cal["hbm_bandwidth"] > 0
    assert artifact["base"]["mxu_efficiency"] == 0.4

    text = capsys.readouterr().out
    assert "searched allocation" in text
    assert "heuristic allocation" in text
    assert "speedup" in text
