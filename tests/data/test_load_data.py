"""Dataset loading tests with random JSONL fixtures, mirroring reference
``tests/data/test_load_data.py`` (all 3 datasets x max_length)."""

import json
import string

import numpy as np
import pytest

from realhf_tpu.api import data as data_api
from realhf_tpu.api.config import DatasetAbstraction


class MockTokenizer:
    """Minimal whitespace tokenizer with the HF call signature the
    datasets rely on (so tests avoid downloading real tokenizers)."""

    eos_token = "<eos>"
    eos_token_id = 1
    pad_token_id = 0
    padding_side = "right"

    def _encode_one(self, s):
        return [2 + (hash(w) % 1000) for w in s.replace("<eos>", " <eos>").split()]

    def __call__(self, texts, truncation=False, max_length=None, padding=False,
                 return_length=False, return_attention_mask=False, **kw):
        ids = [self._encode_one(t) for t in texts]
        if truncation and max_length:
            ids = [x[:max_length] for x in ids]
        out = {"input_ids": ids}
        if return_length:
            out["length"] = [len(x) for x in ids]
        return out


def _random_text(rng, lo=2, hi=20):
    n = rng.integers(lo, hi)
    return " ".join("".join(rng.choice(list(string.ascii_lowercase), size=4))
                    for _ in range(n))


@pytest.fixture
def jsonl_fixtures(tmp_path):
    rng = np.random.default_rng(7)
    prompt_path = tmp_path / "prompt.jsonl"
    pa_path = tmp_path / "pa.jsonl"
    rw_path = tmp_path / "rw.jsonl"
    with open(prompt_path, "w") as f:
        for i in range(37):
            f.write(json.dumps({"id": i, "prompt": _random_text(rng)}) + "\n")
    with open(pa_path, "w") as f:
        for i in range(23):
            f.write(json.dumps({"id": i, "prompt": _random_text(rng),
                                "answer": _random_text(rng)}) + "\n")
    with open(rw_path, "w") as f:
        for i in range(19):
            n_pairs = int(rng.integers(1, 4))
            f.write(json.dumps({
                "id": i, "prompt": _random_text(rng),
                "pos_answers": [_random_text(rng) for _ in range(n_pairs)],
                "neg_answers": [_random_text(rng) for _ in range(n_pairs)],
            }) + "\n")
    return dict(prompt=str(prompt_path), prompt_answer=str(pa_path),
                rw_pair=str(rw_path))


@pytest.mark.parametrize("max_length", [16, 128])
@pytest.mark.parametrize("name", ["prompt", "prompt_answer", "rw_pair"])
def test_dataset_loading(jsonl_fixtures, name, max_length):
    import realhf_tpu.datasets  # noqa: F401 - trigger registration

    ds = data_api.make_dataset(
        DatasetAbstraction(
            type_=name,
            args=dict(max_length=max_length, dataset_path=jsonl_fixtures[name])),
        seed=1, dp_rank=0, world_size=1, tokenizer_or_path=MockTokenizer())
    assert len(ds) > 0
    samples = [ds[i] for i in range(len(ds))]
    batch = data_api.SequenceSample.gather(samples)
    assert batch.bs == len(ds)
    if name == "prompt":
        assert "packed_prompts" in batch.keys
    else:
        assert "packed_input_ids" in batch.keys
        total = batch.total_len("packed_input_ids")
        assert batch.data["packed_input_ids"].shape == (total,)


@pytest.mark.parametrize("dp", [1, 2, 3])
def test_dataset_dp_sharding(jsonl_fixtures, dp):
    import realhf_tpu.datasets  # noqa: F401
    from realhf_tpu.api.config import DatasetAbstraction

    lens = []
    all_ids = []
    for r in range(dp):
        ds = data_api.make_dataset(
            DatasetAbstraction("prompt", dict(max_length=32,
                                              dataset_path=jsonl_fixtures["prompt"])),
            seed=1, dp_rank=r, world_size=dp, tokenizer_or_path=MockTokenizer())
        lens.append(len(ds))
        all_ids.extend(ds.ids)
    assert sum(lens) == 37
    assert len(set(all_ids)) == 37  # disjoint shards cover everything


def test_packed_dataloader(jsonl_fixtures):
    import realhf_tpu.datasets  # noqa: F401
    from realhf_tpu.api.config import DatasetAbstraction

    ds = data_api.make_dataset(
        DatasetAbstraction("prompt_answer",
                           dict(max_length=64,
                                dataset_path=jsonl_fixtures["prompt_answer"])),
        seed=1, dp_rank=0, world_size=1, tokenizer_or_path=MockTokenizer())
    dl = data_api.PackedDataLoader(ds, batch_size=8, shuffle=True, seed=3)
    batches = list(dl)
    assert len(batches) == len(dl)
    assert sum(b.bs for b in batches) == len(ds)
    # epoch reshuffling changes order
    first_epoch_ids = [b.ids for b in batches]
    second = [b.ids for b in dl]
    assert first_epoch_ids != second
