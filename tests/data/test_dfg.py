"""DFG construction tests, mirroring reference ``tests/data/test_dfg.py``:
build PPO-like and SFT graphs and check parents/children/edges."""

import pytest

from realhf_tpu.api.config import ModelInterfaceAbstraction, ModelInterfaceType
from realhf_tpu.api.dfg import DFG, MFCDef, OffloadHook, ParamReallocHook


def ppo_nodes():
    itf = ModelInterfaceAbstraction("ppo")
    rw_itf = ModelInterfaceAbstraction("paired_rw")
    return [
        MFCDef(name="actor_gen", n_seqs=32,
               interface_type=ModelInterfaceType.GENERATE, interface_impl=itf,
               model_name="actor", input_keys=("packed_prompts",),
               output_keys=("seq_no_eos_mask", "packed_input_ids",
                            "packed_logprobs", "prompt_mask")),
        MFCDef(name="rew_inf", n_seqs=32,
               interface_type=ModelInterfaceType.INFERENCE, interface_impl=rw_itf,
               model_name="reward", input_keys=("packed_input_ids",),
               output_keys=("rewards",)),
        MFCDef(name="ref_inf", n_seqs=32,
               interface_type=ModelInterfaceType.INFERENCE, interface_impl=itf,
               model_name="ref", input_keys=("packed_input_ids",),
               output_keys=("packed_ref_logprobs",)),
        MFCDef(name="critic_inf", n_seqs=32,
               interface_type=ModelInterfaceType.INFERENCE, interface_impl=itf,
               model_name="critic", input_keys=("packed_input_ids", "seq_no_eos_mask"),
               output_keys=("values",)),
        MFCDef(name="actor_train", n_seqs=32,
               interface_type=ModelInterfaceType.TRAIN_STEP, interface_impl=itf,
               model_name="actor",
               input_keys=("packed_input_ids", "packed_logprobs",
                           "packed_ref_logprobs", "rewards", "values",
                           "prompt_mask", "seq_no_eos_mask")),
        MFCDef(name="critic_train", n_seqs=32,
               interface_type=ModelInterfaceType.TRAIN_STEP, interface_impl=itf,
               model_name="critic",
               input_keys=("packed_input_ids", "packed_logprobs",
                           "packed_ref_logprobs", "rewards", "values",
                           "prompt_mask", "seq_no_eos_mask")),
    ]


class TestDFG:

    def test_ppo_graph_structure(self):
        g = DFG(ppo_nodes())
        gen = g.find("actor_gen")
        assert gen.is_src and not gen.is_dst
        assert {c.name for c in gen.children} == {
            "rew_inf", "ref_inf", "critic_inf", "actor_train", "critic_train"}
        at = g.find("actor_train")
        assert at.is_dst
        assert {p.name for p in at.parents} == {
            "actor_gen", "rew_inf", "ref_inf", "critic_inf"}
        assert set(g.dataset_keys) == {"packed_prompts"}
        assert {n.name for n in g.sinks} == {"actor_train", "critic_train"}
        # actor_gen is not the last actor-role MFC; actor_train is.
        assert not gen.is_dst_of_model_role
        assert at.is_dst_of_model_role

    def test_topological_order(self):
        g = DFG(ppo_nodes())
        order = [n.name for n in g.topological_order()]
        assert order.index("actor_gen") < order.index("rew_inf")
        assert order.index("rew_inf") < order.index("actor_train")

    def test_topological_levels(self):
        g = DFG(ppo_nodes())
        levels = [{n.name for n in lvl} for lvl in g.topological_levels()]
        assert levels[0] == {"actor_gen"}
        # the three inference MFCs are mutually independent: one level
        assert levels[1] == {"rew_inf", "ref_inf", "critic_inf"}
        assert levels[2] == {"actor_train", "critic_train"}
        # levels partition the node set and respect every edge
        flat = [n for lvl in g.topological_levels() for n in lvl]
        assert {n.name for n in flat} == {n.name for n in g.nodes}
        depth = {n.name: i for i, lvl in
                 enumerate(g.topological_levels()) for n in lvl}
        for n in g.nodes:
            for p in n.parents:
                assert depth[p.name] < depth[n.name]

    def test_single_node_graph(self):
        sft = MFCDef(name="trainDefault", n_seqs=8,
                     interface_type=ModelInterfaceType.TRAIN_STEP,
                     interface_impl=ModelInterfaceAbstraction("sft"),
                     model_name="default",
                     input_keys=("packed_input_ids", "prompt_mask"))
        g = DFG([sft])
        assert g.find("trainDefault").is_src and g.find("trainDefault").is_dst
        assert set(g.dataset_keys) == {"packed_input_ids", "prompt_mask"}

    def test_duplicate_names_rejected(self):
        n = ppo_nodes()
        n[1] = MFCDef(name="actor_gen", n_seqs=1,
                      interface_type=ModelInterfaceType.INFERENCE,
                      interface_impl=ModelInterfaceAbstraction("x"),
                      model_name="y")
        with pytest.raises(ValueError):
            DFG(n)

    def test_hooks(self):
        nodes = ppo_nodes()
        g = DFG(nodes)
        at = g.find("actor_train")
        at.add_pre_hook(ParamReallocHook(source=nodes[0].model_name))
        at.add_post_hook(OffloadHook())
        assert len(at._pre_hooks) == 1 and len(at._post_hooks) == 1
        with pytest.raises(ValueError):
            at.add_pre_hook(OffloadHook())
