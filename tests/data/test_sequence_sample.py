"""SequenceSample gather/split round-trips, mirroring reference
``tests/data/test_sequence_gather_split.py`` (incl. nested seqlens and
dp splits 1..16)."""

import numpy as np
import pytest

from realhf_tpu.api.data import SequenceSample, SequenceSplitSpec


def make_sample(rng, n, nested=False):
    samples = []
    for i in range(n):
        if nested:
            # e.g. multiple responses per prompt
            lens = [int(rng.integers(2, 10)) for _ in range(int(rng.integers(1, 4)))]
            data = dict(packed_input_ids=rng.integers(
                0, 100, size=(sum(lens),)).astype(np.int32))
            s = SequenceSample(
                keys=["packed_input_ids"],
                trailing_shapes=dict(packed_input_ids=()),
                dtypes=dict(packed_input_ids=np.int32),
                ids=[i],
                seqlens=dict(packed_input_ids=[lens]),
                data=data)
        else:
            l = int(rng.integers(2, 20))
            s = SequenceSample.from_default(
                seqlens=[l], ids=[i],
                data=dict(
                    packed_input_ids=rng.integers(0, 100, size=(l,)).astype(np.int32),
                    rewards=rng.standard_normal((1,)).astype(np.float32),
                ))
        samples.append(s)
    return samples


class TestSequenceSample:

    def test_gather_unpack_roundtrip(self):
        rng = np.random.default_rng(0)
        samples = make_sample(rng, 8)
        batch = SequenceSample.gather(samples)
        assert batch.bs == 8
        back = batch.unpack()
        for a, b in zip(samples, back):
            assert a.ids == b.ids
            assert a.seqlens == b.seqlens
            for k in a.keys:
                np.testing.assert_array_equal(a.data[k], b.data[k])

    @pytest.mark.parametrize("dp", [1, 2, 3, 4, 8, 16])
    def test_split_balance_and_consistency(self, dp):
        rng = np.random.default_rng(1)
        batch = SequenceSample.gather(make_sample(rng, 32))
        parts = batch.split(dp)
        assert len(parts) == dp
        assert sum(p.bs for p in parts) == 32
        regather = SequenceSample.gather(parts)
        for k in batch.keys:
            np.testing.assert_array_equal(batch.data[k], regather.data[k])
        assert regather.ids == batch.ids

    def test_nested_seqlens(self):
        rng = np.random.default_rng(2)
        batch = SequenceSample.gather(make_sample(rng, 16, nested=True))
        parts = batch.split(4)
        regather = SequenceSample.gather(parts)
        np.testing.assert_array_equal(
            batch.data["packed_input_ids"], regather.data["packed_input_ids"])
        assert regather.seqlens == batch.seqlens

    def test_meta_and_update(self):
        rng = np.random.default_rng(3)
        batch = SequenceSample.gather(make_sample(rng, 4))
        meta = batch.meta()
        assert meta.data is None
        assert meta.ids == batch.ids
        # amend new key
        lens = [sum(l) for l in batch.seqlens["packed_input_ids"]]
        new = SequenceSample.from_default(
            seqlens=lens, ids=batch.ids,
            data=dict(seq_no_eos_mask=np.zeros(4, dtype=np.bool_)))
        batch.update_(new)
        assert "seq_no_eos_mask" in batch.keys

    def test_remap_and_select(self):
        rng = np.random.default_rng(4)
        batch = SequenceSample.gather(make_sample(rng, 4))
        sel = batch.select(["rewards"])
        assert sel.keys == {"rewards"}
        batch.remap_keys_({"packed_input_ids": "packed_prompts"})
        assert "packed_prompts" in batch.keys
        assert "packed_input_ids" not in batch.keys

    def test_validation_errors(self):
        with pytest.raises(ValueError):
            SequenceSample(
                keys=["a"], trailing_shapes=dict(a=()), dtypes=dict(a=np.int32),
                ids=[0, 0], seqlens=dict(a=[[1], [1]]),
                data=dict(a=np.zeros(2, dtype=np.int32)))
        with pytest.raises(ValueError):
            SequenceSample(
                keys=["a"], trailing_shapes=dict(a=()), dtypes=dict(a=np.int32),
                ids=[0], seqlens=dict(a=[[3]]),
                data=dict(a=np.zeros(2, dtype=np.int32)))  # wrong shape

    def test_split_with_spec_uneven(self):
        rng = np.random.default_rng(5)
        batch = SequenceSample.gather(make_sample(rng, 6))
        parts = batch.split_with_spec(SequenceSplitSpec([(0, 1), (1, 6)]))
        assert parts[0].bs == 1 and parts[1].bs == 5
