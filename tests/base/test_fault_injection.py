"""Deterministic fault-injection harness: parsing, counting, once
semantics, and cross-relaunch state persistence."""

import pytest

from realhf_tpu.base.fault_injection import (
    FaultInjector,
    FaultSpec,
    parse_faults,
)


def test_parse_multi_spec():
    specs = parse_faults(
        "crash:model_worker/0:train_step:2;"
        "delay_reply:*:inference:1:2.5; drop_reply:w/1:*:3")
    assert specs == [
        FaultSpec("crash", "model_worker/0", "train_step", 2),
        FaultSpec("delay_reply", "*", "inference", 1, 2.5),
        FaultSpec("drop_reply", "w/1", "*", 3),
    ]


@pytest.mark.parametrize("bad", [
    "explode:w:h:1",        # unknown kind
    "crash:w:h",            # too few fields
    "crash:w:h:0",          # nth < 1
    "crash:w:h:1:2.0:extra",
])
def test_parse_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        parse_faults(bad)


def test_counts_fire_on_nth_matching_event_only():
    inj = FaultInjector(parse_faults("crash:model_worker/0:train_step:2"))
    # non-matching events advance nothing
    assert inj.on_event("model_worker/1", "train_step") is None
    assert inj.on_event("model_worker/0", "inference") is None
    assert inj.on_event("model_worker/0", "train_step") is None  # 1st
    fired = inj.on_event("model_worker/0", "train_step")         # 2nd
    assert fired is not None and fired.kind == "crash"
    # once: never again
    assert inj.on_event("model_worker/0", "train_step") is None


def test_wildcards_and_independent_counters():
    inj = FaultInjector(parse_faults(
        "delay_reply:*:inference:1:0.5;drop_reply:*:train_step:1"))
    f1 = inj.on_event("w/3", "inference")
    assert f1.kind == "delay_reply" and f1.seconds == 0.5
    f2 = inj.on_event("w/9", "train_step")
    assert f2.kind == "drop_reply"


def test_state_file_survives_relaunch(tmp_path):
    state = str(tmp_path / "faults_state")
    spec = "crash:w/0:train_step:1"
    inj = FaultInjector(parse_faults(spec), state_path=state)
    assert inj.on_event("w/0", "train_step") is not None
    # a relaunched worker builds a fresh injector over the same state
    # file: the fault already fired, so it must not crash-loop
    inj2 = FaultInjector(parse_faults(spec), state_path=state)
    assert inj2.on_event("w/0", "train_step") is None


def test_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("REALHF_TPU_FAULTS", raising=False)
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("REALHF_TPU_FAULTS", "die:w/0:*:1")
    monkeypatch.setenv("REALHF_TPU_FAULTS_STATE",
                       str(tmp_path / "state"))
    inj = FaultInjector.from_env()
    assert inj is not None
    assert inj.on_event("w/0", "anything").kind == "die"


# ----------------------------------------------------------------------
# network chaos: net_drop / net_delay / partition (PR 7)
# ----------------------------------------------------------------------
from realhf_tpu.base.fault_injection import (  # noqa: E402
    NET_KINDS,
    NetChaos,
    default_net_chaos,
    set_net_chaos,
)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_parse_net_kinds():
    specs = parse_faults(
        "net_drop:gen_server/0:send.done:3;"
        "net_delay:*:recv:1:0.5;"
        "partition:gen_server/2:*:1:6.0")
    assert [s.kind for s in specs] == list(NET_KINDS)
    assert specs[2].seconds == 6.0


@pytest.mark.parametrize("bad,hint", [
    ("net_delay:w:*:1", "positive seconds"),
    ("net_delay:w:*:1:0", "positive seconds"),
    ("partition:w:*:1", "positive seconds"),
    ("partition:w:*:2:-1.0", "positive seconds"),
    ("net_drop:w:*:1:2.0", "takes no seconds"),
])
def test_net_spec_validation_is_actionable(bad, hint):
    with pytest.raises(ValueError, match=hint):
        parse_faults(bad)


def test_net_drop_fires_on_nth_then_never_again():
    chaos = NetChaos(parse_faults("net_drop:s0:send.done:2"),
                     clock=_Clock())
    assert chaos.check("s0", "send.done") is None        # 1st passes
    assert chaos.check("s0", "send.tokens") is None      # no match
    assert chaos.check("s0", "send.done") == "drop"      # 2nd: fires
    assert chaos.check("s0", "send.done") is None        # one-shot
    assert chaos.stats["dropped"] == 1


def test_net_delay_sleeps_inline():
    clock = _Clock()
    slept = []
    chaos = NetChaos(parse_faults("net_delay:s0:recv:1:0.7"),
                     clock=clock, sleep=slept.append)
    assert chaos.check("s0", "recv") is None
    assert slept == [0.7]
    assert chaos.stats["delayed"] == 1


def test_partition_window_drops_everything_then_heals():
    clock = _Clock()
    chaos = NetChaos(parse_faults("partition:s1:*:1:5.0"),
                     clock=clock)
    # the opening event itself is dropped, and so is all of s1's
    # traffic inside the window, on every channel
    assert chaos.check("s1", "send.done") == "drop"
    assert chaos.partitioned("s1")
    assert chaos.check("s1", "recv") == "drop"
    assert chaos.check("s1", "send.tokens") == "drop"
    # other workers are unaffected
    assert chaos.check("s0", "send.done") is None
    assert not chaos.partitioned("s0")
    clock.advance(5.1)  # window closes
    assert not chaos.partitioned("s1")
    assert chaos.check("s1", "send.done") is None


def test_open_partition_programmatic():
    clock = _Clock()
    chaos = NetChaos([], clock=clock)
    chaos.open_partition("s2", 2.0)
    assert chaos.partitioned("s2")
    assert chaos.check("s2", "recv") == "drop"
    clock.advance(2.5)
    assert not chaos.partitioned("s2")


def test_net_kinds_split_between_injector_and_chaos(monkeypatch):
    """FaultInjector.from_env must NOT consume net_* specs (they
    execute at the wire shims), and NetChaos.from_env takes ONLY
    them."""
    monkeypatch.setenv(
        "REALHF_TPU_FAULTS",
        "crash:w0:train_step:1;net_drop:w0:send.done:1")
    inj = FaultInjector.from_env()
    assert [s.kind for s in inj.specs] == ["crash"]
    chaos = NetChaos.from_env()
    assert [s.kind for s in chaos._inj.specs] == ["net_drop"]
    # a handler-side event stream never trips the net spec
    assert inj.on_event("w0", "send.done") is None
    monkeypatch.setenv("REALHF_TPU_FAULTS", "crash:w0:train_step:1")
    assert NetChaos.from_env() is None


def test_net_state_file_dedup_across_relaunch(tmp_path):
    """Cross-relaunch once-semantics cover the net_* kinds: a
    recovered process must not re-drop the same message."""
    state = str(tmp_path / "faults_state")
    chaos = NetChaos(parse_faults("net_drop:s0:send.done:1"),
                     state_path=state, clock=_Clock())
    assert chaos.check("s0", "send.done") == "drop"
    # "relaunch": fresh NetChaos over the same state file
    chaos2 = NetChaos(parse_faults("net_drop:s0:send.done:1"),
                      state_path=state, clock=_Clock())
    assert chaos2.check("s0", "send.done") is None
    assert chaos2.stats["dropped"] == 0


def test_default_net_chaos_singleton(monkeypatch):
    prev = set_net_chaos(None)
    try:
        assert default_net_chaos() is None
        mine = NetChaos([], clock=_Clock())
        set_net_chaos(mine)
        assert default_net_chaos() is mine
    finally:
        set_net_chaos(prev)
