"""Deterministic fault-injection harness: parsing, counting, once
semantics, and cross-relaunch state persistence."""

import pytest

from realhf_tpu.base.fault_injection import (
    FaultInjector,
    FaultSpec,
    parse_faults,
)


def test_parse_multi_spec():
    specs = parse_faults(
        "crash:model_worker/0:train_step:2;"
        "delay_reply:*:inference:1:2.5; drop_reply:w/1:*:3")
    assert specs == [
        FaultSpec("crash", "model_worker/0", "train_step", 2),
        FaultSpec("delay_reply", "*", "inference", 1, 2.5),
        FaultSpec("drop_reply", "w/1", "*", 3),
    ]


@pytest.mark.parametrize("bad", [
    "explode:w:h:1",        # unknown kind
    "crash:w:h",            # too few fields
    "crash:w:h:0",          # nth < 1
    "crash:w:h:1:2.0:extra",
])
def test_parse_rejects_bad_specs(bad):
    with pytest.raises(ValueError):
        parse_faults(bad)


def test_counts_fire_on_nth_matching_event_only():
    inj = FaultInjector(parse_faults("crash:model_worker/0:train_step:2"))
    # non-matching events advance nothing
    assert inj.on_event("model_worker/1", "train_step") is None
    assert inj.on_event("model_worker/0", "inference") is None
    assert inj.on_event("model_worker/0", "train_step") is None  # 1st
    fired = inj.on_event("model_worker/0", "train_step")         # 2nd
    assert fired is not None and fired.kind == "crash"
    # once: never again
    assert inj.on_event("model_worker/0", "train_step") is None


def test_wildcards_and_independent_counters():
    inj = FaultInjector(parse_faults(
        "delay_reply:*:inference:1:0.5;drop_reply:*:train_step:1"))
    f1 = inj.on_event("w/3", "inference")
    assert f1.kind == "delay_reply" and f1.seconds == 0.5
    f2 = inj.on_event("w/9", "train_step")
    assert f2.kind == "drop_reply"


def test_state_file_survives_relaunch(tmp_path):
    state = str(tmp_path / "faults_state")
    spec = "crash:w/0:train_step:1"
    inj = FaultInjector(parse_faults(spec), state_path=state)
    assert inj.on_event("w/0", "train_step") is not None
    # a relaunched worker builds a fresh injector over the same state
    # file: the fault already fired, so it must not crash-loop
    inj2 = FaultInjector(parse_faults(spec), state_path=state)
    assert inj2.on_event("w/0", "train_step") is None


def test_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("REALHF_TPU_FAULTS", raising=False)
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("REALHF_TPU_FAULTS", "die:w/0:*:1")
    monkeypatch.setenv("REALHF_TPU_FAULTS_STATE",
                       str(tmp_path / "state"))
    inj = FaultInjector.from_env()
    assert inj is not None
    assert inj.on_event("w/0", "anything").kind == "die"
