"""The CI collection gate: a broken import must fail the check, not
silently shrink the suite."""

import importlib.util
import os
import textwrap


def _load_check_collect():
    path = os.path.join(os.path.dirname(__file__), "..", "..",
                        "scripts", "check_collect.py")
    spec = importlib.util.spec_from_file_location("check_collect", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_detects_import_error(tmp_path):
    mod = _load_check_collect()
    d = tmp_path / "suite"
    d.mkdir()
    (d / "test_good.py").write_text("def test_ok():\n    assert True\n")
    (d / "test_broken.py").write_text(textwrap.dedent("""
        import definitely_not_a_module_xyz  # noqa: F401

        def test_never_collects():
            assert True
    """))
    ok, report = mod.check_collection([str(d)], cwd=str(tmp_path))
    assert not ok
    assert "test_broken.py" in report


def test_required_dirs_gate(tmp_path):
    """The default run fails when a registered suite directory (e.g.
    tests/serving) collects no tests -- a renamed/emptied suite must
    not vanish from CI silently."""
    mod = _load_check_collect()
    t = tmp_path / "tests"
    for d in mod.REQUIRED_DIRS:
        (t / os.path.basename(d)).mkdir(parents=True)
    for d in mod.REQUIRED_DIRS[:-1]:
        base = os.path.basename(d)
        # unique module names: same-named test files in sibling dirs
        # without __init__.py would themselves error collection
        (t / base / f"test_{base}.py").write_text(
            "def test_ok():\n    assert True\n")
    ok, report = mod.check_collection(None, cwd=str(tmp_path))
    assert not ok
    assert mod.REQUIRED_DIRS[-1] in report


def test_passes_clean_suite(tmp_path):
    mod = _load_check_collect()
    d = tmp_path / "suite"
    d.mkdir()
    (d / "test_good.py").write_text("def test_ok():\n    assert True\n")
    ok, report = mod.check_collection([str(d)], cwd=str(tmp_path))
    assert ok, report
    assert "1 tests" in report or "OK" in report
