"""name_resolve lease semantics: keepalive_ttl expiry, touch-based
renewal, get_subtree under concurrent add/delete, and fencing epochs
on re-registration -- on BOTH the in-memory and filesystem backends
(the serving fleet's registry runs on either)."""

import threading
import time

import pytest

from realhf_tpu.base import name_resolve
from realhf_tpu.base.name_resolve import (
    MemoryNameRecordRepository,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(params=["memory", "nfs"])
def repo_clock(request, tmp_path):
    """(repository, advance(dt)) pairs. The memory backend runs on a
    fake clock (exact expiry); NFS uses real file mtimes, so its
    `advance` sleeps wall-clock time and the TTLs below stay >= 0.3s
    to keep mtime granularity out of the picture."""
    if request.param == "memory":
        clk = FakeClock()
        yield MemoryNameRecordRepository(clock=clk), clk.advance
    else:
        repo = NfsNameRecordRepository(record_root=str(tmp_path))
        yield repo, time.sleep
        repo.reset()


def test_keepalive_ttl_expires(repo_clock):
    repo, advance = repo_clock
    repo.add("fleet/replicas/r0", "addr0", keepalive_ttl=0.4)
    assert repo.get("fleet/replicas/r0") == "addr0"
    advance(0.6)
    with pytest.raises(NameEntryNotFoundError):
        repo.get("fleet/replicas/r0")
    assert repo.find_subtree("fleet/replicas") == []
    assert repo.get_subtree("fleet/replicas") == []
    # an expired key is re-addable even without replace=True
    repo.add("fleet/replicas/r0", "addr1", keepalive_ttl=0.4)
    assert repo.get("fleet/replicas/r0") == "addr1"


def test_no_ttl_means_persistent(repo_clock):
    repo, advance = repo_clock
    repo.add("k", "v")
    advance(0.7)
    assert repo.get("k") == "v"


def test_touch_refreshes_lease(repo_clock):
    repo, advance = repo_clock
    repo.add("lease/r0", "v", keepalive_ttl=0.5)
    for _ in range(3):
        advance(0.3)
        repo.touch("lease/r0")  # keeps beating inside the ttl
    assert repo.get("lease/r0") == "v"  # 0.9s after add: still alive
    advance(0.7)  # stop touching: lease decays
    with pytest.raises(NameEntryNotFoundError):
        repo.touch("lease/r0")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("lease/r0")


def test_touch_missing_entry_raises(repo_clock):
    repo, _ = repo_clock
    with pytest.raises(NameEntryNotFoundError):
        repo.touch("never/registered")


def test_get_subtree_under_concurrent_add_delete(repo_clock):
    """Readers walking the subtree while writers add/delete must never
    crash and must only ever see values that were actually stored."""
    repo, _ = repo_clock
    valid = {f"v{i}" for i in range(8)}
    stop = threading.Event()
    errors = []

    def writer(i):
        try:
            while not stop.is_set():
                repo.add(f"sub/tree/k{i}", f"v{i}", replace=True)
                try:
                    repo.delete(f"sub/tree/k{i}")
                except NameEntryNotFoundError:
                    pass
        except Exception as e:  # noqa: BLE001 - fail the test below
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 1.0
    reads = 0
    while time.monotonic() < deadline:
        vals = repo.get_subtree("sub/tree")
        keys = repo.find_subtree("sub/tree")
        assert all(v in valid for v in vals), vals
        assert all(k.startswith("sub/tree/") for k in keys), keys
        reads += 1
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors, errors
    assert reads > 10


def test_register_with_epoch_bumps_across_expiry(repo_clock):
    """The fencing story: every (re-)registration returns a HIGHER
    epoch, and the counter survives lease expiry."""
    repo, advance = repo_clock
    e1 = repo.register_with_epoch("f/replicas/r0", "addr",
                                  epoch_name="f/epochs/r0",
                                  keepalive_ttl=0.4)
    assert e1 == 1
    # live re-registration (e.g. restart before expiry) also bumps
    e2 = repo.register_with_epoch("f/replicas/r0", "addr",
                                  epoch_name="f/epochs/r0",
                                  keepalive_ttl=0.4)
    assert e2 == 2
    advance(0.6)  # lease decays ...
    with pytest.raises(NameEntryNotFoundError):
        repo.get("f/replicas/r0")
    # ... but the epoch counter does not
    assert repo.get("f/epochs/r0") == "2"
    e3 = repo.register_with_epoch("f/replicas/r0", "addr2",
                                  epoch_name="f/epochs/r0",
                                  keepalive_ttl=0.4)
    assert e3 == 3
    assert repo.get("f/replicas/r0") == "addr2"


def test_register_with_epoch_callable_value(repo_clock):
    """The stored value may embed the epoch (one atomic read gives
    consumers a consistent (epoch, payload) pair)."""
    repo, _ = repo_clock
    e = repo.register_with_epoch("f/replicas/r1",
                                 lambda ep: f"{ep}:tcp://h:1",
                                 epoch_name="f/epochs/r1",
                                 keepalive_ttl=5.0)
    assert repo.get("f/replicas/r1") == f"{e}:tcp://h:1"


def test_module_level_touch_and_epoch(tmp_path, monkeypatch):
    """The module-level wrappers reach the default repository."""
    name_resolve.reconfigure("memory")
    try:
        e = name_resolve.register_with_epoch("m/k", "v",
                                             keepalive_ttl=10.0)
        assert e == 1
        name_resolve.touch("m/k")
        assert name_resolve.get("m/k") == "v"
    finally:
        name_resolve.reconfigure(None)  # back to the env default
