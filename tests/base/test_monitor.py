"""Profiling subsystem: per-MFC spans, trace dumps, memory stats
(reference model_worker.py:664-721 + base/monitor.py:375-427)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from realhf_tpu.base import constants, monitor


def test_mfc_profile_region_records_span():
    monitor.tmark_db().clear()
    with monitor.mfc_profile_region("actor_gen"):
        jnp.sum(jnp.ones((64, 64))).block_until_ready()
    s = monitor.tmark_db().summary()
    assert "mfc/actor_gen" in s and s["mfc/actor_gen"] > 0


def test_trace_dump(monkeypatch, tmp_path):
    monkeypatch.setattr(constants, "ROOT_DIR", str(tmp_path))
    constants.set_experiment_trial_names("montest", "t0")
    monkeypatch.setenv(monitor.DUMP_TRACE_ENV, "1")
    with monitor.mfc_profile_region("ref_inf"):
        jnp.dot(jnp.ones((128, 128)), jnp.ones((128, 128))) \
            .block_until_ready()
    d = monitor.trace_dir("ref_inf")
    # jax.profiler.trace wrote a tensorboard/perfetto event tree
    files = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert files, d


def test_device_memory_stats():
    st = monitor.device_memory_stats()
    assert set(st) == {"bytes_in_use", "peak_bytes_in_use",
                       "bytes_limit"}


def test_flop_formulas_positive():
    f = monitor.transformer_train_flops(
        n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2,
        head_dim=16, intermediate_dim=128, vocab_size=256,
        seqlens=[32, 16])
    assert f > 0
