"""Profiling subsystem: per-MFC spans, trace dumps, memory stats
(reference model_worker.py:664-721 + base/monitor.py:375-427)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from realhf_tpu.base import constants, monitor


def test_mfc_profile_region_records_span():
    monitor.tmark_db().clear()
    with monitor.mfc_profile_region("actor_gen"):
        jnp.sum(jnp.ones((64, 64))).block_until_ready()
    s = monitor.tmark_db().summary()
    assert "mfc/actor_gen" in s and s["mfc/actor_gen"] > 0


def test_trace_dump(monkeypatch, tmp_path):
    monkeypatch.setattr(constants, "ROOT_DIR", str(tmp_path))
    constants.set_experiment_trial_names("montest", "t0")
    monkeypatch.setenv(monitor.DUMP_TRACE_ENV, "1")
    with monitor.mfc_profile_region("ref_inf"):
        jnp.dot(jnp.ones((128, 128)), jnp.ones((128, 128))) \
            .block_until_ready()
    d = monitor.trace_dir("ref_inf")
    # jax.profiler.trace wrote a tensorboard/perfetto event tree
    files = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert files, d


def test_device_memory_stats():
    st = monitor.device_memory_stats()
    assert set(st) == {"bytes_in_use", "peak_bytes_in_use",
                       "bytes_limit"}


def test_flop_formulas_positive():
    f = monitor.transformer_train_flops(
        n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2,
        head_dim=16, intermediate_dim=128, vocab_size=256,
        seqlens=[32, 16])
    assert f > 0


def test_kernel_classification(tmp_path):
    """Chrome-trace kernel classification (reference
    kernelStatFromTrace, monitor.py:517-699) against a synthetic
    TPU-shaped trace: device tracks aggregated by category, host
    tracks ignored."""
    import gzip
    import json

    trace = {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "python host"}},
        {"ph": "X", "pid": 1, "tid": 0, "name": "fusion.12",
         "ts": 1000, "dur": 500},
        {"ph": "X", "pid": 1, "tid": 0, "name": "dot_general.3",
         "ts": 1500, "dur": 300},
        {"ph": "X", "pid": 1, "tid": 1, "name": "all-reduce.1",
         "ts": 1600, "dur": 200},
        {"ph": "X", "pid": 1, "tid": 0, "name": "copy.7",
         "ts": 1900, "dur": 100},
        {"ph": "X", "pid": 1, "tid": 0, "name": "weird-op",
         "ts": 2000, "dur": 50},
        # host event must be ignored
        {"ph": "X", "pid": 9, "tid": 0, "name": "fusion.fake",
         "ts": 0, "dur": 99999},
    ]}
    p = tmp_path / "host.trace.json.gz"
    with gzip.open(p, "wt") as f:
        json.dump(trace, f)

    stats = monitor.kernel_stats_from_trace(str(tmp_path))
    assert stats["compute"] == pytest.approx(800e-6)
    assert stats["comm"] == pytest.approx(200e-6)
    assert stats["mem"] == pytest.approx(100e-6)
    assert stats["misc"] == pytest.approx(50e-6)
    assert stats["total_busy"] == pytest.approx(1150e-6)
    assert stats["span"] == pytest.approx((2050 - 1000) * 1e-6)
