"""Retry/backoff policy: deterministic with injected rng + sleep."""

import random

import pytest

from realhf_tpu.base.retry import RetryPolicy, backoff_delays, retry_call


def test_backoff_growth_and_cap():
    pol = RetryPolicy(max_attempts=6, base_delay=1.0, factor=2.0,
                      max_delay=4.0, jitter=0.0)
    assert list(backoff_delays(pol)) == [1.0, 2.0, 4.0, 4.0, 4.0]


def test_backoff_jitter_bounds():
    pol = RetryPolicy(max_attempts=50, base_delay=1.0, factor=1.0,
                      max_delay=1.0, jitter=0.5)
    ds = list(backoff_delays(pol, rng=random.Random(0)))
    assert all(1.0 <= d <= 1.5 for d in ds)
    assert len(set(ds)) > 1  # actually jittered


def test_retry_call_succeeds_after_failures():
    calls = []
    slept = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("transient")
        return "ok"

    out = retry_call(flaky, RetryPolicy(max_attempts=4, base_delay=0.1,
                                        jitter=0.0),
                     sleep=slept.append)
    assert out == "ok"
    assert len(calls) == 3
    assert slept == [0.1, 0.2]


def test_retry_call_exhausts_and_raises_last():
    calls = []

    def always_fails():
        calls.append(1)
        raise TimeoutError("always")

    with pytest.raises(TimeoutError, match="always"):
        retry_call(always_fails,
                   RetryPolicy(max_attempts=3, base_delay=0.0,
                               jitter=0.0),
                   sleep=lambda _d: None)
    assert len(calls) == 3


def test_retry_call_non_matching_exception_propagates_immediately():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry_call(boom, RetryPolicy(max_attempts=5, base_delay=0.0),
                   retry_on=(TimeoutError,), sleep=lambda _d: None)
    assert len(calls) == 1


def test_retry_call_on_retry_hook():
    seen = []

    def flaky():
        if len(seen) < 1:
            raise TimeoutError("x")
        return 7

    assert retry_call(flaky, RetryPolicy(max_attempts=2, base_delay=0.0,
                                         jitter=0.0),
                      on_retry=lambda a, e: seen.append((a, str(e))),
                      sleep=lambda _d: None) == 7
    assert seen == [(1, "x")]


class FakeClock:
    """Deterministic monotonic clock advanced by fake sleeps."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def sleep(self, d):
        self.now += d


def test_max_elapsed_budget_abandons_remaining_attempts():
    """The total-deadline budget (ISSUE 4 satellite): stacked backoff
    must stop once spent-plus-next-sleep would overrun max_elapsed,
    surfacing the real failure instead of masking it for the full
    attempt count."""
    clock = FakeClock()
    calls = []

    def always_fails():
        calls.append(1)
        clock.now += 1.0  # each attempt itself costs 1s
        raise TimeoutError("worker gone")

    with pytest.raises(TimeoutError, match="worker gone"):
        retry_call(always_fails,
                   RetryPolicy(max_attempts=10, base_delay=4.0,
                               factor=1.0, jitter=0.0,
                               max_elapsed=7.0),
                   sleep=clock.sleep, clock=clock)
    # attempt(1s) + sleep(4s) + attempt(1s): the next 4s sleep would
    # hit 10s > 7s, so attempts 3..10 never run
    assert len(calls) == 2
    assert clock.now == pytest.approx(6.0)


def test_max_elapsed_none_keeps_attempt_bound():
    clock = FakeClock()
    calls = []

    def always_fails():
        calls.append(1)
        raise TimeoutError("x")

    with pytest.raises(TimeoutError):
        retry_call(always_fails,
                   RetryPolicy(max_attempts=3, base_delay=100.0,
                               jitter=0.0, max_elapsed=None),
                   sleep=clock.sleep, clock=clock)
    assert len(calls) == 3


def test_max_elapsed_generous_budget_does_not_interfere():
    clock = FakeClock()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("t")
        return "ok"

    assert retry_call(flaky,
                      RetryPolicy(max_attempts=5, base_delay=1.0,
                                  factor=1.0, jitter=0.0,
                                  max_elapsed=100.0),
                      sleep=clock.sleep, clock=clock) == "ok"
    assert len(calls) == 3
