"""Retry/backoff policy: deterministic with injected rng + sleep."""

import random

import pytest

from realhf_tpu.base.retry import RetryPolicy, backoff_delays, retry_call


def test_backoff_growth_and_cap():
    pol = RetryPolicy(max_attempts=6, base_delay=1.0, factor=2.0,
                      max_delay=4.0, jitter=0.0)
    assert list(backoff_delays(pol)) == [1.0, 2.0, 4.0, 4.0, 4.0]


def test_backoff_jitter_bounds():
    pol = RetryPolicy(max_attempts=50, base_delay=1.0, factor=1.0,
                      max_delay=1.0, jitter=0.5)
    ds = list(backoff_delays(pol, rng=random.Random(0)))
    assert all(1.0 <= d <= 1.5 for d in ds)
    assert len(set(ds)) > 1  # actually jittered


def test_retry_call_succeeds_after_failures():
    calls = []
    slept = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("transient")
        return "ok"

    out = retry_call(flaky, RetryPolicy(max_attempts=4, base_delay=0.1,
                                        jitter=0.0),
                     sleep=slept.append)
    assert out == "ok"
    assert len(calls) == 3
    assert slept == [0.1, 0.2]


def test_retry_call_exhausts_and_raises_last():
    calls = []

    def always_fails():
        calls.append(1)
        raise TimeoutError("always")

    with pytest.raises(TimeoutError, match="always"):
        retry_call(always_fails,
                   RetryPolicy(max_attempts=3, base_delay=0.0,
                               jitter=0.0),
                   sleep=lambda _d: None)
    assert len(calls) == 3


def test_retry_call_non_matching_exception_propagates_immediately():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry_call(boom, RetryPolicy(max_attempts=5, base_delay=0.0),
                   retry_on=(TimeoutError,), sleep=lambda _d: None)
    assert len(calls) == 1


def test_retry_call_on_retry_hook():
    seen = []

    def flaky():
        if len(seen) < 1:
            raise TimeoutError("x")
        return 7

    assert retry_call(flaky, RetryPolicy(max_attempts=2, base_delay=0.0,
                                         jitter=0.0),
                      on_retry=lambda a, e: seen.append((a, str(e))),
                      sleep=lambda _d: None) == 7
    assert seen == [(1, "x")]
