"""Retry/backoff policy: deterministic with injected rng + sleep."""

import random

import pytest

from realhf_tpu.base.retry import RetryPolicy, backoff_delays, retry_call


def test_backoff_growth_and_cap():
    pol = RetryPolicy(max_attempts=6, base_delay=1.0, factor=2.0,
                      max_delay=4.0, jitter=0.0)
    assert list(backoff_delays(pol)) == [1.0, 2.0, 4.0, 4.0, 4.0]


def test_backoff_jitter_bounds():
    pol = RetryPolicy(max_attempts=50, base_delay=1.0, factor=1.0,
                      max_delay=1.0, jitter=0.5)
    ds = list(backoff_delays(pol, rng=random.Random(0)))
    assert all(1.0 <= d <= 1.5 for d in ds)
    assert len(set(ds)) > 1  # actually jittered


def test_retry_call_succeeds_after_failures():
    calls = []
    slept = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("transient")
        return "ok"

    out = retry_call(flaky, RetryPolicy(max_attempts=4, base_delay=0.1,
                                        jitter=0.0),
                     sleep=slept.append)
    assert out == "ok"
    assert len(calls) == 3
    assert slept == [0.1, 0.2]


def test_retry_call_exhausts_and_raises_last():
    calls = []

    def always_fails():
        calls.append(1)
        raise TimeoutError("always")

    with pytest.raises(TimeoutError, match="always"):
        retry_call(always_fails,
                   RetryPolicy(max_attempts=3, base_delay=0.0,
                               jitter=0.0),
                   sleep=lambda _d: None)
    assert len(calls) == 3


def test_retry_call_non_matching_exception_propagates_immediately():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry_call(boom, RetryPolicy(max_attempts=5, base_delay=0.0),
                   retry_on=(TimeoutError,), sleep=lambda _d: None)
    assert len(calls) == 1


def test_retry_call_on_retry_hook():
    seen = []

    def flaky():
        if len(seen) < 1:
            raise TimeoutError("x")
        return 7

    assert retry_call(flaky, RetryPolicy(max_attempts=2, base_delay=0.0,
                                         jitter=0.0),
                      on_retry=lambda a, e: seen.append((a, str(e))),
                      sleep=lambda _d: None) == 7
    assert seen == [(1, "x")]


class FakeClock:
    """Deterministic monotonic clock advanced by fake sleeps."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def sleep(self, d):
        self.now += d


def test_max_elapsed_budget_abandons_remaining_attempts():
    """The total-deadline budget (ISSUE 4 satellite): stacked backoff
    must stop once spent-plus-next-sleep would overrun max_elapsed,
    surfacing the real failure instead of masking it for the full
    attempt count."""
    clock = FakeClock()
    calls = []

    def always_fails():
        calls.append(1)
        clock.now += 1.0  # each attempt itself costs 1s
        raise TimeoutError("worker gone")

    with pytest.raises(TimeoutError, match="worker gone"):
        retry_call(always_fails,
                   RetryPolicy(max_attempts=10, base_delay=4.0,
                               factor=1.0, jitter=0.0,
                               max_elapsed=7.0),
                   sleep=clock.sleep, clock=clock)
    # attempt(1s) + sleep(4s) + attempt(1s): the next 4s sleep would
    # hit 10s > 7s, so attempts 3..10 never run
    assert len(calls) == 2
    assert clock.now == pytest.approx(6.0)


def test_max_elapsed_none_keeps_attempt_bound():
    clock = FakeClock()
    calls = []

    def always_fails():
        calls.append(1)
        raise TimeoutError("x")

    with pytest.raises(TimeoutError):
        retry_call(always_fails,
                   RetryPolicy(max_attempts=3, base_delay=100.0,
                               jitter=0.0, max_elapsed=None),
                   sleep=clock.sleep, clock=clock)
    assert len(calls) == 3


def test_max_elapsed_generous_budget_does_not_interfere():
    clock = FakeClock()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("t")
        return "ok"

    assert retry_call(flaky,
                      RetryPolicy(max_attempts=5, base_delay=1.0,
                                  factor=1.0, jitter=0.0,
                                  max_elapsed=100.0),
                      sleep=clock.sleep, clock=clock) == "ok"
    assert len(calls) == 3


# ----------------------------------------------------------------------
# hedged(): first-success-wins with cooperative cancellation
# ----------------------------------------------------------------------
import threading
import time as _time

from realhf_tpu.base.retry import HedgeAttempt, hedged  # noqa: E402


def test_hedged_primary_wins_no_hedge_launched():
    seen = []

    def call(att: HedgeAttempt):
        seen.append(att.index)
        return f"ok-{att.index}"

    assert hedged(call, delay=10.0, max_hedges=2) == "ok-0"
    assert seen == [0]  # fast primary: the stagger never elapsed


def test_hedged_slow_primary_loses_and_is_cancelled():
    events = {}

    def call(att: HedgeAttempt):
        events[att.index] = att
        if att.index == 0:
            # slow primary: parks until cancelled by the winner
            att.cancelled.wait(timeout=30.0)
            raise TimeoutError("cancelled")
        return "hedge-won"

    t0 = _time.monotonic()
    assert hedged(call, delay=0.05, max_hedges=1) == "hedge-won"
    assert _time.monotonic() - t0 < 5.0
    # the loser's cancellation event fired
    assert events[0].cancelled.wait(timeout=5.0)


def test_hedged_failure_triggers_immediate_next_attempt():
    order = []

    def call(att: HedgeAttempt):
        order.append((att.index, _time.monotonic()))
        if att.index == 0:
            raise ConnectionError("replica down")
        return "ok"

    t0 = _time.monotonic()
    assert hedged(call, delay=30.0, max_hedges=1) == "ok"
    # the hedge launched on FAILURE, not after the 30s stagger
    assert _time.monotonic() - t0 < 5.0
    assert [i for i, _ in order] == [0, 1]


def test_hedged_all_fail_raises_last():
    def call(att: HedgeAttempt):
        raise ValueError(f"boom-{att.index}")

    with pytest.raises(ValueError, match="boom-"):
        hedged(call, delay=0.01, max_hedges=2)


def test_hedged_max_elapsed_deadline_cancels_everyone():
    attempts = []

    def call(att: HedgeAttempt):
        attempts.append(att)
        # deadline propagated: every attempt sees the SAME absolute
        # total budget
        assert att.deadline is not None
        att.cancelled.wait(timeout=30.0)
        raise TimeoutError("cancelled")

    t0 = _time.monotonic()
    with pytest.raises(TimeoutError):
        hedged(call, delay=0.05, max_hedges=1, max_elapsed=0.3)
    assert _time.monotonic() - t0 < 5.0
    assert len(attempts) == 2  # primary + one hedge, both launched
    for att in attempts:
        assert att.cancelled.wait(timeout=5.0)


def test_hedged_non_retryable_exception_propagates():
    def call(att: HedgeAttempt):
        raise KeyError("not in retry_on")

    with pytest.raises(KeyError):
        hedged(call, delay=0.01, max_hedges=3,
               retry_on=(ConnectionError,))


def test_hedged_rejects_negative_delay():
    with pytest.raises(ValueError):
        hedged(lambda att: 1, delay=-1.0)
