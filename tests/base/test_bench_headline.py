"""bench.py cold-window contract: the PPO headline is recorded FIRST,
the payload file is flushed incrementally (partial file on disk before
any non-headline phase runs), and --headline-only prints a valid
headline JSON line without touching the non-headline phases."""

import json
import os
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                    "..", ".."))


@pytest.fixture()
def bench_mod(monkeypatch, tmp_path):
    monkeypatch.syspath_prepend(REPO)
    monkeypatch.setenv("REALHF_BENCH_FORCE_CPU", "1")
    monkeypatch.setenv("REALHF_TPU_COMPILE_CACHE", "0")
    monkeypatch.setenv("REALHF_BENCH_PAYLOAD",
                       str(tmp_path / "BENCH_partial.json"))
    import bench
    return bench


def _headline():
    return {"metric": "ppo_tokens_per_sec_per_chip", "value": 123.4,
            "unit": "tokens/s", "vs_baseline": 0.99}


def _read_payload():
    with open(os.environ["REALHF_BENCH_PAYLOAD"]) as f:
        return json.load(f)


def test_headline_only_prints_and_skips_nonheadline_phases(
        bench_mod, monkeypatch, capsys):
    ran = []
    monkeypatch.setattr(
        bench_mod, "bench_ppo",
        lambda on_tpu: (_headline(), {"ppo_step_time_s": 1.0},
                        object()))

    def forbidden(name):
        def _f(*a, **k):
            ran.append(name)
            raise AssertionError(f"{name} must not run in "
                                 "--headline-only mode")
        return _f

    monkeypatch.setattr(bench_mod, "bench_sft", forbidden("sft"))
    monkeypatch.setattr(bench_mod, "_reshard_metrics",
                        forbidden("reshard"))
    monkeypatch.setattr(bench_mod, "_bench_pipeline_schedules",
                        forbidden("pipeline"))
    monkeypatch.setattr(bench_mod, "_bench_serving_hotpath",
                        forbidden("serving"))
    monkeypatch.setattr(bench_mod, "_bench_kv_pool",
                        forbidden("kv_pool"))
    monkeypatch.setattr(bench_mod, "_bench_async",
                        forbidden("async"))
    monkeypatch.setattr(bench_mod, "_bench_agentic",
                        forbidden("agentic"))
    monkeypatch.setattr(bench_mod, "_bench_trace_report",
                        forbidden("trace_report"))
    monkeypatch.setattr(sys, "argv", ["bench.py", "--headline-only"])
    bench_mod.main()
    assert ran == []

    out_lines = [l for l in capsys.readouterr().out.splitlines()
                 if l.startswith("{")]
    assert len(out_lines) == 1
    rec = json.loads(out_lines[0])
    assert rec["metric"] == "ppo_tokens_per_sec_per_chip"
    assert rec["extra"]["headline_only"] is True
    assert rec["extra"]["time_to_first_headline_s"] >= 0

    payload = _read_payload()
    assert payload["phases_done"] == ["ppo_headline",
                                      "kernel_disposition"]
    assert "kernel_disposition" in payload["extra"]
    assert "sft_mfu" not in payload["extra"]


def test_partial_payload_flushed_before_each_nonheadline_phase(
        bench_mod, monkeypatch, capsys):
    """The full run flushes after EVERY phase; each later phase can
    observe the previous flush on disk -- a window dying mid-phase
    always leaves the newest complete record."""
    seen_phases = {}

    monkeypatch.setattr(
        bench_mod, "bench_ppo",
        lambda on_tpu: (_headline(), {"ppo_step_time_s": 1.0},
                        object()))

    def spy(name, ret=None, mutate=None):
        def _f(*a, **k):
            seen_phases[name] = _read_payload()["phases_done"]
            if mutate is not None:
                mutate(*a)
            return ret
        return _f

    monkeypatch.setattr(bench_mod, "_bench_pipeline_schedules",
                        spy("pipeline", ret={"stages": 4}))
    monkeypatch.setattr(bench_mod, "_bench_serving_hotpath",
                        spy("serving", ret={"shared": {}}))
    monkeypatch.setattr(bench_mod, "_bench_kv_pool",
                        spy("kv_pool",
                            ret={"max_concurrent_improvement": 2.5}))
    monkeypatch.setattr(bench_mod, "_bench_async",
                        spy("async", ret={"async_speedup": 1.1}))
    monkeypatch.setattr(bench_mod, "_bench_agentic",
                        spy("agentic", ret={"serving": {}}))
    monkeypatch.setattr(bench_mod, "_bench_trace_report",
                        spy("trace_report",
                            ret={"n_steps": 2, "goodput": 0.8}))
    monkeypatch.setattr(
        bench_mod, "_reshard_metrics",
        spy("reshard",
            mutate=lambda runner, extra: extra.update(
                reshard_latency_s=0.1)))
    monkeypatch.setattr(bench_mod, "bench_sft",
                        spy("sft", ret={"sft_mfu": 0.5}))
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench_mod.main()

    # headline (and disposition) were on disk before the first
    # non-headline phase ran
    assert seen_phases["pipeline"] == ["ppo_headline",
                                       "kernel_disposition"]
    assert seen_phases["serving"][-1] == "pipeline_schedules"
    assert seen_phases["kv_pool"][-1] == "serving_bench"
    assert seen_phases["async"][-1] == "kv_pool_bench"
    assert seen_phases["agentic"][-1] == "async_bench"
    assert seen_phases["trace_report"][-1] == "agentic_bench"
    assert seen_phases["reshard"][-1] == "trace_report"
    assert seen_phases["sft"][-1] == "reshard"

    final = _read_payload()
    assert final["phases_done"] == [
        "ppo_headline", "kernel_disposition", "pipeline_schedules",
        "serving_bench", "kv_pool_bench", "async_bench",
        "agentic_bench", "trace_report", "reshard", "sft",
        "overhead_probe"]
    assert final["extra"]["pipeline_schedule_bench"] == {"stages": 4}
    assert final["extra"]["serving_bench"] == {"shared": {}}
    assert final["extra"]["kv_pool_bench"] == {
        "max_concurrent_improvement": 2.5}
    assert final["extra"]["async_bench"] == {"async_speedup": 1.1}
    assert final["extra"]["agentic_bench"] == {"serving": {}}
    assert final["extra"]["trace_report"] == {"n_steps": 2,
                                              "goodput": 0.8}
    assert final["extra"]["sft_mfu"] == 0.5
    # final stdout line is the full headline record
    out_lines = [l for l in capsys.readouterr().out.splitlines()
                 if l.startswith("{")]
    rec = json.loads(out_lines[-1])
    assert rec["extra"]["reshard_latency_s"] == 0.1


def test_nonheadline_phase_failure_never_voids_headline(
        bench_mod, monkeypatch, capsys):
    monkeypatch.setattr(
        bench_mod, "bench_ppo",
        lambda on_tpu: (_headline(), {"ppo_step_time_s": 1.0},
                        object()))

    def boom(*a, **k):
        raise RuntimeError("window died")

    monkeypatch.setattr(bench_mod, "_bench_pipeline_schedules", boom)
    monkeypatch.setattr(bench_mod, "_bench_serving_hotpath",
                        lambda: {"shared": {}})
    monkeypatch.setattr(bench_mod, "_bench_kv_pool",
                        lambda: {"ok": True})
    monkeypatch.setattr(bench_mod, "_bench_async",
                        lambda: {"async_speedup": 1.0})
    monkeypatch.setattr(bench_mod, "_bench_agentic",
                        lambda: {"serving": {}})
    # the trace_report phase honors the same property: its failure
    # degrades to an error note, never voids the headline
    monkeypatch.setattr(bench_mod, "_bench_trace_report", boom)
    monkeypatch.setattr(bench_mod, "bench_sft",
                        lambda on_tpu: {"sft_mfu": 0.5})
    monkeypatch.setattr(bench_mod, "_reshard_metrics",
                        lambda runner, extra: None)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench_mod.main()
    payload = _read_payload()
    assert "error" in payload["extra"]["pipeline_schedule_bench"]
    assert "error" in payload["extra"]["trace_report"]
    assert payload["phases_done"][-1] == "overhead_probe"


def test_bench_pipeline_script_payload_shape(monkeypatch):
    """The schedule micro-bench payload: exact analytics plus measured
    timings (run in-process at the smallest shape; the S=4/M=4
    acceptance geometry runs from bench.py and in the e2e above the
    tier)."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_pipeline

    out = bench_pipeline.run(stages=2, microbatches=2, layers=2,
                             hidden=32, seqlen=32, reps=1)
    assert out["ticks_per_pass"] == 3 and out["train_ticks"] == 6
    assert out["analytic_bubble_fraction"] == pytest.approx(1 / 3,
                                                            abs=1e-4)
    assert out["schedules"]["gpipe"]["computed_stage_steps"] == 12
    assert out["schedules"]["1f1b"]["computed_stage_steps"] == 8
    for sched in ("gpipe", "1f1b"):
        assert out["schedules"][sched]["step_s"] > 0
    assert -1.0 < out["measured_bubble_fraction"] < 1.0
    json.dumps(out)  # payload-serializable
