"""Redis name_resolve backend against an in-memory fake client
(reference base/name_resolve.py:357; no redis server in CI)."""

import fnmatch
import time

import pytest

from realhf_tpu.base.name_resolve import (
    NameEntryExistsError,
    NameEntryNotFoundError,
    RedisNameRecordRepository,
    make_repository,
)


class FakeRedis:
    def __init__(self):
        self.store = {}
        self.ttls = {}

    def get(self, k):
        return self.store.get(k)

    def set(self, k, v, ex=None, nx=False):
        if nx and k in self.store:
            return None
        self.store[k] = v
        if ex is not None:
            self.ttls[k] = ex
        return True

    def delete(self, k):
        self.ttls.pop(k, None)
        return 1 if self.store.pop(k, None) is not None else 0

    def scan_iter(self, match="*"):
        return [k for k in self.store if fnmatch.fnmatch(k, match)]

    def expire(self, k, ttl):
        if k in self.store:
            self.ttls[k] = ttl


@pytest.fixture
def repo():
    fake = FakeRedis()
    r = RedisNameRecordRepository(client=fake)
    yield r, fake
    r.reset()


def test_add_get_delete(repo):
    r, fake = repo
    r.add("a/b/c", "v1")
    assert r.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        r.add("a/b/c", "v2")
    r.add("a/b/c", "v2", replace=True)
    assert r.get("a/b/c") == "v2"
    r.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        r.get("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        r.delete("a/b/c")


def test_subtree_and_reset(repo):
    r, fake = repo
    r.add("root/x/1", "a")
    r.add("root/x/2", "b")
    r.add("other/y", "c")
    assert r.find_subtree("root/x") == ["root/x/1", "root/x/2"]
    assert r.get_subtree("root/x") == ["a", "b"]
    r.clear_subtree("root")
    assert r.find_subtree("root") == []
    assert r.get("other/y") == "c"
    r.reset()  # delete_on_exit entries removed
    assert fake.get("other/y") is None


def test_keepalive_ttl_refresh(repo):
    r, fake = repo
    r.KEEPALIVE_POLL_FREQUENCY = 0.05
    r.add("live/worker", "up", keepalive_ttl=7.0)
    assert fake.ttls["live/worker"] == 7
    fake.ttls["live/worker"] = 0  # simulate decay
    deadline = time.monotonic() + 3
    while fake.ttls["live/worker"] == 0:
        assert time.monotonic() < deadline, "keepalive never refreshed"
        time.sleep(0.05)
    assert fake.ttls["live/worker"] == 7


def test_make_repository_without_redis_package():
    with pytest.raises(RuntimeError, match="redis"):
        make_repository("redis")
