"""Extended crash-recovery info: versioned round-trip, buffer +
dataloader state, and corrupt/truncated-file tolerance."""

import pickle

import numpy as np
import pytest

from realhf_tpu.api.data import SequenceSample
from realhf_tpu.base import constants, recover
from realhf_tpu.system.buffer import SequenceBuffer


@pytest.fixture(autouse=True)
def _trial_names():
    constants.set_experiment_trial_names("rectest", "t0")
    yield


def _meta(ids):
    return SequenceSample(
        keys=["packed_prompts"],
        trailing_shapes={"packed_prompts": ()},
        dtypes={"packed_prompts": np.int32},
        ids=list(ids),
        seqlens={"packed_prompts": [[4] for _ in ids]})


def _filled_buffer():
    buf = SequenceBuffer(["gen", "train"], capacity=4)
    bid0 = buf.put_batch(_meta(["a", "b"]), "model_worker/0", 0, False)
    bid1 = buf.put_batch(_meta(["c", "d"]), "model_worker/0", 0, True)
    buf.mark_dispatched(bid0, "gen")
    buf.amend_batch(bid0, None, "model_worker/0", "gen")  # completed
    buf.mark_dispatched(bid0, "train")                    # in flight
    return buf, bid0, bid1


def test_recover_info_v2_round_trip(tmp_path, monkeypatch):
    buf, bid0, _ = _filled_buffer()
    info = recover.RecoverInfo(
        recover_start=recover.StepInfo(epoch=1, epoch_step=2,
                                       global_step=7),
        last_step_info=recover.StepInfo(epoch=1, epoch_step=1,
                                        global_step=7),
        hash_vals_to_ignore=["a", "b"],
        buffer_state=buf.state_dict(),
        dataloader_state=dict(epoch=1, epoch_step=2, epochs_fetched=1))
    recover.dump(info)
    assert recover.exists()
    back = recover.load()
    # v3 added ckpt_manifests, v4 switched buffer_state to the
    # per-sample snapshot (tests/recovery/test_recover_schema.py and
    # tests/async_rlhf cover the upgrade chain); the v2-era payload
    # must keep round-tripping unchanged
    assert back.version == recover.RECOVER_INFO_VERSION == 4
    assert back.ckpt_manifests is None
    assert back.recover_start == info.recover_start
    assert back.last_step_info == info.last_step_info
    assert back.hash_vals_to_ignore == ["a", "b"]
    assert back.dataloader_state["epoch_step"] == 2
    # buffer snapshot restores: completion sticks, in-flight work is
    # requeued (undispatched), batch ids stay monotonic
    buf2 = SequenceBuffer(["gen", "train"], capacity=4)
    buf2.load_state_dict(back.buffer_state)
    assert buf2.batch_ids() == [0, 1]
    e0 = buf2.get(0)
    assert e0.completed == {"gen"}
    assert "train" not in e0.dispatched  # re-runs after restart
    assert list(e0.ids) == ["a", "b"]
    ready = buf2.ready_mfcs({"gen": (), "train": ()})
    assert (0, "train") in ready      # requeued, offered again
    assert (0, "gen") not in ready    # completion survived the dump
    assert buf2.put_batch(_meta(["e"]), "w", 1, False) == 2


def test_load_safe_missing_returns_none():
    assert recover.load_safe() is None


def test_load_safe_corrupt_and_truncated(tmp_path):
    info = recover.RecoverInfo(hash_vals_to_ignore=[1, 2, 3])
    recover.dump(info)
    path = recover.dump_path()
    raw = open(path, "rb").read()

    # truncated mid-pickle
    with open(path, "wb") as f:
        f.write(raw[: len(raw) // 2])
    assert recover.load_safe() is None

    # outright garbage
    with open(path, "wb") as f:
        f.write(b"\x00garbage not a pickle")
    assert recover.load_safe() is None

    # not a RecoverInfo
    with open(path, "wb") as f:
        pickle.dump({"hello": "world"}, f)
    assert recover.load_safe() is None

    # intact file still loads
    with open(path, "wb") as f:
        f.write(raw)
    assert recover.load_safe().hash_vals_to_ignore == [1, 2, 3]


def test_load_safe_future_version_falls_back(tmp_path):
    info = recover.RecoverInfo(version=recover.RECOVER_INFO_VERSION + 1)
    recover.dump(info)
    assert recover.load_safe() is None
    # strict load still hands it over for forensic use
    assert recover.load().version == recover.RECOVER_INFO_VERSION + 1


def test_load_upgrades_legacy_v1_pickle(tmp_path):
    """A pre-versioning dump (no version/buffer_state/dataloader_state
    attributes) loads as schema v1 with the new fields defaulted."""
    legacy = recover.RecoverInfo(
        recover_start=recover.StepInfo(epoch=3),
        hash_vals_to_ignore=["x"])
    for f in ("version", "buffer_state", "dataloader_state"):
        del legacy.__dict__[f]
    recover.dump(legacy)
    back = recover.load_safe()
    assert back is not None
    assert back.version == 1
    assert back.buffer_state is None
    assert back.dataloader_state is None
    assert back.recover_start.epoch == 3
    assert back.hash_vals_to_ignore == ["x"]


def test_dump_is_atomic_over_existing(tmp_path):
    recover.dump(recover.RecoverInfo(hash_vals_to_ignore=["old"]))
    recover.dump(recover.RecoverInfo(hash_vals_to_ignore=["new"]))
    assert recover.load_safe().hash_vals_to_ignore == ["new"]
