"""Unit tests for realhf_tpu.base (datapack, name_resolve, timeutil,
seeding, monitor). Mirrors the unit-test tier of the reference suite."""

import time

import numpy as np
import pytest

from realhf_tpu.base import datapack, name_resolve, seeding, timeutil
from realhf_tpu.base import monitor


class TestDatapack:

    @pytest.mark.parametrize("k", [1, 2, 3, 5, 8])
    def test_min_abs_diff_partition_valid(self, k):
        rng = np.random.RandomState(0)
        for _ in range(10):
            n = rng.randint(k, 4 * k + 10)
            lens = rng.randint(1, 512, size=(n,))
            parts = datapack.min_abs_diff_partition(lens, k)
            # contiguous, non-empty, covering
            assert parts[0][0] == 0 and parts[-1][1] == n
            for (s0, e0), (s1, e1) in zip(parts[:-1], parts[1:]):
                assert e0 == s1
            assert all(e > s for s, e in parts)

    def test_partition_balance_quality(self):
        lens = np.array([100] * 64)
        parts = datapack.min_abs_diff_partition(lens, 8)
        sums = [lens[s:e].sum() for s, e in parts]
        assert max(sums) == min(sums) == 800

    def test_partition_min_size(self):
        lens = np.array([1000, 1, 1, 1, 1, 1])
        parts = datapack.min_abs_diff_partition(lens, 3, min_size=2)
        assert all(e - s >= 2 for s, e in parts)

    def test_partition_errors(self):
        with pytest.raises(ValueError):
            datapack.min_abs_diff_partition([1, 2], 3)
        with pytest.raises(ValueError):
            datapack.min_abs_diff_partition(np.ones((2, 2)), 1)

    def test_reorder_to_balanced_batches(self):
        rng = np.random.RandomState(0)
        lens = rng.randint(10, 1000, size=(96,))
        order, max_diff = datapack.reorder_to_balanced_batches(lens, 16)
        assert sorted(order.tolist()) == list(range(96))
        # With n divisible by batch size, every bin has exactly 16 seqs, so
        # consecutive windows of 16 are the bins; token sums differ <= max_diff.
        batch_tokens = [lens[order[i:i + 16]].sum() for i in range(0, 96, 16)]
        assert max(batch_tokens) - min(batch_tokens) == max_diff
        assert max_diff < lens.sum() // 6  # far better than random order

    def test_ffd_allocate(self):
        vals = [5, 3, 3, 2, 2, 1]
        groups = datapack.ffd_allocate(vals, capacity=6)
        assert sorted(datapack.flat2d(groups)) == list(range(6))
        for g in groups:
            assert sum(vals[i] for i in g) <= 6

    def test_flat2d(self):
        assert datapack.flat2d([[1, 2], [3], []]) == [1, 2, 3]


class TestNameResolve:

    def test_add_get_delete(self):
        name_resolve.add("a/b/c", "v1")
        assert name_resolve.get("a/b/c") == "v1"
        with pytest.raises(name_resolve.NameEntryExistsError):
            name_resolve.add("a/b/c", "v2")
        name_resolve.add("a/b/c", "v2", replace=True)
        assert name_resolve.get("a/b/c") == "v2"
        name_resolve.delete("a/b/c")
        with pytest.raises(name_resolve.NameEntryNotFoundError):
            name_resolve.get("a/b/c")

    def test_subtree(self):
        name_resolve.add("root/x/1", "a")
        name_resolve.add("root/x/2", "b")
        name_resolve.add("root/y", "c")
        assert name_resolve.get_subtree("root/x") == ["a", "b"]
        assert len(name_resolve.find_subtree("root")) == 3
        name_resolve.clear_subtree("root/x")
        assert name_resolve.get_subtree("root/x") == []

    def test_subentry_and_wait(self):
        name_resolve.add_subentry("peers", "p0")
        name_resolve.add_subentry("peers", "p1")
        assert sorted(name_resolve.get_subtree("peers")) == ["p0", "p1"]
        with pytest.raises(TimeoutError):
            name_resolve.wait("nonexistent", timeout=0.2)

    def test_nfs_backend(self, tmp_path):
        repo = name_resolve.NfsNameRecordRepository(str(tmp_path / "nr"))
        repo.add("exp/trial/peer/0", "addr0")
        repo.add("exp/trial/peer/1", "addr1")
        assert repo.get("exp/trial/peer/0") == "addr0"
        assert repo.get_subtree("exp/trial/peer") == ["addr0", "addr1"]
        assert repo.find_subtree("exp/trial/peer") == [
            "exp/trial/peer/0", "exp/trial/peer/1"]
        repo.delete("exp/trial/peer/0")
        with pytest.raises(name_resolve.NameEntryNotFoundError):
            repo.get("exp/trial/peer/0")
        repo.reset()
        assert repo.get_subtree("exp/trial/peer") == []


class TestTimeutil:

    def test_frequency_steps(self):
        ctl = timeutil.FrequencyControl(frequency_steps=3)
        assert [ctl.check() for _ in range(7)] == [
            False, False, True, False, False, True, False]

    def test_frequency_seconds(self):
        ctl = timeutil.FrequencyControl(frequency_seconds=0.05)
        assert not ctl.check()
        time.sleep(0.06)
        assert ctl.check()

    def test_initial_value(self):
        ctl = timeutil.FrequencyControl(frequency_steps=10, initial_value=True)
        assert ctl.check()
        assert not ctl.check()

    def test_epoch_step_time(self):
        ctl = timeutil.EpochStepTimeFreqCtl(freq_epoch=None, freq_step=2, freq_sec=None)
        assert not ctl.check(epochs=0, steps=1)
        assert ctl.check(epochs=0, steps=1)


class TestSeeding:

    def test_derive(self, seeded):
        s1 = seeding.derive_seed("worker", "0")
        s2 = seeding.derive_seed("worker", "1")
        assert s1 != s2
        assert s1 == seeding.derive_seed("worker", "0")
        k = seeding.derive_key("model")
        assert k.shape == (2,)


class TestMonitor:

    def test_flops_positive_and_scaling(self):
        kw = dict(n_layers=4, hidden_dim=128, n_q_heads=8, n_kv_heads=8,
                  head_dim=16, intermediate_dim=512, vocab_size=1000)
        f1 = monitor.transformer_forward_flops(seqlens=[128] * 4, **kw)
        f2 = monitor.transformer_forward_flops(seqlens=[128] * 8, **kw)
        assert f2 == 2 * f1
        assert monitor.transformer_train_flops(seqlens=[128], **kw) == \
            3 * monitor.transformer_forward_flops(seqlens=[128], **kw)

    def test_tmark(self):
        db = monitor.TimeMarkDB()
        with db.mark("fwd"):
            time.sleep(0.01)
        assert db.total("fwd") >= 0.01
        assert "fwd" in db.summary()
