"""Core configuration data model.

Parity with reference ``realhf/api/core/config.py``: model identities
(`ModelName`, `ModelShardID`), model family specs, interface types, and
the registry-resolved "abstraction" configs (``type_`` + ``args``) used
to instantiate datasets/models/interfaces/backends at runtime.
"""

import dataclasses
import enum
from typing import Any, Dict


@dataclasses.dataclass(frozen=True)
class ModelName:
    """Unique identity of one LLM instance in the dataflow graph.

    Multiple MFCs may refer to the same *role* (e.g. "actor"); replicas
    with different parallelism layouts get distinct ``replica_id``s
    (reference ``config.py`` + ``experiments/common/utils.py:126``).
    """
    role: str
    replica_id: int = 0

    def __repr__(self):
        return f"{self.role}@{self.replica_id}"


class ModelInterfaceType(enum.Enum):
    GENERATE = "generate"
    TRAIN_STEP = "train_step"
    EVALUATE = "evaluate"
    INFERENCE = "inference"


@dataclasses.dataclass(frozen=True)
class ModelFamily:
    """LLM architecture family + size tag, e.g. llama-7b (actor) or a
    critic variant. Used for HF conversion dispatch and the search
    engine's cost model."""
    _class: str
    size: int = 0
    is_critic: bool = False

    def __repr__(self):
        return f"{self._class}-{self.size}{'-critic' if self.is_critic else ''}"


@dataclasses.dataclass(frozen=True)
class ModelShardID:
    """Identity of one shard of a model: which (dp, tp, pp) coordinate
    of which ModelName. On TPU a "shard" maps to a contiguous slice of
    the model's device mesh owned by one host process."""
    model_name: ModelName
    dp_rank: int = 0
    tp_rank: int = 0
    pp_rank: int = 0

    def __repr__(self):
        return (f"{self.model_name}:d{self.dp_rank}t{self.tp_rank}"
                f"p{self.pp_rank}")


@dataclasses.dataclass
class ModelInterfaceAbstraction:
    """Registry-resolved interface config (reference ``config.py:9-44``)."""
    type_: str
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModelBackendAbstraction:
    type_: str
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModelAbstraction:
    type_: str
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DatasetAbstraction:
    type_: str
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)
