"""Experiment specification: what the launcher materializes.

Parity with the reference's two-level config system
(``realhf/api/core/system_api.py`` ExperimentConfig +
``api/quickstart/model.py`` ModelTrainEvalConfig): an experiment names
its models (role -> spec), the dataflow graph of MFCs, the dataset,
and run control (epochs, save/eval frequency, seed).
"""

import dataclasses
from typing import Dict, List, Optional

from realhf_tpu.api.config import DatasetAbstraction, ModelName
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.engine.optim import OptimizerConfig
from realhf_tpu.parallel.mesh import ParallelismConfig


@dataclasses.dataclass
class ModelSpec:
    """One model role (reference ModelTrainEvalConfig,
    quickstart/model.py:114)."""
    hf_family: str = "llama"
    path: Optional[str] = None  # HF checkpoint dir; None = random init
    # Used when path is None (testing / benchmarking):
    random_init_config: Optional[dict] = None
    is_critic: bool = False
    init_critic_from_actor: bool = False
    optimizer: Optional[OptimizerConfig] = None
    parallel: ParallelismConfig = dataclasses.field(
        default_factory=ParallelismConfig)
    gradient_checkpointing: bool = True
    bf16: bool = True


@dataclasses.dataclass
class SaveEvalControl:
    """Reference ExperimentSaveEvalControl (system_api.py:157)."""
    save_freq_epochs: Optional[int] = None
    save_freq_steps: Optional[int] = None
    save_freq_secs: Optional[float] = None
    eval_freq_epochs: Optional[int] = None
    eval_freq_steps: Optional[int] = None
    benchmark_steps: Optional[int] = None  # stop early after N steps


@dataclasses.dataclass
class ExperimentSpec:
    experiment_name: str
    trial_name: str
    models: Dict[str, ModelSpec]
    mfcs: List[MFCDef]
    dataset: DatasetAbstraction
    # Per-MFC parallelism overrides (MFC name -> layout). An MFC whose
    # layout differs from its role's primary creates a weight replica
    # kept fresh by parameter reallocation (the reference's
    # RPCAllocation, quickstart/device_mesh.py:269).
    allocations: Dict[str, ParallelismConfig] = dataclasses.field(
        default_factory=dict)
    tokenizer_path: Optional[str] = None
    tokenizer: Optional[object] = None  # direct object (tests)
    total_train_epochs: int = 1
    seed: int = 1
    ctl: SaveEvalControl = dataclasses.field(default_factory=SaveEvalControl)
    eval_dataset: Optional[DatasetAbstraction] = None
    # --- distributed runtime (mode=distributed) -----------------------
    # Number of model-worker processes; each owns its own device set
    # and the roles assigned to it (reference: ModelWorker per GPU;
    # on TPU one worker per host-slice).
    n_model_workers: int = 1
    # role -> model worker index OR list of indices (a worker GROUP:
    # the role's mesh spans every group member's devices, and the
    # members form one jax.distributed world -- the reference's
    # multi-node model spanning multiple ModelWorkers). Unassigned
    # roles land on worker 0. The first index is the group LEADER: it
    # owns the dataset/reply protocol for the role.
    worker_assignment: Dict[str, object] = dataclasses.field(
        default_factory=dict)
    # Buffer capacity: how many dataset batches may be in flight at
    # once (>=2 lets MFCs of consecutive steps overlap on disjoint
    # meshes; reference AsyncIOSequenceBuffer pipelining).
    max_concurrent_batches: int = 2
    # How many steps a non-train MFC may run ahead of its role's train
    # MFC (reference master_worker.py:503-509 staleness guard).
    max_head_offpolicyness: int = 0
    # Auto-resolve OffloadHooks: non-trainable roles (ref/reward) move
    # their weights to host after their last MFC of a step, freeing
    # HBM for the train MFCs, and reload on next use (reference
    # resolve_rpc_hooks, experiments/common/utils.py:143 +
    # model_worker.py:542-552).
    auto_offload: bool = False

    def workers_of_role(self, role: str) -> List[int]:
        """Worker group of a role (leader first). Single-int
        assignments are one-member groups."""
        v = self.worker_assignment.get(role, 0)
        if isinstance(v, int):
            return [v]
        out = list(v)
        if len(out) != len(set(out)):
            raise ValueError(f"duplicate workers in group of {role}: {v}")
        return out

    def worker_of_role(self, role: str) -> int:
        """The role's group leader (single worker in the common case)."""
        return self.workers_of_role(role)[0]

    @property
    def multihost(self) -> bool:
        """True when any role's mesh spans more than one worker
        process -- all model workers then join one jax.distributed
        world (the reference's single NCCL world, global_comm.py:44)."""
        return any(len(self.workers_of_role(r)) > 1 for r in self.models)
