"""Experiment specification: what the launcher materializes.

Parity with the reference's two-level config system
(``realhf/api/core/system_api.py`` ExperimentConfig +
``api/quickstart/model.py`` ModelTrainEvalConfig): an experiment names
its models (role -> spec), the dataflow graph of MFCs, the dataset,
and run control (epochs, save/eval frequency, seed).
"""

import dataclasses
from typing import Dict, List, Optional

from realhf_tpu.api.config import DatasetAbstraction
from realhf_tpu.api.dfg import MFCDef
from realhf_tpu.engine.optim import OptimizerConfig
from realhf_tpu.parallel.mesh import ParallelismConfig


@dataclasses.dataclass
class ModelSpec:
    """One model role (reference ModelTrainEvalConfig,
    quickstart/model.py:114)."""
    hf_family: str = "llama"
    path: Optional[str] = None  # HF checkpoint dir; None = random init
    # Used when path is None (testing / benchmarking):
    random_init_config: Optional[dict] = None
    is_critic: bool = False
    init_critic_from_actor: bool = False
    optimizer: Optional[OptimizerConfig] = None
    parallel: ParallelismConfig = dataclasses.field(
        default_factory=ParallelismConfig)
    gradient_checkpointing: bool = True
    bf16: bool = True
    # Host-RAM-bounded checkpoint load (hf/registry.py
    # load_hf_checkpoint_streamed): place weights layer-by-layer
    # directly onto the mesh; peak host memory = one transformer layer
    # + embeddings instead of the full model. Required for >host-RAM
    # models (70B). None (default) = automatic: stream when the
    # checkpoint's safetensors total exceeds 16 GB (single-process
    # meshes only -- process-spanning meshes need the explicit flag so
    # every member takes the same collective path); True/False force.
    streamed_load: Optional[bool] = None
    # Set by the RECOVERY path when `path` was redirected to a recover
    # checkpoint: restore saved Adam moments/master alongside the
    # weights. Never set for ordinary warm-starts from a checkpoint
    # dir -- a new run must begin with fresh optimizer state even if
    # the dir carries an optimizer_state.npz.
    restore_optimizer_state: bool = False
    # pp/ctx meshes generate through a decode view -- a SECOND full
    # weight copy on a collapsed dp x tp mesh (Engine.decode_engine).
    # True frees that copy after every generate MFC (steady-state HBM
    # back to one copy, the 70B OOM frontier) at the price of one
    # cross-mesh reshard per rollout; False keeps it resident so only
    # weight changes pay the reshard.
    drop_decode_view_after_rollout: bool = False


@dataclasses.dataclass
class MFCAllocation:
    """Per-MFC placement: layout + (optionally) its own worker group
    and per-worker device subset.

    The reference's RPCAllocation (quickstart/device_mesh.py:269):
    every MFC may run on its own device subset of the cluster with its
    own 3D-parallel strategy. ``workers=None`` keeps the MFC on its
    role's primary worker group (same devices, different layout =>
    same-group replica). ``workers`` different from the role's group
    puts the MFC on OTHER processes/devices entirely; the role's
    weights then flow to it through the host data plane after every
    train step (same-role cross-group reallocation -- the reference's
    param_realloc NCCL broadcast, comm/param_realloc.py:312, as a
    DCN-class host relay per SURVEY §5.8).

    ``device_ids``: local device indices each exec worker contributes
    to this MFC's mesh (reference per-worker GPU isolation,
    base/gpu_utils.py:64); None = the worker's default slice.
    """
    parallel: ParallelismConfig
    workers: Optional[List[int]] = None
    device_ids: Optional[List[int]] = None


@dataclasses.dataclass
class SaveEvalControl:
    """Reference ExperimentSaveEvalControl (system_api.py:157)."""
    save_freq_epochs: Optional[int] = None
    save_freq_steps: Optional[int] = None
    save_freq_secs: Optional[float] = None
    eval_freq_epochs: Optional[int] = None
    eval_freq_steps: Optional[int] = None
    benchmark_steps: Optional[int] = None  # stop early after N steps


@dataclasses.dataclass
class FaultToleranceConfig:
    """Knobs for the fault-tolerant runtime (heartbeats, watchdog,
    retry/backoff, requeue); see docs/distributed.md "Fault tolerance
    & recovery"."""
    # liveness: WorkerServer beats every interval; a beat older than
    # heartbeat_timeout marks the worker LOST
    heartbeat_interval: float = 2.0
    heartbeat_timeout: float = 20.0
    watchdog_poll_secs: float = 1.0
    # allowance for process spawn + jax import before the first beat
    startup_grace_secs: float = 120.0
    # requeue: how often one MFC may be requeued after worker loss
    # before the trial fails (relaunch-level recovery takes over)
    max_mfc_retries: int = 1
    # a worker continuously LOST this long fails the trial even if
    # nothing was in flight on it (it will be needed eventually)
    worker_lost_fatal_secs: float = 60.0
    # excluded_workers backoff: a lost worker is kept out of dispatch
    # for base * 2**(losses-1) seconds (capped, jittered)
    exclude_base_secs: float = 5.0
    exclude_max_secs: float = 120.0
    # save/eval dispatch+gather: attempts and per-attempt timeout.
    # The retry stack's TOTAL wall clock is additionally capped by
    # gather_max_elapsed_secs (RetryPolicy.max_elapsed) so stacked
    # backoffs during a degradation event cannot outlive the watchdog
    # grace window and mask a real worker loss.
    gather_retries: int = 2
    gather_timeout_secs: float = 600.0
    gather_max_elapsed_secs: Optional[float] = None
    # --- elastic degraded-mode training (system/elastic.py) ----------
    # re-plan MFCs of LOST/preempted workers onto survivors instead of
    # requeue-and-hope; re-expand when the worker rejoins
    elastic_degrade: bool = False
    # launcher resubmits a PREEMPTED worker's process once it exits
    # (the "replacement worker rejoins" path)
    elastic_rejoin: bool = False
    # grace window a preempted worker gets to drain + emergency-save
    preempt_grace_secs: float = 15.0
    # at most this many adopted (migrated) MFC replicas per survivor:
    # each adoption is a full extra weight copy in HBM
    max_adopted_per_worker: int = 2
    # --- host failure domains (system/pod.py) ------------------------
    # workers of one host whose heartbeats go stale within this many
    # seconds of each other are attributed as ONE HOST_LOST (one
    # flight event, one backoff entry) instead of N independent
    # losses. None -> the watchdog defaults to heartbeat_timeout.
    host_lost_window_secs: Optional[float] = None
    # --- durable checkpoints (system/ckpt_manager.py) ----------------
    # route model-worker saves through the sharded-manifest manager
    # (per-shard checksums, atomic COMMITTED marker, verified load
    # with fallback, GC); the HF layout is preserved via a `latest`
    # symlink so external consumers keep working
    durable_ckpt: bool = True
    # committed checkpoints retained per role (older ones are GCed)
    ckpt_keep: int = 2


@dataclasses.dataclass
class ServingSpec:
    """A standalone rollout/serving deployment (docs/serving.md): one
    or more ``GenServerWorker`` processes, each running a
    continuous-batching ``RolloutServer`` over the named model role.
    Launched by ``apps.main.run_serve`` -- standalone or alongside a
    training trial as its asynchronous rollout producer."""
    model_role: str = "default"
    n_servers: int = 1
    #: decode slots per server (concurrent sequences in the batch)
    n_slots: int = 4
    #: decode steps per host<->device sync
    chunk_size: int = 8
    max_prompt_len: int = 512
    #: admission control: queue entries beyond this are rejected with
    #: a retry_after hint (backpressure) instead of growing unbounded
    max_queue_depth: int = 256
    #: reject/evict sequences whose start weight version lags the
    #: installed version by more than this; None disables the bound
    max_staleness: Optional[int] = None
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    #: GenerationHyperparameters kwargs (max_new_tokens, greedy, ...);
    #: force_no_logits_mask is always set -- inflight serving never
    #: produces the PPO logits mask
    gconfig: dict = dataclasses.field(default_factory=dict)
    #: send incremental token deltas after every decode chunk
    stream_tokens: bool = True
    # -- paged KV pool (docs/perf.md "Paged KV & quantization"):
    # replace the dense per-slot [cache_len] KV windows with one
    # block-granular device pool (engine/kv_pool.py) shared with the
    # radix prefix cache. Decode memory then tracks ACTUAL tokens, so
    # concurrency is bounded by blocks, not worst-case windows, and
    # admission backpressure rides pool free blocks.
    paged_kv: bool = False
    #: KV storage dtype: None = the model's compute dtype (dense
    #: semantics); "fp32"/"bf16" set the storage dtype; "int8" stores
    #: quantized rows + per-row scales (requires/implies paged_kv --
    #: dequant-on-read lives in the pool gather path).
    kv_cache_dtype: Optional[str] = None
    #: tokens per pool block (the allocation granule; internal
    #: fragmentation is < 1 block per sequence)
    kv_block_len: int = 16
    #: total pool blocks; None sizes the pool at dense parity
    #: (n_slots * ceil(cache_len / kv_block_len)) -- shrink it to
    #: trade worst-case headroom for more decode slots per byte
    kv_pool_blocks: Optional[int] = None
    # -- serving hot path (docs/serving.md "Prefix cache &
    # speculative decoding") --------------------------------------
    #: byte budget for the radix prefix/KV cache (host memory):
    #: requests sharing a cached prefix skip its prefill and only run
    #: the uncached suffix. 0 disables reuse entirely (behaviorally
    #: identical to a cache-less server).
    prefix_cache_bytes: int = 64 * 1024 * 1024
    #: prompt-lookup speculative decoding: draft k tokens per round
    #: from the request's own history and verify them in one forward
    #: (greedy-exact; ignored unless gconfig is greedy). 0 disables.
    #: The REALHF_TPU_SPEC_K env var overrides at worker start.
    spec_decode_k: int = 0
    #: seconds drain() waits for in-flight sequences at shutdown
    drain_timeout_secs: float = 30.0
    #: HARD deadline on any drain: in-flight sequences still running
    #: past it are force-fenced with explicit
    #: ``cancelled(reason=drain_deadline)`` terminals (never silent
    #: loss) and a flight event names the abandoned rids. None = the
    #: drain timeout itself is the deadline.
    drain_deadline_secs: Optional[float] = None
    #: log-only autoscaling advisory (superseded by the closed loop
    #: below, kept for single-server deployments): when a server's
    #: queue depth stays above this threshold, an ElasticPlanner GROW
    #: suggestion is emitted (counter + flight event + warning log --
    #: no fleet change). 0 disables.
    autoscale_queue_threshold: int = 0
    # -- closed-loop autoscaling (docs/serving.md "Autoscaling"):
    # run_serve supervises an AutoscaleController that spawns/retires
    # GenServer replicas from live router signals. Requires
    # fleet_router (the router is both the signal source and the
    # discovery path for new replicas).
    autoscale: bool = False
    #: replica-count bounds; scale-down never goes below the floor
    #: (and never takes the last healthy replica while traffic is in
    #: flight, even with floor 0)
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 4
    #: seconds between policy observations in the launcher loop
    autoscale_interval_secs: float = 2.0
    #: scale-up pressure: queued requests per live replica above this
    autoscale_up_queue_per_replica: int = 8
    #: scale-up pressure: response-latency EWMA above this (None off)
    autoscale_up_latency_secs: Optional[float] = None
    #: consecutive pressured/idle observations before acting
    autoscale_consecutive_up: int = 3
    autoscale_consecutive_down: int = 10
    #: scale-down idle bound: in-flight per REMAINING replica
    autoscale_down_idle_per_replica: float = 1.0
    #: same-direction re-arm time between actions
    autoscale_cooldown_secs: float = 30.0
    #: seconds a spawned replica gets to register before the spawn is
    #: written off as failed
    autoscale_spawn_deadline_secs: float = 180.0
    #: where run_serve reads the router's autoscale signals:
    #: "zmq" (default; the router's stats worker command) or "http"
    #: (GET the router's /metrics telemetry endpoint -- the same
    #: Prometheus text a real scraper sees, resolved through
    #: names.telemetry; falls back to zmq when unreachable)
    autoscale_signal_source: str = "zmq"
    #: which latency figure feeds the scale-up policy: "ewma"
    #: (default), or "p50"/"p95" from the router_latency_seconds
    #: histogram (tail latency reacts to stragglers the EWMA smooths
    #: over)
    autoscale_latency_signal: str = "ewma"
    # -- resilient fleet mode (docs/serving.md "Fleet, failover &
    # circuit breakers"): a FleetRouter fronts the n_servers replicas;
    # replicas register leases in the fleet registry and clients talk
    # to the router (server_name="router") instead of a replica.
    fleet_router: bool = False
    #: replica lease TTL; a replica silent for this long vanishes from
    #: the registry and its in-flight work fails over
    lease_ttl_secs: float = 5.0
    #: dispatch a speculative duplicate when a request has not started
    #: within this many seconds (None disables hedging)
    router_hedge_delay_secs: Optional[float] = None
    router_max_hedges: int = 1
    #: consecutive failures that open a replica's circuit breaker
    router_breaker_failures: int = 3
    #: seconds an open breaker waits before the half-open probe
    router_breaker_cooldown_secs: float = 5.0
    #: no reply at all to a dispatched request within this -> failover
    router_dispatch_timeout_secs: float = 10.0
    #: an accepted request silent for this long -> failover (None
    #: disables; covers a dropped terminal-event send)
    router_response_timeout_secs: Optional[float] = 60.0
    #: cap on router-tracked in-flight requests (backpressure beyond)
    router_max_pending: int = 1024
    #: prefix-affinity dispatch: hash a request's first N tokens and
    #: prefer the replica that last served that hash, so fleet traffic
    #: concentrates prefix-cache hits instead of spraying a shared
    #: system prompt across every replica. 0 disables (pure
    #: least-loaded). Health/breaker/fencing gates always win.
    router_affinity_prefix_len: int = 16
    # -- sharded router plane (docs/serving.md "Sharded router
    # plane"): with n_routers > 1, that many RouterWorker shards
    # split rid space by consistent hash; each holds its own
    # lease/epoch and a shard death re-homes its range to survivors.
    n_routers: int = 1
    # -- chunked weight distribution (docs/serving.md "Chunked weight
    # distribution"): content-hashed chunk pushes over a relay tree
    # instead of full-copy unicast per replica.
    #: max raw bytes packed per chunk
    weight_push_chunk_bytes: int = 4 << 20
    #: wire encoding for pushed chunks: "raw" or "int8" (per-row
    #: symmetric quantization, reusing the paged-KV helpers)
    weight_push_encoding: str = "raw"
    #: relay-tree fanout; 0 = unicast (root pushes to every replica)
    weight_push_fanout: int = 2
    # -- HTTP front door (docs/serving.md "Front door"): a
    # GatewayWorker serving OpenAI-compatible streaming
    # ``/v1/completions`` over SSE, fronting the router plane with
    # per-tenant quotas, SLO classes, and deadline-aware shedding.
    gateway: bool = False
    #: TCP port for the gateway's HTTP listener; 0 = OS-assigned (the
    #: bound address is published via name_resolve either way)
    gateway_port: int = 0
    #: default per-tenant token-bucket refill rate (requests/second)
    #: and burst capacity; tenants absent from ``gateway_tenants``
    #: get these
    gateway_tenant_rate: float = 50.0
    gateway_tenant_burst: float = 100.0
    #: per-tenant overrides: ``{tenant: {"rate": .., "burst": ..}}``
    gateway_tenants: Dict[str, dict] = dataclasses.field(
        default_factory=dict)
    #: SLO budgets (seconds): a request without an explicit
    #: ``deadline_secs`` gets its class's budget as the deadline the
    #: shed decision evaluates against
    gateway_interactive_slo_secs: float = 2.0
    gateway_batch_slo_secs: float = 30.0
    #: brownout level 2+ trims ``max_tokens`` down to this
    gateway_trim_max_new_tokens: int = 32
    #: seconds the gateway waits on a wire stream before closing the
    #: HTTP request with an ``expired`` terminal
    gateway_stream_timeout_secs: float = 120.0


@dataclasses.dataclass
class ExperimentSpec:
    experiment_name: str
    trial_name: str
    models: Dict[str, ModelSpec]
    mfcs: List[MFCDef]
    dataset: DatasetAbstraction
    # Per-MFC placement overrides (MFC name -> layout or full
    # MFCAllocation). An MFC whose layout differs from its role's
    # primary creates a weight replica kept fresh by parameter
    # reallocation; an MFCAllocation with its own ``workers`` puts the
    # replica on a different worker group / device subset entirely
    # (the reference's RPCAllocation, quickstart/device_mesh.py:269).
    allocations: Dict[str, object] = dataclasses.field(
        default_factory=dict)
    tokenizer_path: Optional[str] = None
    tokenizer: Optional[object] = None  # direct object (tests)
    total_train_epochs: int = 1
    seed: int = 1
    ctl: SaveEvalControl = dataclasses.field(default_factory=SaveEvalControl)
    ft: FaultToleranceConfig = dataclasses.field(
        default_factory=FaultToleranceConfig)
    eval_dataset: Optional[DatasetAbstraction] = None
    # --- distributed runtime (mode=distributed) -----------------------
    # Number of model-worker processes; each owns its own device set
    # and the roles assigned to it (reference: ModelWorker per GPU;
    # on TPU one worker per host-slice).
    n_model_workers: int = 1
    # role -> model worker index OR list of indices (a worker GROUP:
    # the role's mesh spans every group member's devices, and the
    # members form one jax.distributed world -- the reference's
    # multi-node model spanning multiple ModelWorkers). Unassigned
    # roles land on worker 0. The first index is the group LEADER: it
    # owns the dataset/reply protocol for the role.
    worker_assignment: Dict[str, object] = dataclasses.field(
        default_factory=dict)
    # Buffer capacity: how many dataset batches may be in flight at
    # once (>=2 lets MFCs of consecutive steps overlap on disjoint
    # meshes; reference AsyncIOSequenceBuffer pipelining). With the
    # per-sample buffer this also bounds the largest per-MFC n_seqs an
    # assembly can ever satisfy: capacity * source n_seqs samples.
    max_concurrent_batches: int = 2
    # How many of its OWN batches a non-train MFC may run ahead of its
    # role's train MFCs, measured on per-sample consumption watermarks
    # (reference master_worker.py:503-509 staleness guard; with
    # uniform n_seqs this is exactly "k-1-offpolicyness batches").
    max_head_offpolicyness: int = 0
    # Auto-resolve OffloadHooks: non-trainable roles (ref/reward) move
    # their weights to host after their last MFC of a step, freeing
    # HBM for the train MFCs, and reload on next use (reference
    # resolve_rpc_hooks, experiments/common/utils.py:143 +
    # model_worker.py:542-552).
    auto_offload: bool = False
    # Rollout/serving deployment (apps.main.run_serve spawns
    # ``serving.n_servers`` GenServerWorker processes); None for
    # ordinary training trials.
    serving: Optional[ServingSpec] = None

    def workers_of_role(self, role: str) -> List[int]:
        """Worker group of a role (leader first). Single-int
        assignments are one-member groups."""
        v = self.worker_assignment.get(role, 0)
        if isinstance(v, int):
            return [v]
        out = list(v)
        if len(out) != len(set(out)):
            raise ValueError(f"duplicate workers in group of {role}: {v}")
        return out

    def worker_of_role(self, role: str) -> int:
        """The role's group leader (single worker in the common case)."""
        return self.workers_of_role(role)[0]

    def alloc_of(self, node_name: str) -> Optional[MFCAllocation]:
        """The MFC's allocation, normalized to MFCAllocation (bare
        ParallelismConfig values keep the role's worker group)."""
        v = self.allocations.get(node_name)
        if v is None:
            return None
        if isinstance(v, MFCAllocation):
            return v
        return MFCAllocation(parallel=v)

    def workers_of_node(self, node_name: str, role: str) -> List[int]:
        """The worker group an MFC EXECUTES on (leader first): its
        allocation's own group when set, else its role's group."""
        alloc = self.alloc_of(node_name)
        if alloc is not None and alloc.workers is not None:
            out = list(alloc.workers)
            if not out:
                raise ValueError(
                    f"MFCAllocation for {node_name} has an empty "
                    "workers list; use workers=None for the role's "
                    "own group.")
            if len(out) != len(set(out)):
                raise ValueError(
                    f"duplicate workers in group of {node_name}: {out}")
            return out
        return self.workers_of_role(role)

    def is_cross_group(self, node_name: str, role: str) -> bool:
        """True when the MFC executes on a different worker group than
        its role's primary -- weights then flow via the host data
        plane (same-role cross-group reallocation)."""
        return (set(self.workers_of_node(node_name, role))
                != set(self.workers_of_role(role)))

    @property
    def multihost(self) -> bool:
        """True when any role's (or MFC allocation's) mesh spans more
        than one worker process -- all model workers then join one
        jax.distributed world (the reference's single NCCL world,
        global_comm.py:44). Cross-group single-worker placements do
        NOT need a shared world: each group's mesh is process-local
        and weights move over the host data plane."""
        if any(len(self.workers_of_role(r)) > 1 for r in self.models):
            return True
        return any(
            a is not None and a.workers is not None and len(a.workers) > 1
            for a in (self.alloc_of(n) for n in self.allocations))
