"""The universal data currency: packed variable-length sequence batches.

Parity with reference ``realhf/api/core/data_api.py``: `SequenceSample`
holds named 1D-packed tensors with per-key nested sequence lengths and
supports gather / balanced split / metadata-only views. Host-side
arrays are NumPy; engines move data on-device (with padding to static
bucket shapes) at the pjit boundary, because XLA requires static shapes
while the data plane does not.

Also provides the dataset registry, dataset spec/loading helpers, and
the packed dataloader.
"""

import contextlib
import dataclasses
import json
import random as _random
from typing import Any, Callable, Dict, Hashable, List, Optional, Set, Tuple

import numpy as np

from realhf_tpu.base import datapack, logging

logger = logging.getLogger("data_api")


@dataclasses.dataclass
class SequenceSplitSpec:
    """Contiguous batch partition boundaries (reference ``data_api.py:60``)."""
    partitions: List[Tuple[int, int]]


_VALIDATION_ENABLED = True


class SequenceSample:
    """A batch of named, packed, variable-length sequences.

    See reference ``data_api.py:96-596`` for the full design discussion.
    Invariants:
      - ``ids`` are unique per batch element;
      - ``seqlens[k]`` is a list (batch) of lists (sequences per element)
        of ints;
      - ``data[k]`` is a single array of shape
        ``(sum of all seqlens[k], *trailing_shapes[k])`` or None;
      - a sample with ``data=None`` is a metadata-only view that travels
        over the control plane.
    """

    def __init__(self, keys, trailing_shapes, dtypes, ids, seqlens,
                 data=None, metadata=None):
        self.keys: Set[str] = set(keys)
        self.trailing_shapes: Dict[str, Optional[Tuple]] = dict(trailing_shapes)
        self.dtypes: Dict[str, Optional[np.dtype]] = dict(dtypes)
        self.ids: List[Hashable] = list(ids)
        self.seqlens: Dict[str, List[List[int]]] = dict(seqlens)
        self.data: Optional[Dict[str, Optional[np.ndarray]]] = data
        self.metadata: Dict[str, List[Any]] = dict(metadata) if metadata else {}
        if _VALIDATION_ENABLED:
            self._validate()

    def _validate(self):
        if len(self.ids) != len(set(self.ids)):
            raise ValueError(f"IDs contain duplicates: {self.ids}")
        bs = len(self.ids)
        for k, lens in self.seqlens.items():
            if len(lens) != bs:
                raise ValueError(
                    f"seqlens[{k}] has {len(lens)} entries, expected {bs}.")
            for lens_ in lens:
                if not isinstance(lens_, list) or not all(
                        isinstance(x, int) for x in lens_):
                    raise ValueError(
                        f"seqlens[{k}] must be a list of lists of ints, got {lens}.")
        if self.keys != set(self.seqlens) or self.keys != set(
                self.trailing_shapes) or self.keys != set(self.dtypes):
            raise KeyError(
                f"Key mismatch: keys={self.keys}, seqlens={set(self.seqlens)}, "
                f"trailing_shapes={set(self.trailing_shapes)}, dtypes={set(self.dtypes)}")
        if self.data is not None:
            if self.keys != set(self.data):
                raise KeyError(f"Data keys {set(self.data)} != keys {self.keys}")
            for k, v in self.data.items():
                if v is None:
                    continue
                want = (sum(sum(l) for l in self.seqlens[k]),
                        *tuple(self.trailing_shapes[k]))
                if tuple(v.shape) != want:
                    raise ValueError(
                        f"Key {k}: data shape {v.shape} != expected {want}.")
                if np.dtype(v.dtype) != np.dtype(self.dtypes[k]):
                    raise ValueError(
                        f"Key {k}: dtype {v.dtype} != configured {self.dtypes[k]}.")

    @classmethod
    @contextlib.contextmanager
    def disable_validation(cls):
        global _VALIDATION_ENABLED
        prev = _VALIDATION_ENABLED
        _VALIDATION_ENABLED = False
        try:
            yield
        finally:
            _VALIDATION_ENABLED = prev

    # ------------------------------------------------------------------
    @property
    def bs(self) -> int:
        return len(self.ids)

    def total_len(self, key: str) -> int:
        return sum(sum(l) for l in self.seqlens[key])

    @classmethod
    def gather(cls, samples: List["SequenceSample"],
               keys: Optional[List[str]] = None) -> "SequenceSample":
        """Concatenate batches (reference ``data_api.py:269``)."""
        if not samples:
            raise ValueError("Cannot gather an empty list of samples.")
        keys = set(keys) if keys is not None else samples[0].keys
        seqlens = {k: sum([s.seqlens[k] for s in samples], []) for k in keys}
        if samples[0].data is not None:
            data = {
                k: (np.concatenate([s.data[k] for s in samples], axis=0)
                    if samples[0].data[k] is not None else None)
                for k in keys
            }
        else:
            data = None
        ids = sum([s.ids for s in samples], [])
        metadata = {k: sum([s.metadata[k] for s in samples], [])
                    for k in samples[0].metadata}
        with cls.disable_validation():
            return cls(
                keys=keys,
                trailing_shapes={k: samples[0].trailing_shapes[k] for k in keys},
                dtypes={k: samples[0].dtypes[k] for k in keys},
                ids=ids, seqlens=seqlens, data=data, metadata=metadata)

    def _get_split_key(self) -> str:
        return max(self.keys, key=self.total_len)

    def get_split_spec(self, k: int, key: Optional[str] = None,
                       min_size: int = 1) -> SequenceSplitSpec:
        """Token-balanced contiguous partition into k parts
        (reference ``data_api.py:315``)."""
        key = key or self._get_split_key()
        lens = [sum(l) for l in self.seqlens[key]]
        return SequenceSplitSpec(
            partitions=datapack.min_abs_diff_partition(lens, k, min_size))

    def split_with_spec(self, spec: SequenceSplitSpec) -> List["SequenceSample"]:
        samples = []
        offsets = {k: 0 for k in self.keys}
        for start, end in spec.partitions:
            seqlens = {k: l[start:end] for k, l in self.seqlens.items()}
            chunk = {k: sum(sum(l) for l in v) for k, v in seqlens.items()}
            if self.data is not None:
                data = {k: (v[offsets[k]:offsets[k] + chunk[k]]
                            if v is not None else None)
                        for k, v in self.data.items()}
            else:
                data = None
            for k in self.keys:
                offsets[k] += chunk[k]
            with self.disable_validation():
                samples.append(SequenceSample(
                    keys=self.keys,
                    trailing_shapes=self.trailing_shapes,
                    dtypes=self.dtypes,
                    ids=self.ids[start:end],
                    seqlens=seqlens,
                    data=data,
                    metadata={k: v[start:end] for k, v in self.metadata.items()}))
        return samples

    def split(self, k: int, key: Optional[str] = None,
              min_size: int = 1) -> List["SequenceSample"]:
        return self.split_with_spec(self.get_split_spec(k, key, min_size))

    def unpack(self) -> List["SequenceSample"]:
        return self.split_with_spec(
            SequenceSplitSpec([(i, i + 1) for i in range(self.bs)]))

    def meta(self) -> "SequenceSample":
        """Metadata-only view (reference ``data_api.py:428``)."""
        with self.disable_validation():
            return SequenceSample(
                keys=self.keys, trailing_shapes=self.trailing_shapes,
                dtypes=self.dtypes, ids=self.ids, seqlens=self.seqlens,
                data=None, metadata=self.metadata)

    def select(self, keys: List[str]) -> "SequenceSample":
        """A view holding only the given keys."""
        keys = set(keys)
        missing = keys - self.keys
        if missing:
            raise KeyError(f"Missing keys: {missing}; available: {self.keys}")
        with self.disable_validation():
            return SequenceSample(
                keys=keys,
                trailing_shapes={k: self.trailing_shapes[k] for k in keys},
                dtypes={k: self.dtypes[k] for k in keys},
                ids=self.ids,
                seqlens={k: self.seqlens[k] for k in keys},
                data=None if self.data is None else {
                    k: self.data[k] for k in keys},
                metadata=self.metadata)

    def update_(self, other: "SequenceSample"):
        """Merge keys produced by an MFC (reference ``data_api.py:441``)."""
        assert self.ids == other.ids, (self.ids, other.ids)
        self.keys = self.keys | other.keys
        self.trailing_shapes.update(other.trailing_shapes)
        self.dtypes.update(other.dtypes)
        self.seqlens.update(other.seqlens)
        if self.data is not None and other.data is not None:
            self.data.update(other.data)
        self.metadata.update(other.metadata)

    def remap_keys_(self, remap: Dict[str, str]):
        for k in list(self.keys):
            if k in remap:
                nk = remap[k]
                self.seqlens[nk] = self.seqlens.pop(k)
                self.trailing_shapes[nk] = self.trailing_shapes.pop(k)
                self.dtypes[nk] = self.dtypes.pop(k)
                if self.data is not None:
                    self.data[nk] = self.data.pop(k)
        self.keys = {remap.get(k, k) for k in self.keys}

    # ------------------------------------------------------------------
    _KEYS_LEN_1 = {
        "seq_no_eos_mask", "greedy_seq_no_eos_mask", "loss_mask", "rewards",
        "greedy_rewards", "pos_input_lens", "group_factor", "seq_len",
    }
    _KEYS_LEN_FULL = {
        "input_ids", "packed_seq", "seq", "packed_logits_mask", "logits_mask",
        "prompt_mask", "greedy_prompt_mask", "packed_input_ids",
        "greedy_packed_input_ids", "values", "packed_prompts",
    }
    _KEYS_LEN_MINUS_1 = {
        "packed_logprobs", "logprobs", "packed_ref_logprobs", "ref_logprobs",
        "old_logp", "ref_logp", "advantages", "ppo_loss_mask", "kl_rewards",
        "returns", "staleness", "dense_rewards",
    }

    @classmethod
    def _resolve_seqlen_from_key(cls, key: str,
                                 seqlens: List[int]) -> List[List[int]]:
        if key in cls._KEYS_LEN_1:
            return [[1] for _ in seqlens]
        if key in cls._KEYS_LEN_FULL:
            return [[l] for l in seqlens]
        if key in cls._KEYS_LEN_MINUS_1:
            return [[l - 1] for l in seqlens]
        raise NotImplementedError(
            f"Cannot resolve seqlens for key `{key}`; construct the "
            "SequenceSample explicitly instead of using from_default.")

    @classmethod
    def from_default(cls, seqlens: List[int], ids: List[Hashable],
                     data: Dict[str, Optional[np.ndarray]],
                     metadata: Optional[Dict[str, List[Any]]] = None
                     ) -> "SequenceSample":
        """Build a sample where every element has ONE sequence whose
        length per key follows the standard key-naming rules
        (reference ``data_api.py:500``)."""
        metadata = metadata or {}
        for k, v in metadata.items():
            if not isinstance(v, list) or len(v) != len(seqlens):
                raise ValueError(
                    f"Metadata `{k}` must be a list of len {len(seqlens)}: {v}")
        if seqlens and isinstance(seqlens[0], list):
            assert all(len(s) == 1 for s in seqlens)
            seqlens = [s[0] for s in seqlens]
        keys = set(data.keys())
        return cls(
            keys=keys,
            ids=ids,
            seqlens={k: cls._resolve_seqlen_from_key(k, seqlens) for k in keys},
            trailing_shapes={k: (tuple(data[k].shape[1:])
                                 if data[k] is not None else None)
                             for k in keys},
            dtypes={k: (data[k].dtype if data[k] is not None else None)
                    for k in keys},
            data=data,
            metadata=metadata)

    def __repr__(self):
        return (f"SequenceSample(bs={self.bs}, keys={sorted(self.keys)}, "
                f"meta_only={self.data is None})")


def epoch_qualified(batch: "SequenceSample", epoch: int
                    ) -> "SequenceSample":
    """A view of ``batch`` whose ids are ``(epoch, raw_id)`` tuples.

    Dataset sample ids REPEAT across epochs, so raw ids cannot key the
    data plane once batches of consecutive epochs are live at the same
    time (``max_concurrent_batches > 1``): a finishing batch's
    ``clear_data_cache`` would delete an id an in-flight next-epoch
    batch still needs, and a per-sample assembly spanning the epoch
    boundary would hold duplicate ids. Qualification happens once, at
    the data owner's fetch reply; everything downstream (stores,
    buffer, dispatch, cache clears) speaks qualified ids."""
    with SequenceSample.disable_validation():
        return SequenceSample(
            keys=batch.keys, trailing_shapes=batch.trailing_shapes,
            dtypes=batch.dtypes,
            ids=[(int(epoch), i) for i in batch.ids],
            seqlens=batch.seqlens, data=batch.data,
            metadata=batch.metadata)


def raw_ids(ids) -> list:
    """Strip epoch qualification (inverse of ``epoch_qualified`` for
    id lists): consumed-id skipping on resume compares against the
    dataset's raw ids."""
    return [i[1] if isinstance(i, tuple) and len(i) == 2 else i
            for i in ids]


def drop_ids(batch: "SequenceSample", skip_ids) -> Optional["SequenceSample"]:
    """Remove the batch elements whose id is in ``skip_ids`` (resume:
    data already consumed in the interrupted epoch, reference
    master_worker.py:762-768). Returns None when nothing survives."""
    skip = set(skip_ids)
    if not skip:
        return batch
    keep = [i for i, x in enumerate(batch.ids) if x not in skip]
    if not keep:
        return None
    if len(keep) == batch.bs:
        return batch
    parts = batch.unpack()
    return SequenceSample.gather([parts[i] for i in keep])


# ----------------------------------------------------------------------
# Dataset registry and loading utilities.
# ----------------------------------------------------------------------
@dataclasses.dataclass
class DatasetUtility:
    """Context handed to dataset constructors (reference util object):
    seed, dp rank/size for sharding, and the HF tokenizer."""
    seed: int
    dp_rank: int
    world_size: int
    tokenizer: Any


ALL_DATASET_CLASSES: Dict[str, Callable] = {}


def register_dataset(name: str, dataset_cls: Callable):
    if name in ALL_DATASET_CLASSES:
        raise ValueError(f"Dataset {name} already registered.")
    ALL_DATASET_CLASSES[name] = dataset_cls


def make_dataset(cfg, seed: int, dp_rank: int, world_size: int,
                 tokenizer_or_path: Any):
    """Instantiate a registered dataset (reference ``data_api.py:671``)."""
    from realhf_tpu.api.config import DatasetAbstraction
    if isinstance(cfg, str):
        cfg = DatasetAbstraction(type_=cfg)
    tokenizer = (load_hf_tokenizer(tokenizer_or_path)
                 if isinstance(tokenizer_or_path, str) else tokenizer_or_path)
    util = DatasetUtility(seed=seed, dp_rank=dp_rank, world_size=world_size,
                          tokenizer=tokenizer)
    return ALL_DATASET_CLASSES[cfg.type_](util=util, **cfg.args)


def load_hf_tokenizer(path: str, fast: bool = True, padding_side: str = "left"):
    import transformers
    tok = transformers.AutoTokenizer.from_pretrained(
        path, use_fast=fast, padding_side=padding_side, trust_remote_code=True)
    if tok.pad_token_id is None:
        tok.pad_token_id = tok.eos_token_id
    return tok


def require_record_fields(records: List[Dict], required: Tuple[str, ...],
                          loader: str, hint: str = "") -> List[Dict]:
    """Validate loaded records up front so a malformed file fails with
    the offending record named instead of a bare ``KeyError`` deep in
    tokenization/collation. ``required`` fields must be present and
    non-None on every record."""
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            raise ValueError(
                f"{loader}: record {i} is {type(rec).__name__}, expected "
                f"an object with fields {list(required)}.{hint}")
        missing = [f for f in required if rec.get(f) is None]
        if missing:
            ident = rec.get("id", f"index {i}")
            raise ValueError(
                f"{loader}: record {ident!r} is missing required field"
                f"{'s' if len(missing) > 1 else ''} {missing} "
                f"(present: {sorted(rec)}).{hint}")
    return records


def load_shuffle_split_dataset(util: DatasetUtility, dataset_path: str,
                               dataset_builder: Optional[Callable[[], List[Dict]]] = None
                               ) -> List[Dict]:
    """Load JSON/JSONL records, shuffle with the experiment seed, and
    take this DP rank's contiguous shard (reference ``data_api.py:631``)."""
    if dataset_path:
        if dataset_path.endswith(".jsonl"):
            with open(dataset_path) as f:
                records = [json.loads(line) for line in f if line.strip()]
        elif dataset_path.endswith(".json"):
            with open(dataset_path) as f:
                records = json.load(f)
        else:
            raise NotImplementedError(f"Unknown dataset format: {dataset_path}")
    else:
        assert dataset_builder is not None
        records = dataset_builder()
    if any("id" not in d for d in records):
        logger.warning("Dataset entries missing unique `id`; assigning "
                       "sequential ids.")
        for i, d in enumerate(records):
            d["id"] = i
    ids = [d["id"] for d in records]
    if len(set(ids)) != len(ids):
        raise ValueError("Dataset ids are not unique.")
    rng = _random.Random(util.seed)
    indices = list(range(len(records)))
    rng.shuffle(indices)
    shard = np.array_split(indices, util.world_size)[util.dp_rank]
    return [records[i] for i in shard]


class PackedDataLoader:
    """Iterates a map-style dataset in shuffled fixed-size batches of
    SequenceSamples gathered into one packed batch (reference
    ``data_api.py:761``)."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 seed: int = 0, drop_last: bool = False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        n = len(self.dataset)
        order = list(range(n))
        if self.shuffle:
            _random.Random(self.seed + self.epoch).shuffle(order)
        for i in range(0, n, self.batch_size):
            idx = order[i:i + self.batch_size]
            if self.drop_last and len(idx) < self.batch_size:
                break
            yield SequenceSample.gather([self.dataset[j] for j in idx])
        self.epoch += 1
