"""Dataflow graph of model function calls (MFCs).

Parity with reference ``realhf/api/core/dfg.py``: an algorithm (PPO,
DPO, ...) is a DAG whose nodes are MFCs -- generate / inference /
train_step on a named model -- and whose edges are resolved
automatically from input/output data keys. The graph is
framework-agnostic; the runtime walks it and dispatches each MFC onto
that MFC's device mesh.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple, Union

import networkx as nx

from realhf_tpu.api.config import (
    ModelFamily,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
    ModelName,
)
from realhf_tpu.base import logging

logger = logging.getLogger("dfg", "benchmark")


@dataclasses.dataclass
class OffloadHook:
    """Post-hook: offload the model's weights to host memory after the
    MFC completes (reference ``dfg.py:19``)."""


@dataclasses.dataclass
class ParamReallocHook:
    """Pre/post-hook: reshard weights between model replicas.

    Exactly one of ``source``/``target`` is set; the other side is the
    hooked MFC's own model. ``target = eta * source + (1-eta) * target``
    (eta=1 is plain overwrite; eta<1 implements EMA reference models).
    Reference ``dfg.py:24-46``.
    """
    source: Optional[ModelName] = None
    target: Optional[ModelName] = None
    eta: float = 1.0


RPCHook = Union[OffloadHook, ParamReallocHook]


@dataclasses.dataclass
class MFCDef:
    """One model function call node (reference ``dfg.py:52``).

    :param name: unique node name.
    :param n_seqs: batch size in sequences pulled from the buffer.
        PER-MFC: the per-sample SequenceBuffer assembles each MFC's
        batch from whichever ready samples exist (possibly spanning
        dataset batches), so producer and consumer n_seqs need only
        SHARE samples, not be equal -- generation can stream at 2x the
        train batch while training drains at 1x. The graft-lint
        ``dfg-batch-mismatch`` checker validates each MFC's n_seqs
        against the buffer-capacity contract.
    :param interface_type: generate / inference / train_step.
    :param interface_impl: registry config of the algorithm interface.
    :param model_name: which model executes this call (str role is
        promoted to ``ModelName(role, 0)``).
    :param input_keys / output_keys: data keys for dependency edges.
    :param input_key_remap / output_key_remap: rename keys between the
        graph-level naming and the interface implementation's naming.
    :param n_mbs: number of microbatches when executing.
    :param balanced_dp: if True split exactly n_seqs/dp sequences per DP
        shard; otherwise balance by token count.
    """

    name: str
    n_seqs: int
    interface_type: ModelInterfaceType
    interface_impl: ModelInterfaceAbstraction
    model_name: Union[str, ModelName]

    input_keys: Tuple = dataclasses.field(default_factory=tuple)
    input_key_remap: Dict[str, str] = dataclasses.field(default_factory=dict)
    output_keys: Tuple = dataclasses.field(default_factory=tuple)
    output_key_remap: Dict[str, str] = dataclasses.field(default_factory=dict)

    n_mbs: Optional[int] = None
    balanced_dp: bool = False
    log_return_value: bool = False

    model_type: Optional[ModelFamily] = None
    model_path: Optional[str] = None

    # Filled by build_graph; not user-set.
    _G: Optional[nx.DiGraph] = None
    _pre_hooks: List[RPCHook] = dataclasses.field(default_factory=list)
    _post_hooks: List[RPCHook] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if isinstance(self.model_name, str):
            self.model_name = ModelName(role=self.model_name, replica_id=0)

    def __repr__(self):
        return f"MFCDef[{self.name}]"

    def __hash__(self):
        return hash(self.name)

    @property
    def role(self) -> str:
        return self.model_name.role

    def add_pre_hook(self, h: RPCHook):
        if isinstance(h, OffloadHook):
            raise ValueError("Offload can only be a post hook.")
        if isinstance(h, ParamReallocHook):
            assert (h.source is None) != (h.target is None)
        self._pre_hooks.append(h)

    def add_post_hook(self, h: RPCHook):
        if isinstance(h, ParamReallocHook):
            assert (h.source is None) != (h.target is None)
        self._post_hooks.append(h)

    @property
    def is_src(self) -> bool:
        return len(list(self._G.predecessors(self.name))) == 0

    @property
    def is_dst(self) -> bool:
        return len(list(self._G.successors(self.name))) == 0

    @property
    def data_producers(self) -> Dict[str, "MFCDef"]:
        return self._G.graph["data_producers"]

    @property
    def data_consumers(self) -> Dict[str, List["MFCDef"]]:
        return self._G.graph["data_consumers"]

    @property
    def parents(self) -> List["MFCDef"]:
        return [self._G.nodes[x]["object"] for x in self._G.predecessors(self.name)]

    @property
    def children(self) -> List["MFCDef"]:
        return [self._G.nodes[x]["object"] for x in self._G.successors(self.name)]

    def all_successors(self) -> List["MFCDef"]:
        names = list(nx.dfs_preorder_nodes(self._G, self.name))
        names.remove(self.name)
        return [self._G.nodes[x]["object"] for x in names]

    @property
    def is_dst_of_model_role(self) -> bool:
        """True iff no (transitive) successor runs on the same model
        role -- i.e. this MFC is the last user of these weights in a
        step, so realloc/offload hooks may follow it."""
        return not any(r.role == self.role for r in self.all_successors())


def build_graph(nodes: List[MFCDef], verbose: bool = False) -> nx.DiGraph:
    """Resolve edges from data keys (reference ``dfg.py:238``).

    An edge A->B exists iff some output key of A is an input key of B.
    Keys produced by no node are assumed to come from the dataset.
    """
    if len({n.name for n in nodes}) != len(nodes):
        raise ValueError(f"Duplicate MFC names: {[n.name for n in nodes]}")

    G = nx.DiGraph()
    G.add_nodes_from([(n.name, dict(object=n)) for n in nodes])

    data_producers: Dict[str, MFCDef] = {}
    data_consumers: Dict[str, List[MFCDef]] = {}
    for node in nodes:
        for k in node.output_keys:
            if k in data_producers:
                raise ValueError(
                    f"Data key `{k}` produced by both "
                    f"{data_producers[k].name} and {node.name}.")
            data_producers[k] = node
        for k in node.input_keys:
            data_consumers.setdefault(k, []).append(node)

    for node in nodes:
        for k in node.input_keys:
            if k in data_producers:
                G.add_edge(data_producers[k].name, node.name, key=k)

    G.graph["data_producers"] = data_producers
    G.graph["data_consumers"] = data_consumers
    for node in nodes:
        node._G = G
    if not nx.is_directed_acyclic_graph(G):
        raise ValueError("The MFC graph contains a cycle.")
    if verbose:
        for node in nodes:
            logger.info("%s: parents=%s children=%s", node.name,
                        [p.name for p in node.parents],
                        [c.name for c in node.children])
    return G


class DFG:
    """Convenience wrapper bundling nodes + resolved graph."""

    def __init__(self, nodes: List[MFCDef]):
        self.nodes = list(nodes)
        self.G = build_graph(self.nodes)

    def __iter__(self):
        return iter(self.nodes)

    def find(self, name: str) -> MFCDef:
        return self.G.nodes[name]["object"]

    @property
    def sources(self) -> List[MFCDef]:
        return [n for n in self.nodes if n.is_src]

    @property
    def sinks(self) -> List[MFCDef]:
        return [n for n in self.nodes if n.is_dst]

    def topological_order(self) -> List[MFCDef]:
        return [self.G.nodes[x]["object"] for x in nx.topological_sort(self.G)]

    def topological_levels(self) -> List[List[MFCDef]]:
        """Antichain levels: every node's producers live in earlier
        levels, so all nodes WITHIN a level are mutually independent
        and may execute concurrently (the distributed master exploits
        this across workers, master_worker.py dispatch; the inline
        runner across threads)."""
        return [[self.G.nodes[x]["object"] for x in gen]
                for gen in nx.topological_generations(self.G)]

    @property
    def dataset_keys(self) -> List[str]:
        """Input keys that no MFC produces -- they must come from the
        dataset (reference master_worker data loading)."""
        produced = set(self.G.graph["data_producers"])
        needed = []
        for n in self.nodes:
            for k in n.input_keys:
                if k not in produced and k not in needed:
                    needed.append(k)
        return needed
