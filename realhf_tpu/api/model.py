"""Model API: the Model wrapper, ModelInterface ABC, and registries.

Parity with reference ``realhf/api/core/model_api.py``: a `Model`
bundles one LLM instance (engine + tokenizer + version counters); a
`ModelInterface` implements the algorithm-specific handlers
(generate / inference / train_step / evaluate / save) that MFC nodes
reference by registry name (register_interface, model_api.py:641-658).
"""

import abc
import dataclasses
from typing import Any, Callable, Dict, Optional

from realhf_tpu.api.config import (
    ModelInterfaceAbstraction,
    ModelName,
)
from realhf_tpu.api.data import SequenceSample


@dataclasses.dataclass
class ModelVersion:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0

    def inc(self):
        self.epoch_step += 1
        self.global_step += 1


@dataclasses.dataclass
class Model:
    """One LLM instance living on a mesh (reference model_api.py:470)."""
    name: ModelName
    engine: Any  # realhf_tpu.engine.engine.Engine
    tokenizer: Any
    hf_family: str = "llama"
    version: ModelVersion = dataclasses.field(default_factory=ModelVersion)

    @property
    def config(self):
        return self.engine.cfg

    def inc_version(self):
        self.version.inc()


class ModelInterface(abc.ABC):
    """Algorithm handlers; all default to unimplemented
    (reference model_api.py:605-640)."""

    def save(self, model: Model, save_dir: str, host_params=None,
             writer: bool = True):
        """``host_params``, when given, is a pre-gathered host copy of
        the weights (``Engine.params_numpy()``); without it the save
        streams layer-by-layer from the device arrays. On a
        multi-process mesh the streamed save is a COLLECTIVE: the
        ModelHost calls it on every group member with ``writer=True``
        only on the leader, which alone writes files (see
        ModelHost.save_role)."""
        pass

    def evaluate(self, model: Model, eval_dataloader) -> Dict:
        return {}

    def inference(self, model: Model, input_: SequenceSample,
                  n_mbs: Optional[int] = None) -> SequenceSample:
        raise NotImplementedError()

    def generate(self, model: Model, input_: SequenceSample,
                 n_mbs: Optional[int] = None) -> SequenceSample:
        raise NotImplementedError()

    def train_step(self, model: Model, input_: SequenceSample,
                   n_mbs: Optional[int] = None) -> Dict:
        raise NotImplementedError()

    # Profiler hook (reference model_api.py:619): build synthetic inputs.
    def mock(self, interface_type: str, model: Model,
             input_: SequenceSample) -> SequenceSample:
        raise NotImplementedError()


ALL_INTERFACE_CLASSES: Dict[str, Callable[..., ModelInterface]] = {}


def register_interface(name: str, cls):
    if name in ALL_INTERFACE_CLASSES:
        raise ValueError(f"Interface {name} already registered.")
    ALL_INTERFACE_CLASSES[name] = cls


def make_interface(cfg: ModelInterfaceAbstraction) -> ModelInterface:
    return ALL_INTERFACE_CLASSES[cfg.type_](**cfg.args)
