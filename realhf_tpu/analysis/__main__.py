"""graft-lint CLI.

::

    python -m realhf_tpu.analysis [paths...]
        [--checker NAME ...]        # default: all families
        [--baseline FILE]           # default: scripts/lint_baseline.json
        [--fail-on-new]             # exit 1 only on findings beyond
                                    # the baseline
        [--write-baseline]          # accept the current findings
        [--format text|json]
        [--no-dfg]                  # skip the import-time DFG pass
        [--diff [REF]]              # only report on files changed vs
                                    # a git ref (default HEAD); skips
                                    # the project-wide passes except
                                    # the ones that declare the
                                    # changed files relevant (wire,
                                    # model) -- the fast pre-commit
                                    # mode
        [--no-cache] [--cache-dir D]

Default paths: the ``realhf_tpu`` package under the current directory.
Results are cached under ``.graft_lint_cache/`` (content-hash keyed;
see docs/static_analysis.md "Caching") unless ``--no-cache``.
Exit codes: 0 = clean (or informational run), 1 = new findings with
``--fail-on-new``, 2 = usage error.
"""

import argparse
import json
import os
import subprocess
import sys

from realhf_tpu.analysis import (
    CHECKER_CLASSES,
    ENGINE_VERSION,
    AnalysisCache,
    ProjectChecker,
    all_checkers,
    diff_against_baseline,
    load_baseline,
    run_analysis,
    write_baseline,
)
from realhf_tpu.analysis.cache import CACHE_DIR_NAME

DEFAULT_BASELINE = os.path.join("scripts", "lint_baseline.json")


def _changed_files(ref: str, within):
    """Repo-relative .py files changed vs ``ref`` (committed diff +
    working tree + untracked), filtered to the scan paths."""
    out = set()
    for argv in (["git", "diff", "--name-only", ref, "--", "*.py"],
                 ["git", "ls-files", "--others", "--exclude-standard",
                  "--", "*.py"]):
        proc = subprocess.run(argv, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"`{' '.join(argv)}` failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        out.update(ln.strip() for ln in proc.stdout.splitlines()
                   if ln.strip())
    roots = [os.path.normpath(p) for p in within]
    picked = []
    for f in sorted(out):
        norm = os.path.normpath(f)
        if not os.path.exists(norm):
            continue  # deleted files have nothing to lint
        if any(norm == r or norm.startswith(r + os.sep)
               for r in roots):
            picked.append(norm)
    return picked


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m realhf_tpu.analysis",
        description="graft-lint: framework-aware static analysis "
                    "(docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to scan (default: "
                         "./realhf_tpu)")
    ap.add_argument("--checker", action="append", default=None,
                    choices=sorted(CHECKER_CLASSES),
                    help="run only this family (repeatable)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--fail-on-new", action="store_true",
                    help="diff against the baseline; exit 1 on NEW "
                         "findings only")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the accepted "
                         "baseline and exit 0")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--no-dfg", action="store_true",
                    help="skip the import-time dfg-invariants pass "
                         "(e.g. scanning a fixture tree)")
    ap.add_argument("--diff", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="only report on .py files changed vs the git "
                         "ref (default HEAD); the call graph still "
                         "spans the whole package, and project-wide "
                         "passes are skipped unless they declare the "
                         "changed files relevant (wire runs on "
                         "serving/ edits, model on router_shard.py "
                         "edits)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the on-disk result cache")
    ap.add_argument("--cache-dir", default=CACHE_DIR_NAME,
                    help=f"cache location (default {CACHE_DIR_NAME})")
    args = ap.parse_args(argv)

    try:
        checkers = all_checkers(args.checker)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 2
    if args.no_dfg:
        checkers = [c for c in checkers
                    if c.name != "dfg-invariants"]

    paths = args.paths or ["realhf_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {missing}", file=sys.stderr)
        return 2

    project_paths = None
    if args.diff is not None:
        try:
            changed = _changed_files(args.diff, paths)
        except (OSError, RuntimeError) as e:
            print(f"--diff {args.diff}: {e}", file=sys.stderr)
            return 2
        if not changed:
            print(f"graft-lint: no changed .py files vs {args.diff}.")
            return 0
        # fast pre-commit mode: report on changed files only; the
        # whole-project import-time passes don't decompose per file
        # and are skipped -- except the narrow-scope ones (wire,
        # model) that declare the changed files relevant
        checkers = [c for c in checkers
                    if not isinstance(c, ProjectChecker)
                    or c.diff_relevant(changed)]
        project_paths, paths = paths, changed

    cache = None if args.no_cache else AnalysisCache(
        args.cache_dir, ENGINE_VERSION)
    findings = run_analysis(paths, checkers,
                            project_paths=project_paths, cache=cache)

    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(f"Wrote {len(findings)} accepted finding(s) to "
              f"{args.baseline}.")
        return 0

    if args.fail_on_new:
        baseline = load_baseline(args.baseline)
        new, fixed = diff_against_baseline(findings, baseline)
        if args.format == "json":
            print(json.dumps({
                "new": [f.to_json() for f in new],
                "fixed_fingerprints": fixed,
                "total": len(findings),
            }, indent=1))
        else:
            for f in new:
                print(f"NEW {f.format()}")
            if fixed:
                print(f"note: {len(fixed)} baseline entr"
                      f"{'y is' if len(fixed) == 1 else 'ies are'} "
                      "fixed; regenerate with --write-baseline to "
                      "prune.")
            print(f"graft-lint: {len(findings)} finding(s), "
                  f"{len(new)} new vs baseline "
                  f"({os.path.relpath(args.baseline)}).")
        return 1 if new else 0

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=1))
    else:
        for f in findings:
            print(f.format())
        print(f"graft-lint: {len(findings)} finding(s).")
    return 0


if __name__ == "__main__":
    sys.exit(main())
