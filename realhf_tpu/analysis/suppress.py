"""Per-file and per-line suppression for graft-lint.

Syntax (docs/static_analysis.md):

- ``# graft-lint: disable=<code>[,<code>...]`` on the flagged line
  (or, for multi-line statements, on the statement's first line)
  suppresses those codes there. ``disable=all`` suppresses everything.
- ``# graft-lint: disable-file=<code>[,<code>...]`` anywhere in the
  file suppresses those codes for the whole file.

Codes may be full rule ids (``purity-host-sync``) or checker family
names (``jax-purity``) -- a family name suppresses every rule in it.
"""

import re
from typing import Dict, List, Set

_LINE_RE = re.compile(r"#\s*graft-lint:\s*disable=([\w\-,\s]+)")
_FILE_RE = re.compile(r"#\s*graft-lint:\s*disable-file=([\w\-,\s]+)")


class Suppressions:
    """Parsed suppression directives of one source file."""

    def __init__(self, source: str):
        self.file_codes: Set[str] = set()
        self.line_codes: Dict[int, Set[str]] = {}
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = _FILE_RE.search(text)
            if m:
                self.file_codes |= _split(m.group(1))
                continue
            m = _LINE_RE.search(text)
            if m:
                self.line_codes.setdefault(lineno, set()).update(
                    _split(m.group(1)))

    def is_suppressed(self, code: str, checker: str, line: int) -> bool:
        for scope in (self.file_codes,
                      self.line_codes.get(line, ())):
            if not scope:
                continue
            if "all" in scope or code in scope or checker in scope:
                return True
        return False

    def filter(self, findings: List) -> List:
        return [f for f in findings
                if not self.is_suppressed(f.code, f.checker, f.line)]


def _split(raw: str) -> Set[str]:
    return {p.strip() for p in raw.split(",") if p.strip()}
