"""Baseline load/diff/write for graft-lint.

The baseline (``scripts/lint_baseline.json``) records the ACCEPTED
findings of the current tree as fingerprint -> count (plus one example
per fingerprint for humans). ``--fail-on-new`` exits nonzero only on
findings beyond the baseline, so the gate ratchets: new debt is
blocked, old debt shrinks as fixes land (regenerate with
``--write-baseline`` after fixing; stale entries are pruned).
"""

import json
import os
from typing import Dict, List, Tuple

from realhf_tpu.analysis.finding import Finding, count_by_fingerprint

BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[str, int]:
    """fingerprint -> accepted count; {} for a missing file."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[str, int] = {}
    for fp, entry in data.get("findings", {}).items():
        out[fp] = int(entry.get("count", 1)) if isinstance(entry, dict) \
            else int(entry)
    return out


def write_baseline(path: str, findings: List[Finding]) -> None:
    counts = count_by_fingerprint(findings)
    examples = {}
    for f in findings:
        examples.setdefault(f.fingerprint, f)
    payload = {
        "version": BASELINE_VERSION,
        "findings": {
            fp: {
                "count": counts[fp],
                "code": examples[fp].code,
                "path": examples[fp].path,
                "symbol": examples[fp].symbol,
                "message": examples[fp].message,
            }
            for fp in sorted(counts)
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def diff_against_baseline(
    findings: List[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], List[str]]:
    """(new_findings, fixed_fingerprints).

    A fingerprint present N times in the baseline admits N current
    occurrences; the (N+1)-th and later are new. Baseline entries with
    no current occurrence are reported as fixed (prune by
    regenerating the baseline).
    """
    budget = dict(baseline)
    new: List[Finding] = []
    for f in findings:  # findings arrive location-sorted
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
        else:
            new.append(f)
    fixed = sorted(fp for fp, n in budget.items() if n > 0)
    return new, fixed
