"""lockorder checker: global lock-acquisition ordering, closed over
the call graph.

PR 3's ``conc-lock-blocking`` sees one function at a time; this
family sees the whole package. From every ``with <lock>:`` scope it
derives

- a **lock identity**: ``self._x`` locks key on the enclosing class
  (``mod:Class._x`` -- every instance of the class orders its locks
  the same way), module-level locks on ``mod:name``, and function
  locals on ``mod:func.name``;
- **ordering edges**: lock B acquired (lexically, or inside any
  function reachable through the call graph, depth-bounded) while
  lock A is held adds the edge A -> B.

Two rules:

- ``conc-lock-cycle``: the global ordering graph has a cycle -- two
  threads taking the same locks in opposite orders deadlock. Each
  cycle is reported once, at its lexicographically-first witness
  acquisition, naming the full cycle.
- ``conc-lock-blocking`` (interprocedural extension): while a lock is
  held, a call to a project function that TRANSITIVELY performs a
  blocking operation (``time.sleep``, subprocess, ZMQ send/recv,
  socket connect/accept, ``name_resolve.wait``) -- the same stall the
  direct rule catches, hidden one or more calls deep. The direct
  (same-function) case stays with the ``concurrency`` family; this
  rule only fires when the blocking call is in a callee, and names
  the call chain.

Unresolvable lock expressions (``self.obj.locks[k]`` subscripts,
calls) are skipped entirely -- no identity, no edge, no guess.
"""

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from realhf_tpu.analysis.core import GraphChecker, Module, dotted_name
from realhf_tpu.analysis.finding import Finding

_LOCKISH = re.compile(r"lock|mutex", re.IGNORECASE)

#: transitive blocking triggers: exact dotted calls...
_BLOCKING_CALLS = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen", "name_resolve.wait",
    "name_resolve.get_subtree", "socket.create_connection",
}
#: ... and method names unambiguous enough to trust on any receiver
#: (bare ``join``/``wait`` stay out: str.join / Event.wait-with-
#: timeout would drown the rule in noise)
_BLOCKING_METHODS = {
    "send_multipart", "send_pyobj", "send_string", "send_json",
    "recv", "recv_multipart", "recv_pyobj", "recv_string",
    "recv_json", "accept",
}


def _lock_expr_key(expr: ast.AST, mod: str, cls: Optional[str],
                   func: str, module_globals) -> Optional[str]:
    """Canonical identity of a lock expression, or None when the
    expression cannot be pinned to one lock object."""
    dotted = dotted_name(expr)
    if not dotted or not _LOCKISH.search(dotted):
        return None
    parts = dotted.split(".")
    if parts[0] == "self":
        if cls is None or len(parts) != 2:
            return None
        return f"{mod}:{cls}.{parts[1]}"
    if len(parts) == 1:
        if parts[0] in module_globals:
            return f"{mod}:{parts[0]}"  # one lock per module
        return f"{mod}:{func}.{parts[0]}"
    return f"{mod}:{dotted}"


class LockOrderChecker(GraphChecker):
    name = "lockorder"

    def __init__(self):
        self.index = None
        self._blocking_summaries: Dict[str, Optional[str]] = {}
        #: lock graph: edge (A, B) -> witness (relpath, node, symbol)
        self._edges: Dict[Tuple[str, str], Tuple] = {}
        #: per-module findings computed once for the whole project
        self._by_module: Optional[Dict[str, List[Finding]]] = None

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith((
            "realhf_tpu/system/", "realhf_tpu/serving/",
            "realhf_tpu/base/", "realhf_tpu/apps/",
            "realhf_tpu/parallel/", "realhf_tpu/engine/",
            "realhf_tpu/obs/"))

    # ------------------------------------------------------------------
    def prepare(self, index) -> None:
        self.index = index
        self._by_module = None
        self._edges = {}
        self._blocking_summaries = {}

    def check(self, module: Module) -> List[Finding]:
        if self.index is None:
            from realhf_tpu.analysis.callgraph import ProjectIndex
            self.index = ProjectIndex([module])
        if self._by_module is None:
            self._by_module = self._analyze_project()
        return self._by_module.get(module.relpath, [])

    # ------------------------------------------------------------------
    def _direct_blocking(self, qual: str) -> Optional[str]:
        """Name of a blocking call the function performs directly."""
        for call in self.index.calls_in(qual):
            nm = dotted_name(call.func)
            if nm in _BLOCKING_CALLS:
                return nm
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _BLOCKING_METHODS:
                return f".{call.func.attr}"
        return None

    def _blocking_chain(self, qual: str,
                        max_depth: int = 4) -> Optional[List[str]]:
        """Call chain from ``qual`` (inclusive) to a function with a
        direct blocking call, or None."""
        def blocks(q: str) -> bool:
            if q not in self._blocking_summaries:
                self._blocking_summaries[q] = self._direct_blocking(q)
            return self._blocking_summaries[q] is not None

        if blocks(qual):
            return [qual]
        chain = self.index.reaches(qual, blocks, max_depth=max_depth)
        return chain

    # ------------------------------------------------------------------
    def _analyze_project(self) -> Dict[str, List[Finding]]:
        # sweep every indexed function once: collect lexical lock
        # scopes, ordering edges, and interprocedural blocking calls
        by_module: Dict[str, List[Finding]] = {}
        #: qual -> locks acquired anywhere inside (for interproc
        #: ordering edges); computed in the same sweep
        acquired_in: Dict[str, Set[str]] = {}
        #: (holder qual, held key, call node, callee qual) to check
        #: for interprocedural blocking/acquisition
        held_calls: List[Tuple] = []

        for qual in sorted(self.index.funcs):
            info = self.index.funcs[qual]
            mod, cls, fname = info.module, info.cls, info.name
            cls_name = cls.split(":", 1)[1] if cls else None
            acquired: Set[str] = set()
            module_rel = info.relpath
            mod_globals = self.index.module_globals.get(mod, set())

            def visit(node, held: Tuple[str, ...]):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef, ast.Lambda)):
                    return
                new_held = held
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    keys = []
                    for item in node.items:
                        key = _lock_expr_key(item.context_expr, mod,
                                             cls_name, fname,
                                             mod_globals)
                        if key is not None:
                            keys.append((key, item.context_expr))
                    for key, expr in keys:
                        acquired.add(key)
                        for h in held:
                            if h != key:
                                self._edges.setdefault(
                                    (h, key),
                                    (module_rel, expr, qual))
                        new_held = new_held + (key,)
                if isinstance(node, ast.Call) and held:
                    callee = self.index.resolve_call(node, info)
                    if callee is not None:
                        for h in held:
                            held_calls.append((qual, h, node, callee,
                                               module_rel))
                for child in ast.iter_child_nodes(node):
                    visit(child, new_held)

            for stmt in info.node.body:
                visit(stmt, ())
            acquired_in[qual] = acquired

        # interprocedural closure: locks acquired by (transitive)
        # callees order after the held lock; blocking callees report
        def transitive_locks(qual: str, depth: int = 3,
                             _seen=None) -> Set[str]:
            _seen = _seen if _seen is not None else set()
            if qual in _seen or depth < 0:
                return set()
            _seen.add(qual)
            out = set(acquired_in.get(qual, ()))
            for callee in self.index.callees(qual):
                out |= transitive_locks(callee, depth - 1, _seen)
            return out

        reported_blocking: Set[Tuple[str, str, str]] = set()
        for holder, held_key, call, callee, module_rel in held_calls:
            for lock in sorted(transitive_locks(callee)):
                if lock != held_key:
                    self._edges.setdefault(
                        (held_key, lock), (module_rel, call, holder))
            chain = self._blocking_chain(callee)
            if chain is not None:
                key = (holder, held_key, callee)
                if key in reported_blocking:
                    continue
                reported_blocking.add(key)
                what = self._blocking_summaries.get(chain[-1]) or "?"
                via = " -> ".join(q.split(":", 1)[1] for q in chain)
                by_module.setdefault(module_rel, []).append(Finding(
                    checker=self.name, code="conc-lock-blocking",
                    path=module_rel,
                    line=getattr(call, "lineno", 0),
                    col=getattr(call, "col_offset", 0),
                    message=(f"call to `{via}` while holding "
                             f"`{held_key}`: it transitively performs "
                             f"blocking `{what}` -- a stalled peer "
                             "then stalls every thread contending "
                             "for the lock"),
                    symbol=holder.split(":", 1)[1]))

        # cycle detection over the ordering graph
        for cycle in self._find_cycles():
            edge = (cycle[0], cycle[1 % len(cycle)])
            witness = self._edges.get(edge)
            if witness is None:
                continue
            module_rel, node, qual = witness
            pretty = " -> ".join(cycle + [cycle[0]])
            by_module.setdefault(module_rel, []).append(Finding(
                checker=self.name, code="conc-lock-cycle",
                path=module_rel,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                message=(f"lock-order cycle {pretty}: two threads "
                         "taking these locks in opposite orders "
                         "deadlock; pick one global order"),
                symbol=qual.split(":", 1)[1]))
        for rel in by_module:
            by_module[rel].sort(key=lambda f: (f.line, f.code))
        return by_module

    # ------------------------------------------------------------------
    def _find_cycles(self) -> List[List[str]]:
        """Elementary cycles of the lock graph, canonicalized (each
        reported once, rotated to start at its smallest key)."""
        graph: Dict[str, Set[str]] = {}
        for a, b in self._edges:
            graph.setdefault(a, set()).add(b)
        cycles: Set[Tuple[str, ...]] = set()

        def dfs(start: str, cur: str, path: List[str],
                seen: Set[str]):
            for nxt in sorted(graph.get(cur, ())):
                if nxt == start and len(path) > 1:
                    i = path.index(min(path))
                    cycles.add(tuple(path[i:] + path[:i]))
                elif nxt not in seen and len(path) < 8:
                    seen.add(nxt)
                    dfs(start, nxt, path + [nxt], seen)
                    seen.discard(nxt)

        for start in sorted(graph):
            dfs(start, start, [start], {start})
        return [list(c) for c in sorted(cycles)]
