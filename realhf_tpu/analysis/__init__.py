"""graft-lint: framework-aware static analysis for realhf_tpu.

Checker families guard the invariants the runtime's correctness
rests on (docs/static_analysis.md):

- ``jax-purity``: no host syncs / impure calls under JAX tracing, no
  per-iteration host transfers in decode hot paths.
- ``concurrency``: no blocking calls under locks, no unsynchronized
  cross-thread fields, no unjoined non-daemon threads.
- ``lockorder``: interprocedural lock discipline over the project
  call graph -- lock-order cycles (deadlocks) and transitively
  blocking calls while a lock is held.
- ``collective-determinism``: no unordered iteration feeding sharding
  layouts, collectives, or name_resolve keys.
- ``lifecycle``: paired-operation discipline (KV-pool blocks, prefix
  pins, sockets, threads, staged checkpoints) on every CFG exit
  path, including exceptional ones.
- ``terminal``: exactly-once terminal delivery in the serving
  protocol handlers -- no rid retired from a live table without a
  terminal event, no route dropped before its send succeeded.
- ``dfg-invariants``: registered experiment DFGs are acyclic, edge-
  and mesh-compatible, with totally ordered weight reallocations.
- ``obs-metric-name``: literal metric names are snake_case, counters
  end ``_total``, duration histograms/summaries end
  ``_secs``/``_seconds``.
- ``obs-catalog``: the docs/observability.md metric catalog and the
  instrumented call sites agree, in both directions.
- ``wire``: every serving-plane send/handle site uses kinds, payload
  fields, reasons and request arities declared in
  ``serving/protocol.py``, and the declared protocol is fully
  emitted, fully handled, and FSM-covered in both directions.
- ``model``: bounded explicit-state model checking of the declared
  failover state machines against the guard profile extracted from
  ``serving/router_shard.py`` -- exactly-once terminals, no
  fenced-epoch delivery, journal drained, no parked-forever
  terminal, each violation reported with a replayable trace.

CLI: ``python -m realhf_tpu.analysis [--fail-on-new] [--baseline F]
[--checker NAME] [--diff REF] [paths...]`` -- see ``__main__.py``.
"""

from realhf_tpu.analysis.baseline import (  # noqa: F401
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
from realhf_tpu.analysis.cache import AnalysisCache  # noqa: F401
from realhf_tpu.analysis.concurrency import ConcurrencyChecker
from realhf_tpu.analysis.core import (  # noqa: F401
    ENGINE_VERSION,
    AstChecker,
    GraphChecker,
    Module,
    ProjectChecker,
    run_analysis,
)
from realhf_tpu.analysis.determinism import DeterminismChecker
from realhf_tpu.analysis.dfg_invariants import DfgInvariantsChecker
from realhf_tpu.analysis.explore import ModelChecker
from realhf_tpu.analysis.finding import Finding  # noqa: F401
from realhf_tpu.analysis.jax_purity import JaxPurityChecker
from realhf_tpu.analysis.lifecycle import LifecycleChecker
from realhf_tpu.analysis.lockorder import LockOrderChecker
from realhf_tpu.analysis.obs_catalog import ObsCatalogChecker
from realhf_tpu.analysis.obs_metrics import ObsMetricNameChecker
from realhf_tpu.analysis.terminal import TerminalChecker
from realhf_tpu.analysis.wire import WireChecker

#: family name -> checker class, in documentation order
CHECKER_CLASSES = {
    JaxPurityChecker.name: JaxPurityChecker,
    ConcurrencyChecker.name: ConcurrencyChecker,
    LockOrderChecker.name: LockOrderChecker,
    DeterminismChecker.name: DeterminismChecker,
    LifecycleChecker.name: LifecycleChecker,
    TerminalChecker.name: TerminalChecker,
    DfgInvariantsChecker.name: DfgInvariantsChecker,
    ObsMetricNameChecker.name: ObsMetricNameChecker,
    ObsCatalogChecker.name: ObsCatalogChecker,
    WireChecker.name: WireChecker,
    ModelChecker.name: ModelChecker,
}


def all_checkers(names=None):
    """Instantiate the requested checker families (all by default)."""
    if names:
        unknown = sorted(set(names) - set(CHECKER_CLASSES))
        if unknown:
            raise ValueError(
                f"unknown checker(s) {unknown}; "
                f"available: {sorted(CHECKER_CLASSES)}")
        return [CHECKER_CLASSES[n]() for n in names]
    return [cls() for cls in CHECKER_CLASSES.values()]
