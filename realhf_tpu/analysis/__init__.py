"""graft-lint: framework-aware static analysis for realhf_tpu.

Four checker families guard the invariants the runtime's correctness
rests on (docs/static_analysis.md):

- ``jax-purity``: no host syncs / impure calls under JAX tracing, no
  per-iteration host transfers in decode hot paths.
- ``concurrency``: no blocking calls under locks, no unsynchronized
  cross-thread fields, no unjoined non-daemon threads.
- ``collective-determinism``: no unordered iteration feeding sharding
  layouts, collectives, or name_resolve keys.
- ``dfg-invariants``: registered experiment DFGs are acyclic, edge-
  and mesh-compatible, with totally ordered weight reallocations.
- ``obs-metric-name``: literal metric names are snake_case, counters
  end ``_total``, duration histograms/summaries end
  ``_secs``/``_seconds``.

CLI: ``python -m realhf_tpu.analysis [--fail-on-new] [--baseline F]
[--checker NAME] [paths...]`` -- see ``__main__.py``.
"""

from realhf_tpu.analysis.baseline import (  # noqa: F401
    diff_against_baseline,
    load_baseline,
    write_baseline,
)
from realhf_tpu.analysis.concurrency import ConcurrencyChecker
from realhf_tpu.analysis.core import (  # noqa: F401
    AstChecker,
    Module,
    ProjectChecker,
    run_analysis,
)
from realhf_tpu.analysis.determinism import DeterminismChecker
from realhf_tpu.analysis.dfg_invariants import DfgInvariantsChecker
from realhf_tpu.analysis.finding import Finding  # noqa: F401
from realhf_tpu.analysis.jax_purity import JaxPurityChecker
from realhf_tpu.analysis.obs_metrics import ObsMetricNameChecker

#: family name -> checker class, in documentation order
CHECKER_CLASSES = {
    JaxPurityChecker.name: JaxPurityChecker,
    ConcurrencyChecker.name: ConcurrencyChecker,
    DeterminismChecker.name: DeterminismChecker,
    DfgInvariantsChecker.name: DfgInvariantsChecker,
    ObsMetricNameChecker.name: ObsMetricNameChecker,
}


def all_checkers(names=None):
    """Instantiate the requested checker families (all by default)."""
    if names:
        unknown = sorted(set(names) - set(CHECKER_CLASSES))
        if unknown:
            raise ValueError(
                f"unknown checker(s) {unknown}; "
                f"available: {sorted(CHECKER_CLASSES)}")
        return [CHECKER_CLASSES[n]() for n in names]
    return [cls() for cls in CHECKER_CLASSES.values()]
