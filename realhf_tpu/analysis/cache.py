"""AST+result cache for graft-lint (docs/static_analysis.md).

The full gate must stay cheap on a 1-vCPU box, so analysis results
persist under ``.graft_lint_cache/`` (gitignored) between runs:

- **per-file findings** key on ``(relpath, content sha1, checker)`` --
  an unchanged file re-runs nothing, an edited file re-runs only
  itself;
- **whole-tree findings** (interprocedural families and cacheable
  project checkers) key on a stamp over every scanned file's content
  hash -- any edit re-runs those families over the tree, an unchanged
  tree skips them (and skips parsing) entirely;
- ``ENGINE_VERSION`` (:mod:`realhf_tpu.analysis.core`) is part of the
  payload: a version bump discards the whole cache.

Content hashes -- not mtimes -- are the key: reading+hashing a file
is cheap next to parsing and checking it, and hashes cannot go stale
on coarse filesystem timestamps. The cache is a single pickle; a
corrupt or unreadable file silently degrades to a cold run (a cache
must never break the gate).
"""

import os
import pickle
import tempfile
from typing import Dict, List, Optional

from realhf_tpu.analysis.finding import Finding

CACHE_DIR_NAME = ".graft_lint_cache"
_CACHE_FILE = "results.pkl"


class AnalysisCache:
    """Findings cache for one analysis run (see module doc)."""

    def __init__(self, dir_path: str, engine_version: int):
        self.dir_path = dir_path
        self.engine_version = engine_version
        self.path = os.path.join(dir_path, _CACHE_FILE)
        self.stats = dict(file_hits=0, file_misses=0,
                          project_hit=False, loaded=False)
        self._dirty = False
        self._data = self._load()

    # ------------------------------------------------------------------
    def _load(self) -> Dict:
        empty = {"engine": self.engine_version, "local": {},
                 "project": {"stamp": None, "by_checker": {}}}
        try:
            with open(self.path, "rb") as f:
                data = pickle.load(f)
        except (OSError, pickle.PickleError, EOFError, AttributeError,
                ImportError, IndexError):
            return empty
        if not isinstance(data, dict) \
                or data.get("engine") != self.engine_version:
            return empty
        self.stats["loaded"] = True
        return data

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            os.makedirs(self.dir_path, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.dir_path,
                                       suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                pickle.dump(self._data, f,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self.path)
            self._dirty = False
        except OSError:
            pass  # a cache that cannot write is just a cold cache

    # ------------------------------------------------------------------
    def get_local(self, relpath: str, sha: str,
                  checker: str) -> Optional[List[Finding]]:
        entry = self._data["local"].get(relpath)
        if entry is None or entry.get("sha") != sha:
            self.stats["file_misses"] += 1
            return None
        hit = entry["by_checker"].get(checker)
        if hit is None:
            self.stats["file_misses"] += 1
            return None
        self.stats["file_hits"] += 1
        return hit

    def put_local(self, relpath: str, sha: str, checker: str,
                  findings: List[Finding]) -> None:
        entry = self._data["local"].get(relpath)
        if entry is None or entry.get("sha") != sha:
            entry = {"sha": sha, "by_checker": {}}
            self._data["local"][relpath] = entry
        entry["by_checker"][checker] = list(findings)
        self._dirty = True

    # ------------------------------------------------------------------
    def get_project(self, stamp: str,
                    checker: str) -> Optional[List[Finding]]:
        proj = self._data["project"]
        if proj.get("stamp") != stamp:
            return None
        return proj["by_checker"].get(checker)

    def put_project(self, stamp: str, checker: str,
                    findings: List[Finding]) -> None:
        proj = self._data["project"]
        if proj.get("stamp") != stamp:
            self._data["project"] = proj = {"stamp": stamp,
                                            "by_checker": {}}
        proj["by_checker"][checker] = list(findings)
        self._dirty = True
