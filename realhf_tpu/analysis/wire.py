"""wire checker: every serving send site matches the declared protocol.

``serving/protocol.py`` is the normative declaration of the rollout
wire protocol (kinds, frame schemas, reason strings, state machines).
This project checker is the enforcement arm -- the obs-catalog
pattern applied to the protocol. Per call site it checks that

- the event/request kind is spelled as a ``protocol.*`` constant, not
  a raw string literal (``wire-literal-kind``), and resolves to a
  declared kind (``wire-undeclared-kind``);
- a literal payload dict only sets declared frame fields
  (``wire-undeclared-field``) and a literal ``reason=`` is in the
  frame's declared reason set (``wire-undeclared-reason``);
- a positional request tuple has the declared arity
  (``wire-request-arity``).

Project-wide it cross-checks emitters vs handlers in BOTH directions:

- a declared kind no code site emits (``wire-unemitted-kind``), and
  its sharper variant: a state-machine transition riding a kind with
  no emit site (``wire-fsm-no-site``);
- a dispatchable kind no code site switches on
  (``wire-unhandled-kind``) -- a ``kind in TERMINAL_KINDS``
  membership test handles every terminal at once;
- a rid-scoped event kind no declared state machine rides
  (``wire-fsm-uncovered-kind``), plus any internal inconsistency of
  the machines themselves (``wire-fsm-invalid``).

Resolution is conservative: only string constants, ``protocol.X``
attributes, and names from-imported out of the protocol module are
resolved; dynamic kinds (``ev.kind`` forwarded verbatim) are out of
scope -- the checker never guesses.

Known intentional envelope: the scheduler's internal
``ServeEvent(done, rid, dict(result=...))`` is unpacked by
``RolloutServer._deliver`` into the declared ``done`` frame before it
reaches the wire; ``INTERNAL_ENVELOPE_FIELDS`` whitelists it.
"""

import ast
import hashlib
import os
from typing import Dict, List, Optional, Set, Tuple

from realhf_tpu.analysis.core import (
    ProjectChecker,
    enclosing_symbols,
    iter_python_files,
)
from realhf_tpu.analysis.finding import Finding
from realhf_tpu.serving import protocol

#: emit helpers: callee name -> (kind arg index, data arg index).
#: Covers the server/router/shard send paths and the scheduler's
#: ServeEvent constructor (see docs/serving.md "Wire protocol").
EMIT_CALLS: Dict[str, Tuple[int, int]] = {
    "_send": (1, 2),
    "_reply": (1, 3),
    "_forward": (1, 2),
    "_send_ident": (1, 3),
    "_finish": (1, 2),
    "ServeEvent": (0, 2),
    # gateway -> browser: one SSE frame per wire event
    # (serving/gateway.py `_sse_event(wfile, kind, data)`)
    "_sse_event": (1, 2),
}

#: extra payload keys allowed at specific emit sites: internal
#: envelopes unpacked before they reach the wire.
INTERNAL_ENVELOPE_FIELDS: Dict[str, Set[str]] = {
    # scheduler -> server: _deliver() explodes the FinishedRollout
    # into the declared `done` frame fields.
    protocol.DONE: {"result"},
}

#: names whose membership tests handle every terminal kind at once
_TERMINAL_TUPLE_NAMES = ("TERMINAL_KINDS",)

#: comparison partners that mark a string compare as a kind dispatch
_KIND_VAR_NAMES = ("kind", "k", "status", "ev_kind")


def _resolve_kind(node: ast.AST, imports: Dict[str, str]
                  ) -> Tuple[Optional[str], bool]:
    """(kind string, was a raw literal) for one kind expression.

    Resolves string constants, ``protocol.X`` attributes, and names
    from-imported out of the protocol module; everything else yields
    ``(None, False)`` -- dynamic, out of scope.
    """
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, True
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "protocol":
        val = getattr(protocol, node.attr, None)
        if isinstance(val, str):
            return val, False
        return None, False
    if isinstance(node, ast.Name) and node.id in imports:
        return imports[node.id], False
    return None, False


def _protocol_imports(tree: ast.AST) -> Dict[str, str]:
    """local name -> kind string, for names from-imported out of the
    protocol module (or re-exported through serving.server)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or not node.module:
            continue
        if not (node.module.endswith("protocol")
                or node.module.endswith("serving.server")):
            continue
        for alias in node.names:
            val = getattr(protocol, alias.name, None)
            if isinstance(val, str):
                out[alias.asname or alias.name] = val
    return out


def _dict_items(node: ast.AST
                ) -> Optional[List[Tuple[str, ast.AST]]]:
    """(key, value expr) pairs of a literal dict construct --
    ``{...}`` with constant keys or a ``dict(...)`` keyword call --
    else None (dynamic payload, out of scope)."""
    if isinstance(node, ast.Dict):
        items = []
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                return None
            items.append((k.value, v))
        return items
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "dict" and not node.args:
        items = []
        for kw in node.keywords:
            if kw.arg is None:
                return None  # **splat
            items.append((kw.arg, kw.value))
        return items
    return None


def _callee_name(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


class WireChecker(ProjectChecker):
    name = "wire"
    cacheable = True

    def __init__(self, package: str = os.path.join("realhf_tpu",
                                                   "serving")):
        self.package = package

    def diff_relevant(self, changed) -> bool:
        scope = self.package.replace(os.sep, "/") + "/"
        return any(c.replace(os.sep, "/").startswith(scope)
                   for c in changed)

    def stamp_extra(self, root: str) -> str:
        # the declarations live in the imported protocol module, not
        # the scanned tree -- stamp its source so editing the
        # protocol invalidates cached runs over unchanged files.
        try:
            with open(protocol.__file__, encoding="utf-8") as f:
                return hashlib.sha1(f.read().encode()).hexdigest()
        except OSError:
            return "protocol-missing"

    # ------------------------------------------------------------------
    def check_project(self, root: str) -> List[Finding]:
        pkg_abs = os.path.join(root, self.package)
        if not os.path.isdir(pkg_abs):
            return []
        findings: List[Finding] = []
        emitted: Set[str] = set()
        handled: Set[str] = set()
        has_declaration = False
        for path in iter_python_files([pkg_abs], root):
            if os.path.basename(path) == "protocol.py":
                has_declaration = True
                continue  # the declaration itself, not a use site
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError, ValueError):
                continue
            self._check_file(tree, rel, findings, emitted, handled)
        # exhaustiveness only means something against the real tree;
        # a fixture package without the declaration file gets the
        # per-site rules only
        if has_declaration:
            findings.extend(self._cross_check(emitted, handled))
        return findings

    # -- per-file pass -------------------------------------------------
    def _check_file(self, tree: ast.AST, rel: str,
                    findings: List[Finding], emitted: Set[str],
                    handled: Set[str]) -> None:
        imports = _protocol_imports(tree)
        symbols = enclosing_symbols(tree)
        comparator_tuples: Set[int] = set()
        call_arg_tuples: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare):
                self._scan_compare(node, rel, imports, symbols,
                                   findings, handled,
                                   comparator_tuples)
            elif isinstance(node, ast.Call):
                for arg in list(node.args) + [kw.value for kw
                                              in node.keywords]:
                    if isinstance(arg, ast.Tuple):
                        call_arg_tuples.add(id(arg))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._scan_emit_call(node, rel, imports, symbols,
                                     findings, emitted)
            elif isinstance(node, ast.Tuple) \
                    and id(node) not in comparator_tuples:
                self._scan_emit_tuple(node, rel, imports, symbols,
                                      findings, emitted,
                                      call_arg=id(node)
                                      in call_arg_tuples)

    def _scan_compare(self, node: ast.Compare, rel: str,
                      imports: Dict[str, str],
                      symbols: Dict[ast.AST, str],
                      findings: List[Finding], handled: Set[str],
                      comparator_tuples: Set[int]) -> None:
        sides = [node.left] + list(node.comparators)
        membership = any(isinstance(op, (ast.In, ast.NotIn))
                         for op in node.ops)
        for side in sides:
            if isinstance(side, ast.Tuple):
                comparator_tuples.add(id(side))
                for elt in side.elts:
                    kind, literal = _resolve_kind(elt, imports)
                    if kind in protocol.ALL_KINDS:
                        handled.add(kind)
                        if literal:
                            self._literal_finding(
                                elt, kind, rel, symbols.get(node, ""),
                                findings)
                continue
            if membership and isinstance(side, (ast.Name,
                                                ast.Attribute)):
                name = side.id if isinstance(side, ast.Name) \
                    else side.attr
                if name in _TERMINAL_TUPLE_NAMES:
                    handled.update(protocol.TERMINAL_KINDS)
                    continue
            kind, literal = _resolve_kind(side, imports)
            if kind not in protocol.ALL_KINDS:
                continue
            if literal and not self._kindish_partner(sides, side):
                continue  # unrelated string compare
            handled.add(kind)
            if literal:
                self._literal_finding(side, kind, rel,
                                      symbols.get(node, ""), findings)

    @staticmethod
    def _kindish_partner(sides: List[ast.AST],
                         literal_side: ast.AST) -> bool:
        """Some other side of the compare is a kind-carrying variable
        (``kind``/``k``/``status``/``.kind``) -- guards the literal
        rule against unrelated string comparisons."""
        for other in sides:
            if other is literal_side:
                continue
            name = ""
            if isinstance(other, ast.Name):
                name = other.id
            elif isinstance(other, ast.Attribute):
                name = other.attr
            if name in _KIND_VAR_NAMES:
                return True
        return False

    # -- emit sites ----------------------------------------------------
    def _scan_emit_call(self, node: ast.Call, rel: str,
                        imports: Dict[str, str],
                        symbols: Dict[ast.AST, str],
                        findings: List[Finding],
                        emitted: Set[str]) -> None:
        callee = _callee_name(node)
        spec = EMIT_CALLS.get(callee)
        if spec is None:
            return
        kind_idx, data_idx = spec
        if len(node.args) <= kind_idx:
            return
        kind, literal = _resolve_kind(node.args[kind_idx], imports)
        if kind is None:
            return  # dynamic kind forwarded verbatim
        symbol = symbols.get(node, "")
        if literal:
            self._literal_finding(node, kind, rel, symbol, findings)
        if kind not in protocol.FRAMES:
            findings.append(Finding(
                checker=self.name, code="wire-undeclared-kind",
                path=rel, line=node.lineno, col=node.col_offset,
                message=(f"`{callee}` emits kind `{kind}`, which "
                         "serving/protocol.py does not declare -- "
                         "add a Frame or fix the kind"),
                symbol=symbol))
            return
        emitted.add(kind)
        if len(node.args) > data_idx:
            self._check_payload(node.args[data_idx], kind, rel,
                                node, symbol, imports, findings)

    def _scan_emit_tuple(self, node: ast.Tuple, rel: str,
                         imports: Dict[str, str],
                         symbols: Dict[ast.AST, str],
                         findings: List[Finding],
                         emitted: Set[str],
                         call_arg: bool = True) -> None:
        """Positional wire tuples: ``(submit, rid, ...)`` request
        envelopes and ``(kind, [rid,] data)`` event pairs queued for
        delivery. A raw-literal head only counts when the tuple is
        a call argument (being sent somewhere) -- otherwise
        ``__slots__``-style string tuples would false-positive."""
        if not node.elts:
            return
        kind, literal = _resolve_kind(node.elts[0], imports)
        if kind is None:
            return
        if literal and not call_arg:
            return
        symbol = symbols.get(node, "")
        if kind in protocol.REQUESTS:
            if literal:
                self._literal_finding(node, kind, rel, symbol,
                                      findings)
            emitted.add(kind)
            req = protocol.REQUESTS[kind]
            arity = len(node.elts)
            if not req.min_arity <= arity <= req.max_arity:
                findings.append(Finding(
                    checker=self.name, code="wire-request-arity",
                    path=rel, line=node.lineno, col=node.col_offset,
                    message=(f"`{kind}` request tuple has arity "
                             f"{arity}, declared "
                             f"{req.min_arity}..{req.max_arity} "
                             f"{req.doc}"),
                    symbol=symbol))
            return
        if kind in protocol.FRAMES:
            if literal:
                self._literal_finding(node, kind, rel, symbol,
                                      findings)
            emitted.add(kind)
            for elt in node.elts[1:]:
                if _dict_items(elt) is not None:
                    self._check_payload(elt, kind, rel, node,
                                        symbol, imports, findings)

    def _check_payload(self, data_node: ast.AST, kind: str, rel: str,
                       site: ast.AST, symbol: str,
                       imports: Dict[str, str],
                       findings: List[Finding]) -> None:
        items = _dict_items(data_node)
        if items is None:
            return  # dynamic payload, out of scope
        fr = protocol.FRAMES[kind]
        allowed = fr.fields | INTERNAL_ENVELOPE_FIELDS.get(kind,
                                                           set())
        for key, value in items:
            if key not in allowed:
                findings.append(Finding(
                    checker=self.name, code="wire-undeclared-field",
                    path=rel, line=site.lineno, col=site.col_offset,
                    message=(f"`{kind}` payload sets field "
                             f"`{key}`, not declared in its Frame "
                             "-- declare it or drop it"),
                    symbol=symbol))
            if key == "reason" and fr.reasons:
                reason, _ = _resolve_kind(value, imports)
                if reason is not None \
                        and reason not in fr.reasons:
                    findings.append(Finding(
                        checker=self.name,
                        code="wire-undeclared-reason",
                        path=rel, line=site.lineno,
                        col=site.col_offset,
                        message=(f"`{kind}` reason `{reason}` is "
                                 "not in the frame's declared "
                                 "reason set"),
                        symbol=symbol))

    def _literal_finding(self, node: ast.AST, kind: str, rel: str,
                         symbol: str,
                         findings: List[Finding]) -> None:
        findings.append(Finding(
            checker=self.name, code="wire-literal-kind",
            path=rel,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=(f"wire kind `{kind}` spelled as a raw string "
                     "-- use the serving/protocol.py constant "
                     "(one source of truth)"),
            symbol=symbol))

    # -- project-wide cross-check --------------------------------------
    def _cross_check(self, emitted: Set[str],
                     handled: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        proto_rel = "realhf_tpu/serving/protocol.py"
        fsm_kinds = protocol.declared_fsm_kinds()
        for m in protocol.MACHINES:
            for err in m.validate():
                findings.append(Finding(
                    checker=self.name, code="wire-fsm-invalid",
                    path=proto_rel, line=0, col=0,
                    message=f"state machine inconsistency: {err}",
                    symbol=m.name))
        for kind in protocol.ALL_KINDS:
            if kind not in emitted:
                machines = sorted(m.name for m in protocol.MACHINES
                                  if kind in m.kinds())
                if machines:
                    findings.append(Finding(
                        checker=self.name, code="wire-fsm-no-site",
                        path=proto_rel, line=0, col=0,
                        message=(f"state machine(s) "
                                 f"{', '.join(machines)} ride kind "
                                 f"`{kind}` but no serving/ code "
                                 "site emits it"),
                        symbol=kind))
                else:
                    findings.append(Finding(
                        checker=self.name,
                        code="wire-unemitted-kind",
                        path=proto_rel, line=0, col=0,
                        message=(f"declared kind `{kind}` has no "
                                 "emit site in serving/ -- dead "
                                 "declaration or renamed kind"),
                        symbol=kind))
            fr = protocol.FRAMES.get(kind)
            dispatchable = fr.dispatch if fr is not None else True
            if dispatchable and kind not in handled:
                findings.append(Finding(
                    checker=self.name, code="wire-unhandled-kind",
                    path=proto_rel, line=0, col=0,
                    message=(f"kind `{kind}` is declared "
                             "dispatchable but no serving/ code "
                             "site switches on it -- emitted into "
                             "the void"),
                    symbol=kind))
            if fr is not None and fr.rid_scoped \
                    and kind not in fsm_kinds:
                findings.append(Finding(
                    checker=self.name,
                    code="wire-fsm-uncovered-kind",
                    path=proto_rel, line=0, col=0,
                    message=(f"rid-scoped event kind `{kind}` is "
                             "ridden by no declared state machine "
                             "-- declare the transition it drives"),
                    symbol=kind))
        return findings
