"""Bounded model of the sharded serving/failover plane (graft-verify).

``serving/protocol.py`` declares the wire protocol and its state
machines; this module turns the *failover semantics* of
``serving/router_shard.py`` into a small explicit-state transition
system that ``analysis/explore.py`` exhaustively explores at small
scope under a fault model (message loss/dup/reorder on the submit
path, shard SIGKILL, lease decay, same-name re-registration with an
epoch bump).

The model is tied to the code it abstracts through
:func:`extract_guards`: an AST pass over the real
``router_shard.py`` source that detects whether each load-bearing
guard is present -- the PR 16 epoch-bump resubmit in
``ShardedRolloutClient._check_failover``, terminal parking for
unattached adopted rids in ``_send_ident``, the fenced-send gate, the
parked-terminal handover in ``_handle_client``, and the journal
adoption sweep. Each missing guard flips the corresponding
:class:`GuardProfile` flag, and the explorer then finds the concrete
interleaving the guard was protecting against (the killer regression:
drop the epoch comparison and the checker reproduces the
parked-forever-terminal liveness hole PR 16 fixed).

Deliberate abstractions (documented, not bugs):

- ``wrong_owner`` bouncing and priorities are elided; the ring maps
  each rid to a deterministic home among the *active* shards.
- No TTLs/timeouts: a submit lost before any shard journals the rid
  is the training loop's requeue problem (``system/rollout.py``),
  not a protocol-delivery hole, so quiescence only flags rids whose
  terminal was *produced* but can never reach an open client.
- Message loss is physical: a send fails synchronously (``_send_to``
  returns False, so the client never commits ``target_epoch``) or an
  in-flight message dies because its peer connection is down (target
  fenced/crashed). TCP does not silently eat acknowledged sends to a
  live peer.
- Intermediate events (accepted/started/tokens) are elided; only
  terminal delivery is tracked, which is what the invariants govern.

Invariants (see docs/static_analysis.md "Model checking"):

- ``exactly-once-terminal`` (safety): a client never harvests a
  second terminal for a rid.
- ``no-fenced-delivery`` (safety): nothing sent by a fenced shard
  incarnation reaches a client.
- ``journal-drained`` (quiescence): once nothing can move, no
  journal entry survives for a closed rid.
- ``terminal-delivered`` (quiescence): once nothing can move, no rid
  has a produced terminal while its client is still open
  (no-parked-forever-terminal).
"""

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

# ----------------------------------------------------------------------
# Guard extraction: tie the model to the real source
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GuardProfile:
    """Which load-bearing failover guards the scanned source carries.

    All flags are extracted syntactically (presence of the guarding
    construct inside the named method); a missing method extracts as
    False -- the model then explores the weakened system.
    """

    #: PR 16: ``_check_failover`` compares the recorded
    #: ``target_epoch`` against the registry's current epoch, so a
    #: fence-and-rejoin (name never left the ring) still triggers a
    #: client resubmit.
    client_epoch_resubmit: bool = True
    #: ``_send_ident`` parks terminals for adopted rids whose client
    #: has not re-attached (``ident is None``) instead of dropping
    #: them.
    terminal_parking: bool = True
    #: ``_send_ident`` returns without sending while fenced.
    fenced_send_guard: bool = True
    #: ``_handle_client`` hands a parked terminal over on the
    #: re-attaching submit.
    parked_handover: bool = True
    #: ``_adopt_orphans`` exists: journaled rids of dead/fenced
    #: owners are re-adopted by the ring owner.
    journal_adoption: bool = True
    #: ``_on_msg`` drops events for rids whose terminal already
    #: surfaced (the ``_closed`` tombstones): exactly-once at the
    #: harvest boundary over an at-least-once wire.
    client_terminal_dedupe: bool = True


def _method_index(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            out.setdefault(node.name, node)
    return out


def _mentions_attr(node: ast.AST, attr: str) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr == attr
               for n in ast.walk(node))


def extract_guards(source: str) -> GuardProfile:
    """Scan (router_shard-shaped) source for the failover guards."""
    tree = ast.parse(source)
    methods = _method_index(tree)

    epoch = False
    fn = methods.get("_check_failover")
    if fn is not None:
        epoch = any(isinstance(n, ast.Compare)
                    and _mentions_attr(n, "target_epoch")
                    for n in ast.walk(fn))

    parking = False
    fence_gate = False
    fn = methods.get("_send_ident")
    if fn is not None:
        parking = any(
            isinstance(n, ast.Assign)
            and any(isinstance(t, ast.Subscript)
                    and _mentions_attr(t, "_parked")
                    for t in n.targets)
            for n in ast.walk(fn))
        fence_gate = any(
            isinstance(n, ast.If) and _mentions_attr(n.test, "_fenced")
            and any(isinstance(b, ast.Return) for b in n.body)
            for n in ast.walk(fn))

    dedupe = False
    fn = methods.get("_on_msg")
    if fn is not None:
        dedupe = any(
            isinstance(n, ast.Compare)
            and any(isinstance(op, ast.In) for op in n.ops)
            and _mentions_attr(n, "_closed")
            for n in ast.walk(fn))

    handover = False
    fn = methods.get("_handle_client")
    if fn is not None:
        handover = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "pop"
            and _mentions_attr(n.func.value, "_parked")
            for n in ast.walk(fn))

    return GuardProfile(
        client_epoch_resubmit=epoch,
        terminal_parking=parking,
        fenced_send_guard=fence_gate,
        parked_handover=handover,
        journal_adoption="_adopt_orphans" in methods,
        client_terminal_dedupe=dedupe)


# ----------------------------------------------------------------------
# Model configuration
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Scope and fault budgets of one exploration."""

    n_shards: int = 1
    n_replicas: int = 1
    n_rids: int = 1
    #: lease decays / SIGKILLs, shared budget (each also permits one
    #: same-name re-registration via the rejoin action)
    crashes: int = 1
    #: submit-path message losses (send_to returning False)
    drops: int = 1
    #: submit-path duplicates (resubmission races)
    dups: int = 1
    #: client failover resubmissions per rid
    resubmits_per_rid: int = 2
    #: model process death (parked/done state lost) in addition to
    #: lease decay (in-memory state survives)
    sigkill: bool = True
    guards: GuardProfile = GuardProfile()

    def shard_names(self) -> Tuple[str, ...]:
        return tuple(f"s{i}" for i in range(self.n_shards))

    def replica_names(self) -> Tuple[str, ...]:
        return tuple(f"g{i}" for i in range(self.n_replicas))

    def rids(self) -> Tuple[str, ...]:
        return tuple(f"r{i}" for i in range(self.n_rids))


#: tier-1 scope: the lint gate explores this exhaustively in well
#: under a second; the PR 16 hole already manifests here.
TIER1_CONFIG = ModelConfig(n_shards=1, n_replicas=1, n_rids=1)

#: the ISSUE's full small scope, exhaustive behind ``-m slow``
FULL_CONFIG = ModelConfig(n_shards=2, n_replicas=2, n_rids=2)


# ----------------------------------------------------------------------
# State (immutable -- states are dict keys in the explorer)
# ----------------------------------------------------------------------

#: client status values
INIT, INFLIGHT, CLOSED = "init", "inflight", "closed"
#: shard request stages
PENDING, DISPATCHED = "pending", "dispatched"
#: shard statuses
ACTIVE, FENCED = "active", "fenced"


@dataclasses.dataclass(frozen=True)
class ShardState:
    status: str = ACTIVE
    epoch: int = 1
    #: rid -> (stage, attached): attached means the client route is
    #: known (ident is not None)
    requests: Tuple[Tuple[str, Tuple[str, bool]], ...] = ()
    done: FrozenSet[str] = frozenset()
    #: rid -> parked terminal kind
    parked: Tuple[Tuple[str, str], ...] = ()


@dataclasses.dataclass(frozen=True)
class ClientState:
    status: str = INIT
    target: str = ""
    target_epoch: int = 0
    terminals: int = 0
    #: late terminals the harvest-boundary tombstones swallowed
    dup_suppressed: int = 0
    fenced_deliveries: int = 0
    resubmits: int = 0


@dataclasses.dataclass(frozen=True)
class FleetState:
    shards: Tuple[Tuple[str, ShardState], ...]
    #: replica -> ((rid, owner shard), ...): generating rids
    replicas: Tuple[Tuple[str, Tuple[Tuple[str, str], ...]], ...]
    clients: Tuple[Tuple[str, ClientState], ...]
    #: registry journal: rid -> owning shard name
    journal: Tuple[Tuple[str, str], ...]
    #: in-flight bags (sorted tuples: delivery order is
    #: nondeterministic, which models reordering for free)
    submits: Tuple[Tuple[str, str], ...]            # (target, rid)
    dispatches: Tuple[Tuple[str, str, str], ...]    # (shard, rep, rid)
    repl_events: Tuple[Tuple[str, str, str], ...]   # (shard, rep, rid)
    #: shard -> client terminals: (sender, rid, kind,
    #: fenced_send) -- the sender tag exists so a SIGKILL can reap
    #: the dead incarnation's unflushed zmq send queue
    events: Tuple[Tuple[str, str, str, bool], ...]
    crashes_left: int = 0
    drops_left: int = 0
    dups_left: int = 0


def _tset(pairs, key, value):
    d = dict(pairs)
    d[key] = value
    return tuple(sorted(d.items()))


def _tdel(pairs, key):
    d = dict(pairs)
    d.pop(key, None)
    return tuple(sorted(d.items()))


def _bag_add(bag, msg):
    # multiplicity is capped at 2: delivery of these messages is
    # idempotent, so a third identical copy in flight reaches no
    # state two copies cannot -- without the cap, timeout/retry
    # cycles would grow the bags (and the state space) unboundedly
    if bag.count(msg) >= 2:
        return bag
    return tuple(sorted(bag + (msg,)))


def _bag_remove(bag, msg):
    out = list(bag)
    out.remove(msg)
    return tuple(out)


# ----------------------------------------------------------------------
# The model
# ----------------------------------------------------------------------


class FleetModel:
    """Transition system over :class:`FleetState`.

    The explorer drives it through :meth:`initial`, :meth:`actions`
    (sorted ``(name, successor)`` pairs -- sorted enumeration keeps
    runs deterministic), :meth:`safety_violations` (checked on every
    reached state) and :meth:`quiescence_violations` (checked on
    states with no enabled action).
    """

    def __init__(self, config: ModelConfig = TIER1_CONFIG):
        self.config = config
        self.guards = config.guards

    # -- setup ---------------------------------------------------------
    def initial(self) -> FleetState:
        cfg = self.config
        return FleetState(
            shards=tuple((s, ShardState())
                         for s in cfg.shard_names()),
            replicas=tuple((g, ()) for g in cfg.replica_names()),
            clients=tuple((r, ClientState()) for r in cfg.rids()),
            journal=(), submits=(), dispatches=(), repl_events=(),
            events=(),
            crashes_left=cfg.crashes, drops_left=cfg.drops,
            dups_left=cfg.dups)

    def _owner_of(self, rid: str, shards) -> Optional[str]:
        """Deterministic ring owner among the *active* shards."""
        active = [n for n, s in shards if s.status == ACTIVE]
        if not active:
            return None
        return active[int(rid[1:]) % len(active)]

    # -- actions -------------------------------------------------------
    def actions(self, st: FleetState
                ) -> List[Tuple[str, FleetState]]:
        out: List[Tuple[str, FleetState]] = []
        shards = dict(st.shards)
        clients = dict(st.clients)

        for rid, c in st.clients:
            if c.status == INIT:
                nxt = self._submit(st, rid, c)
                if nxt is not None:
                    out.append((f"submit({rid})", nxt))
                if st.drops_left > 0:
                    # failed initial send: _submit_to returns False,
                    # so target stays unset and the failover poll
                    # retries (loss never commits client state)
                    out.append((f"submit_fail({rid})",
                                dataclasses.replace(
                                    st,
                                    drops_left=st.drops_left - 1,
                                    clients=_tset(
                                        st.clients, rid,
                                        dataclasses.replace(
                                            c, status=INFLIGHT)))))
            elif c.status == INFLIGHT:
                nxt = self._client_failover(st, rid, c)
                if nxt is not None:
                    out.append((f"failover_poll({rid})", nxt))

        for msg in sorted(set(st.submits)):
            out.append((f"deliver_submit{msg}",
                        self._deliver_submit(st, msg)))
            if st.drops_left > 0 and shards[msg[0]].status != ACTIVE:
                # in-flight loss is a transport property: a message
                # dies only when its peer connection is down (the
                # target fenced/crashed under it)
                out.append((f"drop_submit{msg}", dataclasses.replace(
                    st, submits=_bag_remove(st.submits, msg),
                    drops_left=st.drops_left - 1)))
            if st.dups_left > 0:
                out.append((f"dup_submit{msg}", dataclasses.replace(
                    st, submits=_bag_add(st.submits, msg),
                    dups_left=st.dups_left - 1)))

        for sname, sh in st.shards:
            if sh.status == ACTIVE:
                for rid, (stage, att) in sh.requests:
                    if stage == PENDING:
                        for rep, _ in st.replicas:
                            out.append((
                                f"dispatch({sname},{rep},{rid})",
                                self._dispatch(st, sname, rid, rep)))
                    else:
                        # router.py's dispatch/response timeout:
                        # _fail_assignment returns the rid to pending
                        # and it shops for a replica again (the
                        # client-visible `retrying` is elided)
                        reqs = dict(sh.requests)
                        reqs[rid] = (PENDING, att)
                        out.append((
                            f"response_timeout({sname},{rid})",
                            dataclasses.replace(
                                st, shards=_tset(
                                    st.shards, sname,
                                    dataclasses.replace(
                                        sh, requests=tuple(
                                            sorted(reqs.items())))))))
                if st.crashes_left > 0:
                    out.append((f"lease_lose({sname})",
                                self._fence(st, sname,
                                            lose_memory=False)))
                    if self.config.sigkill:
                        out.append((f"sigkill({sname})",
                                    self._fence(st, sname,
                                                lose_memory=True)))
                if self.guards.journal_adoption:
                    nxt = self._sweep(st, sname)
                    if nxt is not None:
                        out.append((f"sweep({sname})", nxt))
            else:
                out.append((f"rejoin({sname})",
                            self._rejoin(st, sname)))

        for msg in sorted(set(st.dispatches)):
            out.append((f"deliver_dispatch{msg}",
                        self._deliver_dispatch(st, msg)))
        for rep, gen in st.replicas:
            for rid, owner in gen:
                out.append((f"replica_done({rep},{rid})",
                            self._replica_done(st, rep, rid, owner)))
        for msg in sorted(set(st.repl_events)):
            out.append((f"deliver_repl_event{msg}",
                        self._deliver_repl_event(st, msg)))
        for msg in sorted(set(st.events)):
            out.append((f"deliver_event{msg}",
                        self._deliver_event(st, msg)))

        # a successor identical to the state is a disabled no-op, not
        # a transition (quiescence = no action CHANGES anything)
        out = [(n, s) for n, s in out if s != st]
        out.sort(key=lambda p: p[0])
        return out

    # -- client side ---------------------------------------------------
    def _submit(self, st, rid, c) -> Optional[FleetState]:
        owner = self._owner_of(rid, st.shards)
        if owner is None:
            return None
        epoch = dict(st.shards)[owner].epoch
        return dataclasses.replace(
            st,
            submits=_bag_add(st.submits, (owner, rid)),
            clients=_tset(st.clients, rid, dataclasses.replace(
                c, status=INFLIGHT, target=owner,
                target_epoch=epoch)))

    def _client_failover(self, st, rid, c) -> Optional[FleetState]:
        """The ShardedRolloutClient._check_failover poll: resubmit
        when the target left the registry, or -- with the PR 16 guard
        -- when its fencing epoch moved."""
        if c.resubmits >= self.config.resubmits_per_rid:
            return None
        shards = dict(st.shards)
        target = shards.get(c.target)
        gone = target is None or target.status != ACTIVE
        bumped = (not gone and self.guards.client_epoch_resubmit
                  and target.epoch != c.target_epoch)
        if not gone and not bumped:
            return None
        owner = self._owner_of(rid, st.shards)
        if owner is None:
            return None
        return dataclasses.replace(
            st,
            submits=_bag_add(st.submits, (owner, rid)),
            clients=_tset(st.clients, rid, dataclasses.replace(
                c, target=owner,
                target_epoch=shards[owner].epoch,
                resubmits=c.resubmits + 1)))

    def _deliver_event(self, st, msg) -> FleetState:
        _sender, rid, kind, fenced_send = msg
        c = dict(st.clients)[rid]
        st = dataclasses.replace(
            st, events=_bag_remove(st.events, msg))
        if c.status == CLOSED and self.guards.client_terminal_dedupe:
            # harvest-boundary tombstone: the duplicate is counted,
            # never surfaced
            return dataclasses.replace(
                st, clients=_tset(st.clients, rid,
                                  dataclasses.replace(
                                      c, dup_suppressed=c.dup_suppressed
                                      + 1)))
        return dataclasses.replace(
            st, clients=_tset(st.clients, rid, dataclasses.replace(
                c, status=CLOSED, terminals=c.terminals + 1,
                fenced_deliveries=c.fenced_deliveries
                + (1 if fenced_send else 0))))

    # -- shard side ----------------------------------------------------
    def _deliver_submit(self, st, msg) -> FleetState:
        target, rid = msg
        st = dataclasses.replace(
            st, submits=_bag_remove(st.submits, msg))
        shards = dict(st.shards)
        sh = shards.get(target)
        if sh is None or sh.status != ACTIVE:
            return st  # a fenced shard answers nothing
        reqs = dict(sh.requests)
        if rid in sh.done:
            parked = dict(sh.parked)
            if self.guards.parked_handover and rid in parked:
                kind = parked.pop(rid)
                return dataclasses.replace(
                    st,
                    events=_bag_add(st.events,
                                    (target, rid, kind, False)),
                    shards=_tset(st.shards, target,
                                 dataclasses.replace(
                                     sh, parked=tuple(
                                         sorted(parked.items())))))
            return st  # stale duplicate
        if rid in reqs:
            stage, _att = reqs[rid]
            reqs[rid] = (stage, True)  # failover re-attach
            return dataclasses.replace(
                st, shards=_tset(st.shards, target,
                                 dataclasses.replace(
                                     sh, requests=tuple(
                                         sorted(reqs.items())))))
        reqs[rid] = (PENDING, True)
        return dataclasses.replace(
            st,
            journal=_tset(st.journal, rid, target),
            shards=_tset(st.shards, target, dataclasses.replace(
                sh, requests=tuple(sorted(reqs.items())))))

    def _dispatch(self, st, sname, rid, rep) -> FleetState:
        sh = dict(st.shards)[sname]
        reqs = dict(sh.requests)
        reqs[rid] = (DISPATCHED, reqs[rid][1])
        return dataclasses.replace(
            st,
            dispatches=_bag_add(st.dispatches, (sname, rep, rid)),
            shards=_tset(st.shards, sname, dataclasses.replace(
                sh, requests=tuple(sorted(reqs.items())))))

    def _fence(self, st, sname, lose_memory: bool) -> FleetState:
        """Lease decay (in-memory parked/done survive the fence) or
        SIGKILL (they do not); both flush the request table
        terminal-lessly -- the journal is the durable record."""
        sh = dict(st.shards)[sname]
        sh = dataclasses.replace(
            sh, status=FENCED, requests=(),
            done=frozenset() if lose_memory else sh.done,
            parked=() if lose_memory else sh.parked)
        st = dataclasses.replace(
            st, crashes_left=st.crashes_left - 1,
            shards=_tset(st.shards, sname, sh))
        if not lose_memory:
            return st
        # SIGKILL: zmq queues its outbound messages in process
        # memory, so the dead incarnation's unflushed client events
        # and replica dispatches die with it; replica replies
        # addressed to its DEALER identity become unroutable. (A
        # lease decay leaves the process -- and its sockets --
        # alive, so nothing is reaped.)
        return dataclasses.replace(
            st,
            events=tuple(m for m in st.events if m[0] != sname),
            dispatches=tuple(m for m in st.dispatches
                             if m[0] != sname),
            repl_events=tuple(m for m in st.repl_events
                              if m[0] != sname))

    def _rejoin(self, st, sname) -> FleetState:
        """Same-name re-registration at a bumped fencing epoch."""
        sh = dict(st.shards)[sname]
        sh = dataclasses.replace(sh, status=ACTIVE,
                                 epoch=sh.epoch + 1)
        return dataclasses.replace(
            st, shards=_tset(st.shards, sname, sh))

    def _sweep(self, st, sname) -> Optional[FleetState]:
        """Journal adoption: the active ring owner re-adopts
        journaled rids whose recorded owner cannot deliver them."""
        shards = dict(st.shards)
        sh = shards[sname]
        reqs = dict(sh.requests)
        journal = dict(st.journal)
        adopted = False
        for rid, owner in sorted(journal.items()):
            if rid in reqs or rid in sh.done:
                continue
            owner_sh = shards.get(owner)
            owner_live = (owner_sh is not None
                          and owner_sh.status == ACTIVE)
            if owner != sname and owner_live:
                continue
            if self._owner_of(rid, st.shards) != sname:
                continue
            reqs[rid] = (PENDING, False)  # ident unknown until
            journal[rid] = sname          # the client re-attaches
            adopted = True
        if not adopted:
            return None
        return dataclasses.replace(
            st,
            journal=tuple(sorted(journal.items())),
            shards=_tset(st.shards, sname, dataclasses.replace(
                sh, requests=tuple(sorted(reqs.items())))))

    def _deliver_repl_event(self, st, msg) -> FleetState:
        sname, rep, rid = msg
        st = dataclasses.replace(
            st, repl_events=_bag_remove(st.repl_events, msg))
        sh = dict(st.shards)[sname]
        if sh.status != ACTIVE:
            if self.guards.fenced_send_guard:
                return st  # fenced late sends deliver NOTHING
            # missing fence gate: the stale incarnation delivers
            return dataclasses.replace(
                st, events=_bag_add(st.events,
                                    (sname, rid, "done", True)))
        reqs = dict(sh.requests)
        if rid not in reqs:
            return st  # stale event for a flushed/finished rid
        _stage, attached = reqs.pop(rid)
        sh = dataclasses.replace(
            sh, requests=tuple(sorted(reqs.items())),
            done=sh.done | {rid})
        st = dataclasses.replace(
            st, journal=_tdel(st.journal, rid),
            shards=_tset(st.shards, sname, sh))
        if attached:
            return dataclasses.replace(
                st, events=_bag_add(st.events,
                                    (sname, rid, "done", False)))
        if self.guards.terminal_parking:
            parked = dict(sh.parked)
            parked[rid] = "done"
            return dataclasses.replace(
                st, shards=_tset(st.shards, sname,
                                 dataclasses.replace(
                                     sh, parked=tuple(
                                         sorted(parked.items())))))
        return st  # no parking guard: the terminal is dropped

    # -- replica side --------------------------------------------------
    def _deliver_dispatch(self, st, msg) -> FleetState:
        sname, rep, rid = msg
        gen = dict(dict(st.replicas)[rep])
        gen[rid] = sname  # (re-)attach to the latest dispatcher
        return dataclasses.replace(
            st,
            dispatches=_bag_remove(st.dispatches, msg),
            replicas=_tset(st.replicas, rep,
                           tuple(sorted(gen.items()))))

    def _replica_done(self, st, rep, rid, owner) -> FleetState:
        gen = dict(dict(st.replicas)[rep])
        gen.pop(rid)
        return dataclasses.replace(
            st,
            repl_events=_bag_add(st.repl_events, (owner, rep, rid)),
            replicas=_tset(st.replicas, rep,
                           tuple(sorted(gen.items()))))

    # -- invariants ----------------------------------------------------
    def safety_violations(self, st: FleetState) -> List[str]:
        out = []
        for rid, c in st.clients:
            if c.terminals > 1:
                out.append(
                    f"exactly-once-terminal: client harvested "
                    f"{c.terminals} terminals for {rid}")
            if c.fenced_deliveries > 0:
                out.append(
                    f"no-fenced-delivery: a fenced shard "
                    f"incarnation delivered a terminal for {rid}")
        return out

    def quiescence_violations(self, st: FleetState) -> List[str]:
        out = []
        clients = dict(st.clients)
        finished = set()
        for _sname, sh in st.shards:
            finished |= sh.done
        for rid, c in clients.items():
            if c.status != CLOSED and rid in finished:
                out.append(
                    f"terminal-delivered: quiescent with a produced "
                    f"terminal for {rid} the open client can never "
                    "receive (parked-forever / dropped)")
        for rid, owner in st.journal:
            if clients[rid].status == CLOSED:
                out.append(
                    f"journal-drained: quiescent with a journal "
                    f"entry for closed rid {rid} (owner {owner})")
        return out
