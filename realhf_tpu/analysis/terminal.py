"""terminal checker: exactly-once terminal delivery in the serving
protocol handlers.

The serving wire contract (docs/serving.md "The request lifecycle")
says a request retired from a live routing table must leave behind
exactly one terminal event, and the route/owner entry may only be
dropped AFTER the terminal send succeeded -- the PR 2 and PR 12
postmortems ("route dropped only after send succeeds", "owner left
pointing at drained replica") are both instances. This family checks
every CFG path of the handlers in ``serving/{scheduler,router,
server}.py``:

- ``proto-missing-terminal``: a path retires an rid from a live table
  (:data:`LIVE_TABLES`: ``_routes`` / ``_requests`` / ``_pending``
  via ``pop``/``remove``/``discard``/``clear``/``del``) and reaches
  the function's normal exit without any terminal-ish call on that
  path -- the client waits forever on a stream nobody owns.
- ``proto-drop-before-send``: the only terminal on the path happens
  AFTER the retire -- if the send fails, the terminal is lost for
  good because the route is already gone. Send first, drop the route
  only on success (``server.py:_send`` is the canonical shape).

"Terminal-ish" is resolved interprocedurally: a raw socket send
(``send_multipart`` & friends, or ``send`` on a socket-named
receiver), one of the :data:`TERMINAL_HELPERS` by name, or any
project call that transitively reaches a raw send through the call
graph.

Scheduler-side slot/parked retirement is NOT checked here: those
retire through helpers (``_evict``, ``take_parked``) whose terminals
are emitted by their callers against the returned value -- a
contract the per-function path analysis cannot see
(docs/static_analysis.md "What the engine cannot resolve").
Deliberate silent drops (fence flushes) carry inline disables with
their justification.
"""

import ast
from typing import Dict, List, Set, Tuple

from realhf_tpu.analysis.cfg import (
    EXC,
    _walk_no_nested,
    build_cfg,
    iter_functions,
)
from realhf_tpu.analysis.core import GraphChecker, Module, dotted_name
from realhf_tpu.analysis.finding import Finding

#: attributes holding rid -> route/request state the protocol owes a
#: terminal for
LIVE_TABLES = ("_routes", "_requests", "_pending")
#: mutations that retire an entry from a live table
RETIRE_METHODS = ("pop", "remove", "discard", "clear")
#: unambiguous raw send primitives
RAW_SEND_ATTRS = ("send_multipart", "send_pyobj", "send_string",
                  "send_json")
#: ``.send(...)`` counts only on a receiver that is plainly a socket
SOCKETISH = ("sock", "front", "socket")
#: helper names that deliver terminals (fallback when the call graph
#: cannot resolve the callee)
TERMINAL_HELPERS = ("_send", "_reply", "_forward", "_finish",
                    "_deliver", "_fail_assignment", "_send_ident",
                    "_bounce")

_SCOPE_FILES = ("realhf_tpu/serving/scheduler.py",
                "realhf_tpu/serving/router.py",
                "realhf_tpu/serving/router_shard.py",
                "realhf_tpu/serving/server.py")


def _is_raw_send(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    if func.attr in RAW_SEND_ATTRS:
        return True
    if func.attr == "send":
        recv = dotted_name(func.value).lower()
        return any(s in recv for s in SOCKETISH)
    return False


def _retire_tables(tree: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(table attr, node) for every live-table retirement in the
    subtree."""
    out: List[Tuple[str, ast.AST]] = []
    for n in _walk_no_nested(tree):
        if isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in RETIRE_METHODS:
            recv = dotted_name(n.func.value)
            last = recv.rsplit(".", 1)[-1] if recv else ""
            if last in LIVE_TABLES:
                out.append((last, n))
        elif isinstance(n, ast.Delete):
            for t in n.targets:
                if isinstance(t, ast.Subscript):
                    recv = dotted_name(t.value)
                    last = recv.rsplit(".", 1)[-1] if recv else ""
                    if last in LIVE_TABLES:
                        out.append((last, t))
    return out


class TerminalChecker(GraphChecker):
    name = "terminal"

    def __init__(self):
        self.index = None
        self._send_summaries: Dict[str, bool] = {}

    def applies_to(self, relpath: str) -> bool:
        return relpath in _SCOPE_FILES

    # ------------------------------------------------------------------
    def check(self, module: Module) -> List[Finding]:
        if self.index is None:
            from realhf_tpu.analysis.callgraph import ProjectIndex
            self.index = ProjectIndex([module])
        findings: List[Finding] = []
        for qualname, fn in iter_functions(module.tree):
            findings.extend(self._check_function(module, qualname, fn))
        return findings

    # ------------------------------------------------------------------
    def _resolves_to_send(self, call: ast.Call, scope) -> bool:
        if self.index is None or scope is None:
            return False
        target = self.index.resolve_call(call, scope)
        if target is None:
            return False

        def sends(qual: str) -> bool:
            cached = self._send_summaries.get(qual)
            if cached is None:
                info = self.index.funcs.get(qual)
                cached = info is not None and any(
                    _is_raw_send(c) for c in self.index.calls_in(qual))
                self._send_summaries[qual] = cached
            return cached

        if sends(target):
            return True
        return self.index.reaches(target, sends,
                                  max_depth=4) is not None

    def _is_terminal_call(self, call: ast.Call, scope) -> bool:
        if _is_raw_send(call):
            return True
        func = call.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name in TERMINAL_HELPERS:
            return True
        return self._resolves_to_send(call, scope)

    # ------------------------------------------------------------------
    def _check_function(self, module: Module, qualname: str,
                        fn) -> List[Finding]:
        body_mod = ast.Module(body=fn.body, type_ignores=[])
        if not _retire_tables(body_mod):
            return []
        scope = None
        if self.index is not None:
            from realhf_tpu.analysis.callgraph import module_name
            mod = module_name(module.relpath)
            scope = self.index.funcs.get(f"{mod}:{qualname}")

        from realhf_tpu.analysis.dataflow import run_forward
        from realhf_tpu.analysis.lifecycle import _exec_parts

        cfg = build_cfg(fn)
        # node idx -> (retires [(table, ast node)], is_terminal)
        node_info: Dict[int, Tuple[List, bool]] = {}
        for node in cfg.nodes:
            if node.stmt is None:
                continue
            retires: List[Tuple[str, ast.AST]] = []
            terminal = False
            for part in _exec_parts(node.stmt):
                retires.extend(_retire_tables(part))
                for n in _walk_no_nested(part):
                    if isinstance(n, ast.Call) \
                            and self._is_terminal_call(n, scope):
                        terminal = True
            if retires or terminal:
                node_info[node.idx] = (retires, terminal)

        if not any(retires for retires, _t in node_info.values()):
            return []

        # state: (unterm: some path here has no terminal yet,
        #         bad: frozenset of retire node idxs that happened on
        #              such a path and saw no terminal since)
        init = (True, frozenset())

        def transfer(node, state, kind):
            if kind == EXC:
                return state  # the statement didn't happen
            unterm, bad = state
            info = node_info.get(node.idx)
            if info is None:
                return state
            retires, terminal = info
            if retires and unterm:
                bad = bad | {node.idx}
            if terminal:
                return (False, frozenset())
            return (unterm, bad)

        def join(a, b):
            return (a[0] or b[0], a[1] | b[1])

        in_states = run_forward(cfg, init, transfer, join)

        findings: List[Finding] = []
        reported: Set[Tuple[str, int]] = set()

        def report(code: str, node_idx: int, msg: str):
            if (code, node_idx) in reported:
                return
            reported.add((code, node_idx))
            retires, _t = node_info[node_idx]
            table, where = retires[0]
            findings.append(self.finding(
                module, code, where, msg.format(table=table),
                symbol=qualname))

        # drop-before-send: a terminal fires while retires are open
        for node in cfg.nodes:
            info = node_info.get(node.idx)
            state = in_states.get(node.idx)
            if info is None or state is None or not info[1]:
                continue
            for idx in sorted(state[1]):
                report(
                    "proto-drop-before-send", idx,
                    "`{table}` entry retired BEFORE the terminal "
                    "send on this path -- a failed send then loses "
                    "the terminal for good; send first, drop the "
                    "route only on success")
        exit_state = in_states.get(cfg.normal_exit)
        if exit_state is not None:
            for idx in sorted(exit_state[1]):
                report(
                    "proto-missing-terminal", idx,
                    "path retires an rid from `{table}` but emits no "
                    "terminal event before returning -- the client "
                    "waits forever; send done/rejected/cancelled/"
                    "bounce exactly once")
        return findings
