"""concurrency checker: locks vs blocking calls, unsynced thread state.

The control plane's postmortems (PR 1 ``last_exec_info`` read-back
race, PR 2 per-role Engine lock and ZMQ terminal-event loss) all
reduce to three mechanical patterns this checker watches:

- ``conc-lock-blocking``: a blocking call (ZMQ send/recv, socket
  connect/accept, subprocess, ``name_resolve.wait``, ``sleep``,
  thread ``join``) issued while a lock is held. A stalled peer then
  stalls every thread contending for the lock. Serialize only the
  shared-state mutation; do wire/pickle work outside the critical
  section.
- ``conc-unsynced-field``: an attribute written from a thread entry
  point (``Thread(target=...)`` or a ``threading.Thread`` subclass's
  ``run``) and also touched from other methods, with no lock on
  either side.
- ``conc-unjoined-thread``: a non-daemon ``threading.Thread`` that is
  never ``join``-ed -- it outlives shutdown and hides exit hangs.
- ``conc-shared-zmq-socket``: a ZMQ socket attribute with
  send/recv/poll calls both in a thread entry point and in another
  method, with no lock on either side. ZMQ sockets are not
  thread-safe; concurrent I/O corrupts the socket state machine --
  exactly the bug class the serving router/server must avoid (their
  serve loops own each socket exclusively). ``close()`` is NOT
  counted as I/O: the join-then-close teardown pattern is safe.
"""

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from realhf_tpu.analysis.core import (
    AstChecker,
    Module,
    call_name,
    dotted_name,
)
from realhf_tpu.analysis.finding import Finding

#: method names that block on a peer / the OS
BLOCKING_METHODS = {
    "send", "send_multipart", "send_pyobj", "send_string", "send_json",
    "recv", "recv_multipart", "recv_pyobj", "recv_string", "recv_json",
    "connect", "accept", "join", "wait_for",
}
BLOCKING_CALLS = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen", "name_resolve.wait", "name_resolve.get_subtree",
    "socket.create_connection",
}
#: blocking methods excused when the receiver is plainly bounded
#: (queue.get(timeout=...) etc. stay flagged -- keep the list tight)

_LOCKISH = re.compile(r"lock|mutex", re.IGNORECASE)

#: socket methods that touch the ZMQ state machine concurrently
#: (close is deliberately absent: join-then-close teardown is safe)
_SOCKET_IO_METHODS = {
    "send", "send_multipart", "send_pyobj", "send_string", "send_json",
    "recv", "recv_multipart", "recv_pyobj", "recv_string", "recv_json",
    "poll",
}

#: attribute values that are themselves thread-safe handshakes
_SAFE_CTORS = ("threading.Event", "threading.Lock", "threading.RLock",
               "threading.Condition", "threading.Semaphore",
               "threading.BoundedSemaphore", "queue.Queue",
               "queue.SimpleQueue", "collections.deque", "Event",
               "Lock", "RLock", "Condition")


def _is_lock_expr(expr: ast.AST) -> bool:
    try:
        src = ast.unparse(expr)
    except Exception:  # noqa: BLE001 - best effort on exotic nodes
        return False
    return bool(_LOCKISH.search(src))


class ConcurrencyChecker(AstChecker):
    name = "concurrency"

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith((
            "realhf_tpu/system/", "realhf_tpu/serving/",
            "realhf_tpu/base/", "realhf_tpu/apps/",
            "realhf_tpu/parallel/"))

    def check(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_lock_blocking(module))
        findings.extend(self._check_unjoined_threads(module))
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class_fields(module, node))
                findings.extend(
                    self._check_shared_zmq_socket(module, node))
        return findings

    # ------------------------------------------------------------------
    def _check_lock_blocking(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []

        def visit(node: ast.AST, lock_depth: int, symbol: str):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                symbol = node.name
                lock_depth = 0  # a def body runs later, not under the
                # lexically-enclosing with
            if isinstance(node, ast.With):
                if any(_is_lock_expr(i.context_expr)
                       for i in node.items):
                    lock_depth += 1
            if lock_depth > 0 and isinstance(node, ast.Call):
                nm = call_name(node)
                blocking = nm in BLOCKING_CALLS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in BLOCKING_METHODS
                    and not _is_lock_expr(node.func.value)
                    # "sep".join(parts) is str.join, not Thread.join
                    and not isinstance(node.func.value, ast.Constant))
                if blocking:
                    what = nm or f".{node.func.attr}"
                    findings.append(self.finding(
                        module, "conc-lock-blocking", node,
                        f"blocking call `{what}` while holding a lock "
                        f"in `{symbol}`; move wire/serialization work "
                        "outside the critical section",
                        symbol=symbol))
            for child in ast.iter_child_nodes(node):
                visit(child, lock_depth, symbol)

        visit(module.tree, 0, "")
        return findings

    # ------------------------------------------------------------------
    def _check_unjoined_threads(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        has_join = ".join(" in module.source
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = call_name(node)
            if nm.rsplit(".", 1)[-1] != "Thread" or nm == "QThread":
                continue
            daemon = next((kw for kw in node.keywords
                           if kw.arg == "daemon"), None)
            if daemon is not None and not (
                    isinstance(daemon.value, ast.Constant)
                    and daemon.value.value is False):
                continue  # daemon=True (or dynamic: benefit of doubt)
            if daemon is None and has_join:
                continue  # joined somewhere; good enough statically
            findings.append(self.finding(
                module, "conc-unjoined-thread", node,
                "non-daemon Thread never joined in this module; pass "
                "daemon=True or join it on shutdown",
                symbol=""))
        return findings

    # ------------------------------------------------------------------
    def _check_class_fields(self, module: Module,
                            cls: ast.ClassDef) -> List[Finding]:
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if not methods:
            return []
        thread_entries = self._thread_entry_methods(cls, methods)
        if not thread_entries:
            return []
        safe_attrs = self._safe_attrs(methods.get("__init__"))

        # attr -> (locked?, node) per method kind
        def attr_uses(fn, store_only: bool):
            uses: Dict[str, Tuple[bool, ast.AST]] = {}

            def visit(node, lock_depth):
                if isinstance(node, ast.With) and any(
                        _is_lock_expr(i.context_expr)
                        for i in node.items):
                    lock_depth += 1
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    is_store = isinstance(node.ctx,
                                          (ast.Store, ast.Del))
                    if is_store or not store_only:
                        prev = uses.get(node.attr)
                        # an unlocked use wins (that's the bug)
                        if prev is None or (prev[0]
                                            and lock_depth == 0):
                            uses[node.attr] = (lock_depth > 0, node)
                for child in ast.iter_child_nodes(node):
                    visit(child, lock_depth)

            visit(fn, 0)
            return uses

        writes_in_thread: Dict[str, Tuple[bool, ast.AST, str]] = {}
        for name in sorted(thread_entries):
            for attr, (locked, node) in attr_uses(
                    methods[name], store_only=True).items():
                if attr in safe_attrs or attr.startswith("__"):
                    continue
                prev = writes_in_thread.get(attr)
                if prev is None or (prev[0] and not locked):
                    writes_in_thread[attr] = (locked, node, name)

        findings: List[Finding] = []
        for mname, fn in sorted(methods.items()):
            if mname in thread_entries or mname == "__init__":
                continue
            for attr, (locked, _n) in attr_uses(
                    fn, store_only=False).items():
                hit = writes_in_thread.get(attr)
                if hit is None:
                    continue
                t_locked, t_node, t_name = hit
                if locked or t_locked:
                    continue  # one side synchronized: different bug
                findings.append(self.finding(
                    module, "conc-unsynced-field", t_node,
                    f"`self.{attr}` written in thread entry "
                    f"`{cls.name}.{t_name}` and used in "
                    f"`{cls.name}.{mname}` without a common lock",
                    symbol=f"{cls.name}.{t_name}"))
                writes_in_thread.pop(attr)  # one finding per attr
        return findings

    # ------------------------------------------------------------------
    def _check_shared_zmq_socket(self, module: Module,
                                 cls: ast.ClassDef) -> List[Finding]:
        """ZMQ socket I/O (send/recv/poll) from a thread entry AND
        from another method of the same class, with no lock on either
        side. Socket-creation is recognized syntactically: an
        attribute assigned from a ``*.socket(...)`` call."""
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        if not methods:
            return []
        socket_attrs: Set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            f = node.value.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr == "socket"):
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    socket_attrs.add(t.attr)
        if not socket_attrs:
            return []
        entries = self._thread_entry_methods(cls, methods)
        if not entries:
            return []

        def io_uses(fn) -> Dict[str, Tuple[bool, ast.AST]]:
            """socket attr -> (locked?, node) for send/recv/poll calls
            on it; an unlocked use wins (that's the bug)."""
            uses: Dict[str, Tuple[bool, ast.AST]] = {}

            def visit(node, lock_depth):
                if isinstance(node, ast.With) and any(
                        _is_lock_expr(i.context_expr)
                        for i in node.items):
                    lock_depth += 1
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SOCKET_IO_METHODS):
                    tgt = node.func.value
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr in socket_attrs):
                        prev = uses.get(tgt.attr)
                        if prev is None or (prev[0]
                                            and lock_depth == 0):
                            uses[tgt.attr] = (lock_depth > 0, node)
                for child in ast.iter_child_nodes(node):
                    visit(child, lock_depth)

            visit(fn, 0)
            return uses

        # attr -> (locked, node, entry method) of thread-side I/O
        entry_uses: Dict[str, Tuple[bool, ast.AST, str]] = {}
        for name in sorted(entries):
            for attr, (locked, node) in io_uses(methods[name]).items():
                prev = entry_uses.get(attr)
                if prev is None or (prev[0] and not locked):
                    entry_uses[attr] = (locked, node, name)

        findings: List[Finding] = []
        for mname, fn in sorted(methods.items()):
            if mname in entries or mname == "__init__":
                continue
            for attr, (locked, _n) in io_uses(fn).items():
                hit = entry_uses.get(attr)
                if hit is None:
                    continue
                e_locked, e_node, e_name = hit
                if locked or e_locked:
                    continue  # one side synchronized: different bug
                findings.append(self.finding(
                    module, "conc-shared-zmq-socket", e_node,
                    f"ZMQ socket `self.{attr}` used from thread entry "
                    f"`{cls.name}.{e_name}` and from "
                    f"`{cls.name}.{mname}` without a common lock; ZMQ "
                    "sockets are not thread-safe -- confine each "
                    "socket to one thread or lock every use",
                    symbol=f"{cls.name}.{e_name}"))
                entry_uses.pop(attr)  # one finding per socket attr
        return findings

    # ------------------------------------------------------------------
    @staticmethod
    def _thread_entry_methods(cls: ast.ClassDef,
                              methods: Dict) -> Set[str]:
        entries: Set[str] = set()
        is_thread_subclass = any(
            dotted_name(b).rsplit(".", 1)[-1] == "Thread"
            for b in cls.bases)
        if is_thread_subclass and "run" in methods:
            entries.add("run")
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node).rsplit(".", 1)[-1] != "Thread":
                continue
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and target.attr in methods):
                entries.add(target.attr)
        return entries

    @staticmethod
    def _safe_attrs(init: Optional[ast.AST]) -> Set[str]:
        """Attributes initialized to sync primitives (Events, Locks,
        Queues) are their own synchronization."""
        safe: Set[str] = set()
        if init is None:
            return safe
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            if call_name(node.value) not in _SAFE_CTORS:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    safe.add(t.attr)
        return safe
