"""Explicit-state explorer for the fleet model (graft-verify).

Deterministic bounded breadth-first search over
:class:`~realhf_tpu.analysis.model.FleetModel`: actions are
enumerated in sorted order, states are deduped on their (hashable,
frozen) value, and the search carries parent pointers so every
violation comes with a minimal-length action trace a human can replay
against the runtime. Two invariant families:

- safety (``FleetModel.safety_violations``) is checked on every
  state as it is first reached;
- quiescence (``FleetModel.quiescence_violations``) is checked on
  states with no enabled action -- the liveness proxy: "nothing can
  move and the protocol still owes something".

``ModelChecker`` wraps one tier-1-scope exploration of the *real*
``serving/router_shard.py`` (guards extracted from its source, see
:func:`~realhf_tpu.analysis.model.extract_guards`) as a cacheable
project checker in the lint gate: a refactor that silently drops one
of the failover guards turns into a lint finding carrying the
counterexample trace.
"""

import dataclasses
import hashlib
import os
from collections import deque
from typing import List, Optional, Tuple

from realhf_tpu.analysis.core import ProjectChecker
from realhf_tpu.analysis.finding import Finding
from realhf_tpu.analysis.model import (
    TIER1_CONFIG,
    FleetModel,
    ModelConfig,
    extract_guards,
)


@dataclasses.dataclass(frozen=True)
class Violation:
    invariant: str
    message: str
    #: action names from the initial state to the violating state
    trace: Tuple[str, ...]


@dataclasses.dataclass
class ExploreResult:
    states: int
    transitions: int
    max_depth: int
    violations: List[Violation]
    #: True when a bound (max_states / max_depth) cut the search
    #: short -- "no violations" is then a bounded claim, not a proof
    truncated: bool = False

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        verdict = "ok" if self.ok \
            else f"{len(self.violations)} violation(s)"
        extra = " (TRUNCATED)" if self.truncated else ""
        return (f"{self.states} states, {self.transitions} "
                f"transitions, depth {self.max_depth}: "
                f"{verdict}{extra}")


def explore(model: FleetModel, max_states: int = 200_000,
            max_depth: int = 64,
            stop_at_first: bool = True) -> ExploreResult:
    """Exhaust the model's state space within the given bounds."""
    init = model.initial()
    parents = {init: None}  # state -> (parent state, action name)
    queue = deque([(init, 0)])
    transitions = 0
    deepest = 0
    violations: List[Violation] = []
    truncated = False

    def _trace(state) -> Tuple[str, ...]:
        out = []
        while True:
            link = parents[state]
            if link is None:
                return tuple(reversed(out))
            state, action = link
            out.append(action)

    for err in model.safety_violations(init):
        violations.append(Violation(err.split(":")[0], err, ()))

    while queue:
        if len(parents) > max_states:
            truncated = True
            break
        state, depth = queue.popleft()
        deepest = max(deepest, depth)
        succ = model.actions(state)
        transitions += len(succ)
        if not succ:
            for err in model.quiescence_violations(state):
                violations.append(Violation(
                    err.split(":")[0], err, _trace(state)))
                if stop_at_first:
                    return ExploreResult(len(parents), transitions,
                                         deepest, violations)
            continue
        if depth >= max_depth:
            truncated = True
            continue
        for action, nxt in succ:
            if nxt in parents:
                continue
            parents[nxt] = (state, action)
            for err in model.safety_violations(nxt):
                violations.append(Violation(
                    err.split(":")[0], err,
                    _trace(state) + (action,)))
                if stop_at_first:
                    return ExploreResult(len(parents), transitions,
                                         deepest + 1, violations)
            queue.append((nxt, depth + 1))

    return ExploreResult(len(parents), transitions, deepest,
                         violations, truncated=truncated)


def check_source(source: str,
                 config: ModelConfig = TIER1_CONFIG,
                 max_states: int = 200_000,
                 max_depth: int = 64) -> ExploreResult:
    """Extract the guard profile from router_shard-shaped source and
    exhaust the resulting model."""
    guards = extract_guards(source)
    cfg = dataclasses.replace(config, guards=guards)
    return explore(FleetModel(cfg), max_states=max_states,
                   max_depth=max_depth)


# ----------------------------------------------------------------------
# Lint-gate integration
# ----------------------------------------------------------------------

_SHARD_REL = os.path.join("realhf_tpu", "serving", "router_shard.py")


class ModelChecker(ProjectChecker):
    """Model-check the real failover plane inside the lint gate.

    Tier-1 scope (1 shard x 1 replica x 1 rid, full fault budget) is
    exhausted in well under a second and already exposes every guard
    the :class:`~realhf_tpu.analysis.model.GuardProfile` tracks; the
    2x2x2 scope runs in the slow test tier. Cacheable: reruns only
    when router_shard.py (or this analysis code) changes.
    """

    name = "model"
    cacheable = True

    def __init__(self, config: ModelConfig = TIER1_CONFIG,
                 max_states: int = 200_000, max_depth: int = 64):
        self.config = config
        self.max_states = max_states
        self.max_depth = max_depth

    def diff_relevant(self, changed) -> bool:
        rel = _SHARD_REL.replace(os.sep, "/")
        return any(c.replace(os.sep, "/") == rel for c in changed)

    def stamp_extra(self, root: str) -> str:
        h = hashlib.sha1()
        h.update(repr(self.config).encode())
        try:
            with open(os.path.join(root, _SHARD_REL),
                      encoding="utf-8") as f:
                h.update(f.read().encode())
        except OSError:
            h.update(b"missing")
        return h.hexdigest()

    def check_project(self, root: str) -> List[Finding]:
        path = os.path.join(root, _SHARD_REL)
        if not os.path.exists(path):
            return []
        rel = _SHARD_REL.replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            result = check_source(source, self.config,
                                  max_states=self.max_states,
                                  max_depth=self.max_depth)
        except SyntaxError:
            return []  # the per-file passes already flag this
        findings = []
        for v in result.violations:
            trace = " -> ".join(v.trace) or "<initial state>"
            findings.append(Finding(
                checker=self.name, code="model-" + v.invariant,
                path=rel, line=0, col=0,
                message=(f"model checking the failover plane at "
                         f"scope {self.config.n_shards}x"
                         f"{self.config.n_replicas}x"
                         f"{self.config.n_rids} found: {v.message};"
                         f" trace: {trace}"),
                symbol=v.invariant))
        return findings
