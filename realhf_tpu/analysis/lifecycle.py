"""lifecycle checker: paired-operation discipline on every CFG path.

The runtime is full of acquire/release protocols whose unpaired use
is a slow leak or a refcount corruption: KV-pool block refs, prefix-
cache pins, ZMQ sockets, threads, staged checkpoints. This family
proves, per function and per CFG exit path, that a locally-owned
resource is released exactly once:

- ``lifecycle-unreleased``: some NORMAL path (return / fall-off-end)
  exits with the resource still held.
- ``lifecycle-leak-on-raise``: normal paths release, but a path where
  an exception escapes between acquire and release leaks it (the fix
  is ``try/finally`` or an ``except: release; raise``).
- ``lifecycle-double-release``: a path releases the same resource
  twice (refcount corruption for pool blocks, ``ZMQError`` for
  sockets).

What counts as acquire/release comes from the declarative
:data:`PAIRINGS` registry (docs/static_analysis.md "Pairing
registry"); adding a protocol is one table row. The analysis only
tracks resources bound to LOCAL variables whose ownership provably
stays in the function:

- ``with``-managed acquires are safe by construction and ignored;
- returning/yielding the resource, storing it on an attribute or into
  a container, aliasing it, or passing it to an unresolved call all
  ESCAPE (ownership moved -- someone else releases);
- passing it to a project function that (transitively, via the call
  graph) performs the pairing's release counts as the release;
- ``if v: v.close()`` / ``if v is None: ...`` guards are understood
  via branch refinement (the not-held arm drops the resource), so
  the ``v = None; if cond: v = acquire()`` idiom does not
  false-positive.

Daemon threads (``daemon=True``) are exempt from the
``Thread.start``/``join`` pairing -- detaching is their design.
"""

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from realhf_tpu.analysis.cfg import (
    EXC,
    FALSE,
    TRUE,
    _walk_no_nested,
    build_cfg,
    exec_parts,
    iter_functions,
)
from realhf_tpu.analysis.core import (
    GraphChecker,
    Module,
    dotted_name,
)
from realhf_tpu.analysis.dataflow import run_forward
from realhf_tpu.analysis.finding import Finding

#: resource states; per-variable lattice element = frozenset of these.
#: ESCAPED is absorbing: ownership left the function, nothing more to
#: prove (and an ``incref`` of an already-escaped local must not
#: restart tracking -- the escapee owns those refs).
HELD = "H"
RELEASED = "R"
ESCAPED = "E"


@dataclasses.dataclass(frozen=True)
class Pairing:
    """One acquire/release protocol row.

    ``mode``:

    - ``result``: the acquire's return value is the resource
      (``blocks = pool.alloc(n)``; ``sock = ctx.socket(...)``);
    - ``receiver``: a method call on a ctor-produced local is the
      acquire (``t.start()`` after ``t = threading.Thread(...)``),
      gated on ``ctor_re``;
    - ``arg``: the acquire's first argument is the (already-local)
      resource (``pool.incref(blocks)``).

    A release is a call whose attribute is in ``release_methods``
    with the resource as receiver (``sock.close()``) or argument
    (``pool.free(blocks)``; attribute access like ``m.handle``
    included), or a resolved project call that transitively performs
    one with the resource as an argument.
    """
    label: str
    mode: str
    acquire_methods: Tuple[str, ...]
    release_methods: Tuple[str, ...]
    receiver_re: str = ""
    ctor_re: str = ""


PAIRINGS: Tuple[Pairing, ...] = (
    Pairing("kv-pool-blocks", "result", ("alloc",), ("free",),
            receiver_re=r"pool"),
    Pairing("kv-pool-blocks", "arg", ("incref",), ("free",),
            receiver_re=r"pool"),
    Pairing("prefix-pin", "result", ("match",), ("release",),
            receiver_re=r"cache|prefix"),
    Pairing("zmq-socket", "result", ("socket",), ("close",),
            receiver_re=r"ctx|context"),
    Pairing("thread-join", "receiver", ("start",), ("join",),
            ctor_re=r"(?:^|\.)Thread$"),
    Pairing("staged-ckpt", "result", ("begin",),
            ("commit", "abort"), receiver_re=r"ckpt|writer|manager|mgr"),
)

_ALL_ACQUIRE_METHODS = frozenset(
    m for p in PAIRINGS for m in p.acquire_methods)

#: builtins that only read their argument (no ownership transfer)
_NEUTRAL_CALLS = {
    "len", "bool", "str", "repr", "print", "sorted", "min", "max",
    "sum", "any", "all", "enumerate", "range", "isinstance", "float",
    "int", "id", "type", "iter", "zip", "hash", "format",
}


def _occurs(var: str, node: ast.AST) -> bool:
    return any(isinstance(n, ast.Name) and n.id == var
               for n in _walk_no_nested(node))


def _null_test(test: ast.AST) -> Optional[Tuple[str, str]]:
    """Recognize ``v`` / ``not v`` / ``v is None`` / ``v is not
    None`` -> (var, edge kind on which the var is NOT held)."""
    if isinstance(test, ast.Name):
        return test.id, FALSE
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name):
        return test.operand.id, TRUE
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.left, ast.Name) \
            and len(test.comparators) == 1 \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is):
            return test.left.id, TRUE
        if isinstance(test.ops[0], ast.IsNot):
            return test.left.id, FALSE
    return None


#: shared with the terminal family: what executes AT a node
_exec_parts = exec_parts


class LifecycleChecker(GraphChecker):
    name = "lifecycle"

    def __init__(self):
        self.index = None
        #: (qual, pairing label) -> contains a release-form call
        self._release_summaries: Dict[Tuple[str, str], bool] = {}

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith((
            "realhf_tpu/engine/", "realhf_tpu/serving/",
            "realhf_tpu/system/", "realhf_tpu/base/",
            "realhf_tpu/apps/", "realhf_tpu/agentic/"))

    # ------------------------------------------------------------------
    def check(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        for qualname, fn in iter_functions(module.tree):
            findings.extend(self._check_function(module, qualname, fn))
        return findings

    # ------------------------------------------------------------------
    def _scope_info(self, module: Module, qualname: str):
        if self.index is None:
            from realhf_tpu.analysis.callgraph import ProjectIndex
            self.index = ProjectIndex([module])
        from realhf_tpu.analysis.callgraph import module_name
        mod = module_name(module.relpath)
        return self.index.funcs.get(f"{mod}:{qualname}")

    def _callee_releases(self, call: ast.Call, scope,
                         pairing: Pairing) -> bool:
        """Does the call resolve to a project function that
        (transitively) performs a release-form call of this
        pairing?"""
        if scope is None or self.index is None:
            return False
        target = self.index.resolve_call(call, scope)
        if target is None:
            return False

        def releases(qual: str) -> bool:
            key = (qual, pairing.label)
            cached = self._release_summaries.get(key)
            if cached is None:
                info = self.index.funcs.get(qual)
                cached = info is not None and any(
                    isinstance(c.func, ast.Attribute)
                    and c.func.attr in pairing.release_methods
                    for c in self.index.calls_in(qual))
                self._release_summaries[key] = cached
            return cached

        if releases(target):
            return True
        return self.index.reaches(target, releases,
                                  max_depth=3) is not None

    # ------------------------------------------------------------------
    def _check_function(self, module: Module, qualname: str,
                        fn) -> List[Finding]:
        body_mod = ast.Module(body=fn.body, type_ignores=[])
        # cheap prefilter: any acquire-method attribute at all?
        if not any(isinstance(n, ast.Attribute)
                   and n.attr in _ALL_ACQUIRE_METHODS
                   for n in _walk_no_nested(body_mod)):
            return []
        scope = self._scope_info(module, qualname)
        cfg = build_cfg(fn)

        # lexical ctor map for receiver-mode pairings (threads)
        ctor_vars: Dict[str, bool] = {}
        for n in _walk_no_nested(body_mod):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)):
                continue
            cname = dotted_name(n.value.func)
            for p in PAIRINGS:
                if p.mode == "receiver" and p.ctor_re \
                        and re.search(p.ctor_re, cname):
                    daemon = next((kw.value for kw in n.value.keywords
                                   if kw.arg == "daemon"), None)
                    trackable = daemon is None or (
                        isinstance(daemon, ast.Constant)
                        and daemon.value is False)
                    ctor_vars[n.targets[0].id] = trackable

        # pass 1: acquire sites -> the variables this function owns
        acquire_sites: Dict[str, Tuple[Pairing, ast.AST, str]] = {}
        node_acquires: Dict[int, List[Tuple[str, Pairing, str]]] = {}
        for node in cfg.nodes:
            if node.stmt is None:
                continue
            acq = self._node_acquires(node.stmt, ctor_vars)
            if acq:
                node_acquires[node.idx] = acq
                for var, pairing, recv in acq:
                    acquire_sites.setdefault(
                        var, (pairing, node.stmt, recv))
        if not acquire_sites:
            return []
        var_pairing = {v: p for v, (p, _s, _r)
                       in acquire_sites.items()}

        # pass 2: release/escape events against the owned variables
        node_events: Dict[int, Dict] = {}
        for node in cfg.nodes:
            if node.stmt is None:
                continue
            ev = self._node_event(node.stmt, var_pairing,
                                  node_acquires.get(node.idx, []),
                                  scope)
            if ev is not None:
                node_events[node.idx] = ev

        def transfer(node, state, kind):
            # On the EXC edge, releases and escapes still count (the
            # anti-false-positive direction: a raising `free`/`close`
            # or container-store is treated as having taken effect)
            # but acquires do not -- a raising acquire acquired
            # nothing, which is exactly what leak-on-raise needs.
            ev = node_events.get(node.idx)
            out = state
            if ev is not None:
                out = dict(out)
                for var, _call in ev["releases"]:
                    st = out.get(var)
                    if st:
                        # per-path: escaped stays escaped (the
                        # escapee owns the release), held/released
                        # become released
                        out[var] = frozenset(
                            ESCAPED if s == ESCAPED else RELEASED
                            for s in st)
                for var in ev["escapes"]:
                    # unconditional: an escape BEFORE the acquire
                    # (e.g. stored in a node, then incref'd) must
                    # block arg-mode tracking too
                    out[var] = frozenset({ESCAPED})
                if kind != EXC:
                    for var, pairing, _recv in ev["acquires"]:
                        if pairing.mode == "arg" and ESCAPED in \
                                out.get(var, frozenset()):
                            continue  # the escapee owns those refs
                        out[var] = frozenset({HELD})
            stmt = node.stmt
            if kind != EXC and stmt is not None \
                    and isinstance(stmt, (ast.If, ast.While)):
                nt = _null_test(stmt.test)
                if nt is not None and nt[0] in out and kind == nt[1]:
                    out = dict(out)
                    out.pop(nt[0], None)
            return out

        def join(a, b):
            if a == b:
                return a
            out = dict(a)
            for var, st in b.items():
                out[var] = out.get(var, frozenset()) | st
            return out

        in_states = run_forward(cfg, {}, transfer, join)

        findings: List[Finding] = []
        reported: Set[Tuple[str, str]] = set()

        def report(code: str, var: str, extra: str):
            if (code, var) in reported:
                return
            reported.add((code, var))
            pairing, site, recv = acquire_sites[var]
            src = f"`{recv}.{pairing.acquire_methods[0]}(...)`" \
                if recv else f"`.{pairing.acquire_methods[0]}()`"
            findings.append(self.finding(
                module, code, site,
                f"`{var}` acquired via {src} {extra} "
                f"(pairing: {pairing.label}; release with "
                f"{'/'.join(pairing.release_methods)})",
                symbol=qualname))

        # double release: a release applied to an already-released var
        for node in cfg.nodes:
            ev = node_events.get(node.idx)
            state = in_states.get(node.idx)
            if ev is None or state is None:
                continue
            for var, _call in ev["releases"]:
                if state.get(var) == frozenset({RELEASED}):
                    report("lifecycle-double-release", var,
                           "is released twice on some path")
        normal_in = in_states.get(cfg.normal_exit, {})
        raise_in = in_states.get(cfg.raise_exit, {})
        for var, st in sorted(normal_in.items()):
            if HELD in st:
                report("lifecycle-unreleased", var,
                       "may reach a return with the resource still "
                       "held")
        for var, st in sorted(raise_in.items()):
            if HELD in st and HELD not in normal_in.get(var, set()):
                report("lifecycle-leak-on-raise", var,
                       "leaks when an exception escapes before the "
                       "release (wrap in try/finally)")
        return findings

    # ------------------------------------------------------------------
    @staticmethod
    def _node_acquires(stmt: ast.stmt, ctor_vars: Dict[str, bool]
                       ) -> List[Tuple[str, Pairing, str]]:
        """Acquire events of one statement node. Only whole-statement
        shapes count (``v = recv.meth(...)`` / ``v.start()`` /
        ``recv.incref(v)``); acquires inside ``with`` items or nested
        expressions are context-managed or escaped anyway."""
        out: List[Tuple[str, Pairing, str]] = []
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Call) \
                and isinstance(stmt.value.func, ast.Attribute):
            attr = stmt.value.func.attr
            recv = dotted_name(stmt.value.func.value)
            for p in PAIRINGS:
                if p.mode == "result" and attr in p.acquire_methods \
                        and recv and re.search(p.receiver_re, recv,
                                               re.IGNORECASE):
                    out.append((stmt.targets[0].id, p, recv))
                    break
        elif isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Call) \
                and isinstance(stmt.value.func, ast.Attribute):
            call = stmt.value
            attr = call.func.attr
            for p in PAIRINGS:
                if p.mode == "receiver" and attr in p.acquire_methods \
                        and isinstance(call.func.value, ast.Name) \
                        and ctor_vars.get(call.func.value.id):
                    out.append((call.func.value.id, p, ""))
                    break
                if p.mode == "arg" and attr in p.acquire_methods \
                        and call.args \
                        and isinstance(call.args[0], ast.Name):
                    recv = dotted_name(call.func.value)
                    if recv and re.search(p.receiver_re, recv,
                                          re.IGNORECASE):
                        out.append((call.args[0].id, p, recv))
                        break
        return out

    def _node_event(self, stmt: ast.stmt,
                    var_pairing: Dict[str, Pairing],
                    acquires: List[Tuple[str, Pairing, str]],
                    scope) -> Optional[Dict]:
        parts = _exec_parts(stmt)
        if not parts:
            return None
        releases: List[Tuple[str, ast.Call]] = []
        escapes: Set[str] = set()
        acquired_here = {v for v, _p, _r in acquires}

        # which owned vars occur in the executing parts at all?
        present = {v for v in var_pairing
                   if any(_occurs(v, part) for part in parts)}
        if not present and not acquires:
            return None

        for part in parts:
            for n in _walk_no_nested(part):
                if isinstance(n, ast.Call):
                    self._classify_call(n, present, acquired_here,
                                        var_pairing, scope,
                                        releases, escapes)
                elif isinstance(n, (ast.Yield, ast.YieldFrom)) \
                        and getattr(n, "value", None) is not None:
                    escapes |= {v for v in present
                                if _occurs(v, n.value)}
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            escapes |= {v for v in present if _occurs(v, stmt.value)}
        elif isinstance(stmt, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign)) \
                and getattr(stmt, "value", None) is not None \
                and not acquires:
            escapes |= {v for v in present if _occurs(v, stmt.value)}
        elif isinstance(stmt, ast.Delete):
            escapes |= {t.id for t in stmt.targets
                        if isinstance(t, ast.Name) and t.id in present}
        elif isinstance(stmt, ast.Raise):
            escapes |= {v for v in present if _occurs(v, stmt)}

        released_vars = {v for v, _c in releases}
        escapes -= released_vars | acquired_here
        if not (acquires or releases or escapes):
            return None
        return dict(acquires=acquires, releases=releases,
                    escapes=escapes)

    def _classify_call(self, call: ast.Call, present: Set[str],
                       acquired_here: Set[str],
                       var_pairing: Dict[str, Pairing], scope,
                       releases: List, escapes: Set[str]) -> None:
        """Sort one call's owned-variable uses into release / escape /
        neutral (receiver method calls and read-only builtins)."""
        func = call.func
        arg_vars: Set[str] = set()
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            for n in _walk_no_nested(a):
                if isinstance(n, ast.Name) and n.id in present:
                    arg_vars.add(n.id)
        arg_vars -= acquired_here

        if isinstance(func, ast.Attribute):
            attr = func.attr
            recv_var = func.value.id \
                if isinstance(func.value, ast.Name) else None
            if recv_var in present \
                    and attr in var_pairing[recv_var].release_methods:
                releases.append((recv_var, call))
            handled = set()
            for v in sorted(arg_vars):
                if attr in var_pairing[v].release_methods:
                    releases.append((v, call))
                    handled.add(v)
            arg_vars -= handled
            if recv_var in present:
                return  # method call on the resource itself: neutral
        elif isinstance(func, ast.Name) and func.id in _NEUTRAL_CALLS:
            return
        # remaining argument uses: a resolved project callee that
        # releases the pairing counts as the release; anything else
        # takes ownership (escape)
        for v in sorted(arg_vars):
            if self._callee_releases(call, scope, var_pairing[v]):
                releases.append((v, call))
            else:
                escapes.add(v)
