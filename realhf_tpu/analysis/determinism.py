"""collective-determinism checker: unordered iteration feeding layouts.

Every host in a multi-process mesh must issue identical collectives in
identical order, construct identical ``PartitionSpec``/sharding
layouts, and build identical ``name_resolve`` keys -- a ``dict`` or
``set`` whose insertion order differs across hosts (config dicts built
from network messages, resolved worker maps, ...) silently breaks
that: the program deadlocks or, worse, shards land transposed.

Rule ``det-unsorted-iter``: a ``for`` loop or comprehension iterating
``*.items()`` / ``*.keys()`` / ``*.values()`` / a ``set``
(un-``sorted``) whose body constructs partition specs / shardings,
issues collectives or ``device_put``, or builds ``name_resolve`` keys.
Wrap the iterable in ``sorted(...)``.
"""

import ast
from typing import List, Optional

from realhf_tpu.analysis.core import (
    AstChecker,
    Module,
    call_name,
    dotted_name,
    enclosing_symbols,
)
from realhf_tpu.analysis.finding import Finding

#: names whose presence in a loop body marks it layout/collective
#: producing
LAYOUT_NAMES = {
    "PartitionSpec", "NamedSharding", "Mesh", "make_mesh",
    "with_sharding_constraint", "device_put", "make_array_from_callback",
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "axis_index", "broadcast_one_to_all",
    "process_allgather",
}
#: dotted prefixes equally marking the body (module-qualified forms)
LAYOUT_PREFIXES = ("name_resolve.", "jax.sharding.", "multihost.")

_DICT_METHODS = {"items", "keys", "values"}


def _unordered_iterable(node: ast.AST) -> Optional[str]:
    """A human-readable description of why ``node`` iterates in
    unordered fashion, or None when the order is deterministic."""
    if isinstance(node, ast.Call):
        nm = call_name(node)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _DICT_METHODS):
            return f"dict.{node.func.attr}()"
        if nm == "set" or nm == "frozenset":
            return f"{nm}(...)"
    if isinstance(node, ast.Set):
        return "set literal"
    if isinstance(node, (ast.BinOp,)) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
        # set algebra: a | b, a & b, a - b over sets
        if any(isinstance(s, (ast.Set, ast.Call)) and (
                isinstance(s, ast.Set) or call_name(s) == "set")
                for s in (node.left, node.right)):
            return "set expression"
    return None


def _body_builds_layout(body_nodes) -> Optional[str]:
    for stmt in body_nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                nm = call_name(node)
                last = nm.rsplit(".", 1)[-1]
                if last in LAYOUT_NAMES or nm.startswith(
                        LAYOUT_PREFIXES):
                    return nm or last
                if last == "P" and nm in ("P", "jax.P"):
                    return "PartitionSpec (P)"
            elif isinstance(node, (ast.Name, ast.Attribute)):
                nm = (node.id if isinstance(node, ast.Name)
                      else dotted_name(node))
                if nm.rsplit(".", 1)[-1] in LAYOUT_NAMES:
                    return nm
    return None


class DeterminismChecker(AstChecker):
    name = "collective-determinism"

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith((
            "realhf_tpu/models/", "realhf_tpu/parallel/",
            "realhf_tpu/system/", "realhf_tpu/serving/",
            "realhf_tpu/engine/", "realhf_tpu/base/"))

    def check(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        symbols = enclosing_symbols(module.tree)
        for node in ast.walk(module.tree):
            iters = []
            body = None
            if isinstance(node, ast.For):
                iters = [node.iter]
                body = node.body
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                iters = [g.iter for g in node.generators]
                body = ([node.key, node.value]
                        if isinstance(node, ast.DictComp)
                        else [node.elt])
            for it in iters:
                why = _unordered_iterable(it)
                if why is None:
                    continue
                built = _body_builds_layout(body)
                if built is None:
                    continue
                findings.append(self.finding(
                    module, "det-unsorted-iter", node,
                    f"iteration over {why} constructs `{built}` -- "
                    "hosts may disagree on order; wrap the iterable "
                    "in sorted(...)",
                    symbol=symbols.get(node, "")))
        return findings
