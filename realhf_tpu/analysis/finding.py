"""Finding model for graft-lint (docs/static_analysis.md).

A Finding is one checker hit at one source location. Findings are
diffed against a committed baseline (``scripts/lint_baseline.json``)
so CI fails only on NEW findings: the fingerprint therefore excludes
line/column numbers (which shift on every unrelated edit) and hashes
the stable coordinates instead -- checker code, file, enclosing
symbol, and message.
"""

import dataclasses
import hashlib
from typing import Dict, List


@dataclasses.dataclass(frozen=True)
class Finding:
    """One static-analysis hit.

    :param checker: checker family (``jax-purity``, ``concurrency``,
        ``collective-determinism``, ``dfg-invariants``).
    :param code: specific rule id within the family (e.g.
        ``purity-host-sync``); suppressions and baselines match on it.
    :param path: repo-relative posix path of the offending file.
    :param line: 1-based line (0 for whole-file / import-time passes).
    :param col: 0-based column.
    :param message: human-readable description. Must not embed line
        numbers -- it participates in the baseline fingerprint.
    :param symbol: enclosing function/class qualname (or experiment
        name for DFG findings); stabilizes fingerprints across edits
        elsewhere in the file.
    """

    checker: str
    code: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = ""

    @property
    def fingerprint(self) -> str:
        raw = "|".join((self.code, self.path, self.symbol, self.message))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def format(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.code}{sym}: {self.message}"

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d


def count_by_fingerprint(findings: List[Finding]) -> Dict[str, int]:
    """fingerprint -> occurrence count. Identical code on N lines of a
    file yields the same fingerprint N times; baseline diffing is done
    on counts so adding an (N+1)-th occurrence is still NEW."""
    out: Dict[str, int] = {}
    for f in findings:
        out[f.fingerprint] = out.get(f.fingerprint, 0) + 1
    return out
