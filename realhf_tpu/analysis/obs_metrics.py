"""obs-metric-name checker: metric naming conventions at call sites.

The metrics registry (``realhf_tpu/obs/metrics.py``) creates metrics
lazily at the first instrumented call, so a misnamed metric never
fails fast -- it just pollutes the Prometheus export forever (and a
``router_latency`` vs ``router_latency_secs`` mismatch silently
splits one series in two). This checker pins the conventions the
export relies on, at every call site that passes a LITERAL metric
name to the one-line instrumentation API (``inc`` / ``set_gauge`` /
``observe`` / ``observe_hist``) or the registry constructors
(``counter`` / ``gauge`` / ``summary`` / ``histogram``):

- ``obs-metric-name``: names must be snake_case
  (``[a-z][a-z0-9_]*``);
- counters (``inc`` / ``counter``) must end ``_total`` (the
  Prometheus counter convention every recording rule assumes);
- histograms/summaries whose name implies a duration (contains
  ``sec``/``secs``/``seconds``/``latency``/``duration``) must end
  ``_secs`` or ``_seconds`` so the unit is in the name.

Dynamic names (f-strings, variables) are out of scope -- only
``ast.Constant`` strings are checked, so the checker never guesses.
"""

import ast
import re
from typing import List, Optional

from realhf_tpu.analysis.core import AstChecker, Module, \
    enclosing_symbols
from realhf_tpu.analysis.finding import Finding

#: call name -> metric kind implied by the call
METRIC_CALLS = {
    "inc": "counter",
    "counter": "counter",
    "set_gauge": "gauge",
    "gauge": "gauge",
    "observe": "summary",
    "summary": "summary",
    "observe_hist": "histogram",
    "histogram": "histogram",
}

_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_TIMEISH_RE = re.compile(r"sec|latency|duration")


def _literal_name(call: ast.Call) -> Optional[str]:
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


class ObsMetricNameChecker(AstChecker):
    name = "obs-metric-name"

    def check(self, module: Module) -> List[Finding]:
        findings: List[Finding] = []
        symbols = enclosing_symbols(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) \
                else (func.id if isinstance(func, ast.Name) else "")
            kind = METRIC_CALLS.get(attr)
            if kind is None:
                continue
            metric = _literal_name(node)
            if metric is None:
                continue  # dynamic names are out of scope
            problem = None
            if not _SNAKE_RE.match(metric):
                problem = (f"metric name {metric!r} is not snake_case "
                           "([a-z][a-z0-9_]*)")
            elif kind == "counter" \
                    and not metric.endswith("_total"):
                problem = (f"counter {metric!r} must end `_total` "
                           "(Prometheus counter convention)")
            elif kind in ("summary", "histogram") \
                    and _TIMEISH_RE.search(metric) \
                    and not metric.endswith(("_secs", "_seconds")):
                problem = (f"{kind} {metric!r} looks like a duration "
                           "but does not end `_secs`/`_seconds` -- "
                           "put the unit in the name")
            if problem is not None:
                findings.append(self.finding(
                    module, "obs-metric-name", node, problem,
                    symbol=symbols.get(node, "")))
        return findings
