"""Project-wide call graph for graft-lint v2.

Builds, from the parsed modules of one analysis run, a map of every
top-level function and method in the scanned tree plus a best-effort
resolver from call sites to those definitions. Resolution is
deliberately conservative -- a call it cannot pin to exactly one
project definition is simply unresolved (checkers treat unresolved
calls as opaque):

- ``self.m(...)`` / ``cls.m(...)``: method of the lexically enclosing
  class, walking project-resolvable base classes;
- ``name(...)``: a module-level function of the same module, or a
  ``from x import name`` symbol; a class name resolves to its
  ``__init__``;
- ``alias.attr(...)`` / ``pkg.mod.func(...)``: through ``import``
  aliases (collected from the whole module -- function-level imports
  count) to another scanned module's function, or ``Class.method``;
- everything else (arbitrary object attributes, subscripts, calls on
  call results) is unresolved.

``ProjectIndex.reaches`` answers the transitive questions the
interprocedural checkers ask ("does anything this function calls,
up to depth N, satisfy this predicate?") and returns the call chain
as evidence.
"""

import ast
import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from realhf_tpu.analysis.cfg import _walk_no_nested
from realhf_tpu.analysis.core import Module, dotted_name


@dataclasses.dataclass
class FuncInfo:
    """One project function/method definition."""
    qual: str                 # "pkg.mod:Class.meth" or "pkg.mod:func"
    module: str               # dotted module name
    relpath: str
    cls: Optional[str]        # class key "pkg.mod:Class" for methods
    node: ast.AST

    @property
    def name(self) -> str:
        return self.qual.split(":", 1)[1]


def module_name(relpath: str) -> str:
    """'realhf_tpu/serving/server.py' -> 'realhf_tpu.serving.server';
    package __init__ files name the package itself."""
    parts = relpath[:-3].split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "__main__"


class ProjectIndex:
    """Call-graph index over one set of parsed modules."""

    def __init__(self, modules: List[Module]):
        self.modules: Dict[str, Module] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        #: class key -> {"methods": {name: qual}, "bases": [dotted]}
        self.classes: Dict[str, Dict] = {}
        #: module -> names bound at module top level (lock identity)
        self.module_globals: Dict[str, set] = {}
        #: module -> alias -> ("module", dotted) | ("symbol", mod, nm)
        self.imports: Dict[str, Dict[str, Tuple]] = {}
        self._callees: Dict[str, Tuple[str, ...]] = {}
        self._calls: Dict[str, List[ast.Call]] = {}
        for m in modules:
            self._index_module(m)

    # -- construction --------------------------------------------------
    def _index_module(self, m: Module):
        mod = module_name(m.relpath)
        self.modules[mod] = m
        imps: Dict[str, Tuple] = {}
        package = mod if m.relpath.endswith("/__init__.py") \
            else mod.rpartition(".")[0]
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    imps[name] = ("module", target)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = package.split(".") if package else []
                    up = up[: len(up) - (node.level - 1)] \
                        if node.level > 1 else up
                    base = ".".join(up + ([base] if base else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    name = alias.asname or alias.name
                    imps[name] = ("symbol", base, alias.name)
        self.imports[mod] = imps
        self.module_globals[mod] = {
            t.id
            for stmt in m.tree.body
            if isinstance(stmt, (ast.Assign, ast.AnnAssign))
            for t in (stmt.targets if isinstance(stmt, ast.Assign)
                      else [stmt.target])
            if isinstance(t, ast.Name)}
        for stmt in m.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{mod}:{stmt.name}"
                self.funcs[qual] = FuncInfo(qual, mod, m.relpath,
                                            None, stmt)
            elif isinstance(stmt, ast.ClassDef):
                key = f"{mod}:{stmt.name}"
                methods = {}
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        qual = f"{mod}:{stmt.name}.{sub.name}"
                        self.funcs[qual] = FuncInfo(
                            qual, mod, m.relpath, key, sub)
                        methods[sub.name] = qual
                self.classes[key] = dict(
                    methods=methods,
                    bases=[dotted_name(b) for b in stmt.bases])

    # -- symbol resolution ---------------------------------------------
    def _resolve_symbol(self, mod: str, name: str):
        """A bare name in ``mod`` -> ("func", qual) | ("class", key) |
        ("module", dotted) | None."""
        if f"{mod}:{name}" in self.funcs:
            return ("func", f"{mod}:{name}")
        if f"{mod}:{name}" in self.classes:
            return ("class", f"{mod}:{name}")
        imp = self.imports.get(mod, {}).get(name)
        if imp is None:
            return None
        if imp[0] == "module":
            return ("module", imp[1])
        _, src_mod, src_name = imp
        if f"{src_mod}.{src_name}" in self.modules:
            return ("module", f"{src_mod}.{src_name}")
        if src_mod in self.modules and src_mod != mod:
            return self._resolve_symbol(src_mod, src_name)
        return None

    def _resolve_method(self, cls_key: str, name: str,
                        _seen=None) -> Optional[str]:
        _seen = _seen or set()
        if cls_key in _seen:
            return None
        _seen.add(cls_key)
        cls = self.classes.get(cls_key)
        if cls is None:
            return None
        qual = cls["methods"].get(name)
        if qual is not None:
            return qual
        mod = cls_key.split(":", 1)[0]
        for base in cls["bases"]:
            base_key = self._resolve_class(mod, base)
            if base_key is not None:
                found = self._resolve_method(base_key, name, _seen)
                if found is not None:
                    return found
        return None

    def _resolve_class(self, mod: str, dotted: str) -> Optional[str]:
        if not dotted:
            return None
        parts = dotted.split(".")
        sym = self._resolve_symbol(mod, parts[0])
        if sym is None:
            return None
        kind, target = sym
        if kind == "class" and len(parts) == 1:
            return target
        if kind == "module" and len(parts) >= 2:
            sub_mod = ".".join([target] + parts[1:-1])
            if f"{sub_mod}:{parts[-1]}" in self.classes:
                return f"{sub_mod}:{parts[-1]}"
        return None

    # -- call resolution -----------------------------------------------
    def resolve_call(self, call: ast.Call,
                     scope: FuncInfo) -> Optional[str]:
        """Qual of the project function a call targets, or None."""
        func = call.func
        if isinstance(func, ast.Name):
            sym = self._resolve_symbol(scope.module, func.id)
            if sym is None:
                return None
            kind, target = sym
            if kind == "func":
                return target
            if kind == "class":
                return self.classes[target]["methods"].get("__init__")
            return None
        dotted = dotted_name(func)
        if not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] in ("self", "cls") and scope.cls is not None:
            if len(parts) == 2:
                return self._resolve_method(scope.cls, parts[1])
            return None  # self.obj.m(...): attribute types unknown
        if len(parts) < 2:
            return None
        sym = self._resolve_symbol(scope.module, parts[0])
        if sym is None:
            return None
        kind, target = sym
        if kind == "class" and len(parts) == 2:
            return self._resolve_method(target, parts[1])
        if kind == "module":
            mod = ".".join([target] + parts[1:-1])
            if f"{mod}:{parts[-1]}" in self.funcs:
                return f"{mod}:{parts[-1]}"
            if len(parts) >= 3:
                mod2 = ".".join([target] + parts[1:-2])
                cls_key = f"{mod2}:{parts[-2]}"
                if cls_key in self.classes:
                    return self._resolve_method(cls_key, parts[-1])
        return None

    # -- graph queries -------------------------------------------------
    def calls_in(self, qual: str) -> List[ast.Call]:
        """Raw call nodes of a function, nested defs excluded."""
        cached = self._calls.get(qual)
        if cached is None:
            info = self.funcs[qual]
            cached = [n for part in
                      (info.node.body if hasattr(info.node, "body")
                       else [])
                      for n in _walk_no_nested(part)
                      if isinstance(n, ast.Call)]
            self._calls[qual] = cached
        return cached

    def callees(self, qual: str) -> Tuple[str, ...]:
        cached = self._callees.get(qual)
        if cached is None:
            info = self.funcs[qual]
            out = []
            for call in self.calls_in(qual):
                target = self.resolve_call(call, info)
                if target is not None and target != qual \
                        and target not in out:
                    out.append(target)
            cached = tuple(out)
            self._callees[qual] = cached
        return cached

    def reaches(self, qual: str, pred: Callable[[str], bool],
                max_depth: int = 4) -> Optional[List[str]]:
        """BFS the call graph from ``qual`` (exclusive) up to
        ``max_depth`` edges; returns the first call chain
        ``[qual, ..., hit]`` whose tip satisfies ``pred``, else
        None."""
        frontier = [[qual]]
        seen = {qual}
        for _ in range(max_depth):
            nxt = []
            for chain in frontier:
                for callee in self.callees(chain[-1]):
                    if callee in seen:
                        continue
                    seen.add(callee)
                    new_chain = chain + [callee]
                    if pred(callee):
                        return new_chain
                    nxt.append(new_chain)
            frontier = nxt
            if not frontier:
                break
        return None
