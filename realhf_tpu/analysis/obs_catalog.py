"""obs-catalog-drift checker: the metric catalog matches the code.

``docs/observability.md`` carries the catalog every dashboard,
recording rule, and alert is written against. Because the metrics
registry creates metrics lazily, catalog drift never fails fast -- a
renamed counter silently splits a series, an undocumented one is
invisible to operators. This project checker diffs BOTH directions:

- a **literal** metric name at an instrumentation call site
  (``inc`` / ``set_gauge`` / ``observe`` / ``observe_hist`` /
  ``event`` or a registry constructor) that does not appear in the
  catalog -> finding at the call site;
- a catalog row naming a metric that no call site emits -> finding
  at the doc line.

Catalog rows may use brace alternation (``serving_{a,b}_total``
expands to both names) and label sets (a trailing ``{label,...}``
group is dropped). Dynamic names in code are handled two ways:
f-strings with literal head/tail (``f"serving_{key}_total"``) become
patterns that EXCUSE matching doc rows (the doc side can document
what the code spells dynamically), and entirely dynamic names are
out of scope -- the checker never guesses.
"""

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from realhf_tpu.analysis.core import (
    ProjectChecker,
    iter_python_files,
)
from realhf_tpu.analysis.finding import Finding

#: instrumentation entry points taking a literal metric name first
METRIC_CALLS = ("inc", "set_gauge", "observe", "observe_hist",
                "counter", "gauge", "summary", "histogram", "event")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_HEADING_RE = re.compile(r"^#{2,}\s")


def expand_doc_token(token: str) -> Set[str]:
    """Expand one backticked catalog token into metric names: a
    trailing ``{...}`` group is a label set (dropped); an interior
    one is brace alternation (each alternative recursively
    expanded)."""
    i = token.find("{")
    if i < 0:
        return {token} if _NAME_RE.match(token) else set()
    depth, j = 0, i
    for j in range(i, len(token)):
        if token[j] == "{":
            depth += 1
        elif token[j] == "}":
            depth -= 1
            if depth == 0:
                break
    if depth != 0:
        return set()
    head, group, tail = token[:i], token[i + 1:j], token[j + 1:]
    if not tail:  # trailing group = label set
        return expand_doc_token(head)
    alts, buf, depth = [], "", 0
    for ch in group:
        if ch == "," and depth == 0:
            alts.append(buf)
            buf = ""
            continue
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        buf += ch
    alts.append(buf)
    out: Set[str] = set()
    for alt in alts:
        # expand the alternative itself first (it may carry its own
        # label group), then splice into head/tail and re-expand
        for mid in expand_doc_token(alt.strip()):
            out |= expand_doc_token(head + mid + tail)
    return out


def parse_catalog(doc_text: str) -> Dict[str, int]:
    """metric name -> first line number, from the '### Catalog'
    section's table rows."""
    out: Dict[str, int] = {}
    in_catalog = False
    for lineno, line in enumerate(doc_text.splitlines(), start=1):
        if line.strip().startswith("### Catalog"):
            in_catalog = True
            continue
        if in_catalog and _HEADING_RE.match(line):
            break
        if not in_catalog or not line.lstrip().startswith("|"):
            continue
        first_cell = line.split("|")[1] if line.count("|") >= 2 else ""
        for token in re.findall(r"`([^`]+)`", first_cell):
            for name in expand_doc_token(token.strip()):
                out.setdefault(name, lineno)
    return out


def _literal_or_pattern(call: ast.Call
                        ) -> Tuple[Optional[str], Optional[str]]:
    """(literal name, regex pattern) of the call's first arg: a
    constant yields a literal, an f-string with constant fragments a
    pattern, anything else (None, None)."""
    if not call.args:
        return None, None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, None
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(re.escape(str(v.value)))
            else:
                parts.append(r"[a-z0-9_]+")
        return None, "".join(parts)
    return None, None


class ObsCatalogChecker(ProjectChecker):
    name = "obs-catalog"
    cacheable = True

    def __init__(self, package: str = "realhf_tpu",
                 doc_path: str = os.path.join("docs",
                                              "observability.md")):
        self.package = package
        self.doc_path = doc_path

    def stamp_extra(self, root: str) -> str:
        try:
            with open(os.path.join(root, self.doc_path),
                      encoding="utf-8") as f:
                import hashlib
                return hashlib.sha1(f.read().encode()).hexdigest()
        except OSError:
            return "missing"

    # ------------------------------------------------------------------
    def check_project(self, root: str) -> List[Finding]:
        doc_abs = os.path.join(root, self.doc_path)
        pkg_abs = os.path.join(root, self.package)
        if not os.path.exists(doc_abs) or not os.path.isdir(pkg_abs):
            return []  # fixture trees without the doc: nothing to pin
        with open(doc_abs, encoding="utf-8") as f:
            doc_text = f.read()
        doc_names = parse_catalog(doc_text)
        doc_rel = self.doc_path.replace(os.sep, "/")

        #: literal name -> first (relpath, line, col, symbol)
        code_names: Dict[str, Tuple] = {}
        patterns: List[str] = []
        for path in iter_python_files([pkg_abs], root):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError, ValueError):
                continue
            from realhf_tpu.analysis.core import enclosing_symbols
            symbols = enclosing_symbols(tree)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                attr = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name)
                          else "")
                if attr not in METRIC_CALLS:
                    continue
                literal, pattern = _literal_or_pattern(node)
                if pattern is not None:
                    patterns.append(pattern)
                if literal is None or not _NAME_RE.match(literal):
                    continue
                code_names.setdefault(
                    literal, (rel, node.lineno, node.col_offset,
                              symbols.get(node, "")))

        findings: List[Finding] = []
        for name in sorted(code_names):
            if name in doc_names:
                continue
            rel, line, col, symbol = code_names[name]
            findings.append(Finding(
                checker=self.name, code="obs-catalog-drift",
                path=rel, line=line, col=col,
                message=(f"metric `{name}` is emitted here but "
                         f"missing from the {doc_rel} catalog -- "
                         "add a row (operators only see documented "
                         "series)"),
                symbol=symbol))
        compiled = [re.compile(p + r"$") for p in patterns]
        for name in sorted(doc_names):
            if name in code_names:
                continue
            if any(p.match(name) for p in compiled):
                continue  # spelled dynamically in code
            findings.append(Finding(
                checker=self.name, code="obs-catalog-drift",
                path=doc_rel, line=doc_names[name], col=0,
                message=(f"catalog row names metric `{name}` but no "
                         "call site emits it -- stale doc or renamed "
                         "metric (dashboards built on it see no "
                         "data)"),
                symbol=name))
        return findings
