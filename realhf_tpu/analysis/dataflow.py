"""Shared worklist-dataflow framework over analysis CFGs.

A small forward engine the path-sensitive checker families
(``lifecycle``, ``terminal``) share. States are immutable; a checker
supplies

- ``init``: the state entering the function;
- ``transfer(node, state, kind) -> state``: the effect of executing
  one statement node along an out-edge of the given kind (NORMAL;
  TRUE/FALSE for the two arms of a branch header; EXC for the edge an
  escaping exception takes -- the checker decides which of the
  statement's effects "happened" on each kind; virtual nodes pass
  state through);
- ``join(a, b) -> state``: the merge at control-flow confluences.

``join`` must be monotone over a finite lattice -- the engine
iterates to fixpoint and returns the in-state of every reachable
node.
"""

from typing import Callable, Dict

from realhf_tpu.analysis.cfg import CFG


def run_forward(
    cfg: CFG,
    init,
    transfer: Callable,
    join: Callable,
    max_iter: int = 100000,
) -> Dict[int, object]:
    """Fixpoint forward analysis; returns node idx -> in-state for
    every node reachable from the entry."""
    in_states: Dict[int, object] = {cfg.entry: init}
    work = [cfg.entry]
    iters = 0
    while work:
        iters += 1
        if iters > max_iter:  # safety valve; lattices here are tiny
            break
        idx = work.pop()
        state = in_states[idx]
        node = cfg.nodes[idx]
        post: Dict[str, object] = {}  # per-edge-kind, computed lazily
        for to, kind in node.succs:
            if kind not in post:
                post[kind] = transfer(node, state, kind)
            out = post[kind]
            prev = in_states.get(to)
            merged = out if prev is None else join(prev, out)
            if prev is None or merged != prev:
                in_states[to] = merged
                if to not in work:
                    work.append(to)
    return in_states
