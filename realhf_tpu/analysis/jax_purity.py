"""jax-purity checker: host syncs and impurity under JAX tracing.

Jitted code must stay pure: a ``.item()`` / ``np.asarray`` /
``jax.device_get`` inside a traced function forces a blocking
host<->device round-trip per trace-time call site (and silently
freezes the value at trace time when the result feeds Python control
flow); ``time.time()`` / ``random.*`` / ``print`` burn themselves into
the compiled program once; mutating a closed-over list leaks tracers
across traces. This checker resolves which functions run under trace
-- ``@jax.jit``-style decorators, wrapper assignments
(``self._f = jax.jit(functools.partial(f, ...))``), and call sites
(``lax.scan(body, ...)``, ``while_loop(cond, body, ...)``) -- then
flags the impurities inside them.

A second rule (``purity-sync-in-loop``) targets HOST-side decode hot
paths: a per-element ``.item()`` / ``np.asarray`` inside a Python loop
pays one device sync per iteration; batch it into a single bundled
``jax.device_get`` before the loop (see docs/perf.md).

Rules:
- ``purity-host-sync``: host transfer inside a traced function.
- ``purity-impure-call``: wall-clock / host-RNG / I-O call inside a
  traced function.
- ``purity-closure-mutation``: mutation of a closed-over container
  inside a traced function.
- ``purity-sync-in-loop``: per-iteration host transfer in host-side
  engine/serving loops.
- ``purity-obs-in-trace``: observability call (``obs.tracing`` span,
  metrics registry op, flight-recorder append) inside a traced
  function. Spans time wall-clock and metrics mutate host state:
  under trace they execute ONCE at trace time, so the timeline/counts
  they produce are lies -- instrument around the jitted call instead
  (docs/observability.md).
"""

import ast
from typing import Dict, List, Set

from realhf_tpu.analysis.core import (
    AstChecker,
    Module,
    call_name,
    dotted_name,
)
from realhf_tpu.analysis.finding import Finding

#: call wrappers whose function-valued arguments run under trace
TRACE_WRAPPERS = {
    "jit", "pjit", "shard_map", "scan", "while_loop", "cond",
    "fori_loop", "vmap", "pmap", "grad", "value_and_grad", "remat",
    "checkpoint", "custom_vjp", "custom_jvp", "map", "switch",
    "associated_scan", "associative_scan",
}

#: decorator names (last dotted component) marking a def as traced
TRACE_DECORATORS = {"jit", "pjit", "shard_map", "vmap", "pmap",
                    "grad", "value_and_grad", "remat", "checkpoint",
                    "custom_vjp", "custom_jvp"}

HOST_SYNC_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array", "jax.device_get", "device_get",
    "jax.block_until_ready",
}
HOST_SYNC_METHODS = {"item", "block_until_ready", "tolist", "copy_to_host"}

IMPURE_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "print", "input", "open",
}
IMPURE_PREFIXES = ("random.", "np.random.", "numpy.random.",
                   "os.urandom")

MUTATOR_METHODS = {"append", "extend", "insert", "remove", "pop",
                   "clear", "add", "update", "setdefault", "popitem"}

#: observability namespaces (realhf_tpu/obs/) whose calls must stay
#: host-side -- a span/counter inside a jitted function fires once at
#: trace time and records garbage
OBS_PREFIXES = ("tracing.", "obs_tracing.", "metrics.", "obs_metrics.",
                "flight.", "obs_flight.", "obs.tracing.", "obs.metrics.",
                "obs.flight.")
#: obs API entry points (module-level convenience functions AND the
#: Tracer/MetricsRegistry/FlightRecorder methods)
OBS_METHODS = {"span", "start_span", "inc", "set_gauge", "observe",
               "event", "record", "maybe_flush", "flush"}

#: package paths where the host-loop rule applies (decode hot paths)
_HOT_PATH_PREFIXES = ("realhf_tpu/engine/", "realhf_tpu/serving/")


def _is_wrapper_name(name: str) -> bool:
    if "tree" in name:  # jax.tree.map / tree_util.* run on the host
        return False
    last = name.rsplit(".", 1)[-1]
    return last in TRACE_WRAPPERS and (
        "." not in name
        or name.split(".", 1)[0] in ("jax", "lax", "functools", "jnp")
        or ".lax." in name or ".experimental." in name
        or name.startswith("jax."))


def _function_args(call: ast.Call) -> List[ast.AST]:
    """Positional arguments of a wrapper call that can denote
    functions: bare names, lambdas, local defs via functools.partial."""
    out: List[ast.AST] = []
    for arg in call.args:
        if isinstance(arg, (ast.Name, ast.Lambda)):
            out.append(arg)
        elif isinstance(arg, ast.Call):
            inner = call_name(arg)
            if inner.rsplit(".", 1)[-1] == "partial" and arg.args:
                out.append(arg.args[0])
    return out


class _Scope(ast.NodeVisitor):
    """Collects local bindings of one function (no nested defs)."""

    def __init__(self, fn: ast.AST):
        self.names: Set[str] = set()
        a = fn.args
        for grp in (a.posonlyargs, a.args, a.kwonlyargs):
            self.names.update(x.arg for x in grp)
        for va in (a.vararg, a.kwarg):
            if va is not None:
                self.names.add(va.arg)
        for node in ast.walk(fn):
            if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.names.add(node.name)
                continue
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                self.names.add(node.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    self.names.add(
                        (alias.asname or alias.name).split(".")[0])


class JaxPurityChecker(AstChecker):
    name = "jax-purity"

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith((
            "realhf_tpu/engine/", "realhf_tpu/interfaces/",
            "realhf_tpu/ops/", "realhf_tpu/models/",
            "realhf_tpu/serving/", "realhf_tpu/parallel/",
            "realhf_tpu/search/"))

    # ------------------------------------------------------------------
    def check(self, module: Module) -> List[Finding]:
        defs = [n for n in ast.walk(module.tree)
                if isinstance(n, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))]
        by_name: Dict[str, List[ast.AST]] = {}
        for d in defs:
            by_name.setdefault(d.name, []).append(d)

        traced: Set[ast.AST] = set()
        # (a) decorators
        for d in defs:
            for dec in d.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                nm = dotted_name(target)
                if nm and nm.rsplit(".", 1)[-1] in TRACE_DECORATORS:
                    traced.add(d)
        # (b) wrapper call sites anywhere in the module
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = call_name(node)
            if not nm or not _is_wrapper_name(nm):
                continue
            for arg in _function_args(node):
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name):
                    traced.update(by_name.get(arg.id, ()))
        # (c) closure: nested defs and same-module helpers referenced
        # from traced bodies run under the same trace
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                for node in ast.walk(fn):
                    if node is fn:
                        continue
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        if node not in traced:
                            traced.add(node)
                            changed = True
                    elif (isinstance(node, ast.Name)
                          and isinstance(node.ctx, ast.Load)
                          and node.id in by_name):
                        for cand in by_name[node.id]:
                            if cand not in traced:
                                traced.add(cand)
                                changed = True

        findings: List[Finding] = []
        for fn in traced:
            if isinstance(fn, ast.Lambda):
                continue  # single expressions: covered via host fns
            findings.extend(self._check_traced(module, fn))
        if (not module.relpath.startswith("realhf_tpu/")
                or module.relpath.startswith(_HOT_PATH_PREFIXES)):
            findings.extend(
                self._check_host_loops(module, traced))
        return findings

    # ------------------------------------------------------------------
    def _check_traced(self, module: Module, fn) -> List[Finding]:
        findings: List[Finding] = []
        scope = _Scope(fn)
        for node in self._walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            nm = call_name(node)
            f = None
            if nm in HOST_SYNC_CALLS:
                f = ("purity-host-sync",
                     f"`{nm}` forces a host sync inside traced "
                     f"function `{fn.name}`; return device values and "
                     "transfer after the jitted call")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in HOST_SYNC_METHODS
                  and not node.args):
                f = ("purity-host-sync",
                     f"`.{node.func.attr}()` forces a host sync inside "
                     f"traced function `{fn.name}`")
            elif nm in ("float", "int", "bool") and self._syncs(node):
                f = ("purity-host-sync",
                     f"`{nm}()` on a traced value forces a host sync "
                     f"inside traced function `{fn.name}`")
            elif (nm.startswith(OBS_PREFIXES)
                  and nm.rsplit(".", 1)[-1] in OBS_METHODS):
                f = ("purity-obs-in-trace",
                     f"observability call `{nm}` inside traced "
                     f"function `{fn.name}` executes once at trace "
                     "time (spans/metrics record garbage); instrument "
                     "around the jitted call")
            elif nm in IMPURE_CALLS or nm.startswith(IMPURE_PREFIXES):
                f = ("purity-impure-call",
                     f"impure call `{nm}` inside traced function "
                     f"`{fn.name}` executes once at trace time; use "
                     "jax-native equivalents")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in MUTATOR_METHODS
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id not in scope.names):
                f = ("purity-closure-mutation",
                     f"`{node.func.value.id}.{node.func.attr}(...)` "
                     f"mutates a closed-over container inside traced "
                     f"function `{fn.name}`; tracers leak across "
                     "traces")
            if f is not None:
                findings.append(self.finding(module, f[0], node, f[1],
                                             symbol=fn.name))
        return findings

    @staticmethod
    def _walk_shallow(fn):
        """Walk a function body without descending into nested defs
        (they are traced-set members checked on their own)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _syncs(call: ast.Call) -> bool:
        """float()/int() on shapes, lens, or literals is static and
        fine; anything else on a traced value blocks."""
        if len(call.args) != 1:
            return False
        arg = call.args[0]
        if isinstance(arg, (ast.Constant, ast.UnaryOp)):
            return False
        src = ast.unparse(arg)
        return not any(t in src for t in (".shape", ".ndim", ".size",
                                          "len("))

    # ------------------------------------------------------------------
    def _check_host_loops(self, module: Module,
                          traced: Set[ast.AST]) -> List[Finding]:
        """Per-iteration host transfers in host-side loops."""
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn in traced:
                continue
            for loop in self._walk_shallow(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    nm = call_name(node)
                    is_sync = nm in HOST_SYNC_CALLS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("item",
                                               "block_until_ready")
                        and not node.args)
                    if is_sync:
                        findings.append(self.finding(
                            module, "purity-sync-in-loop", node,
                            f"per-iteration host transfer `{nm or node.func.attr}` "
                            f"in host loop of `{fn.name}`; batch into "
                            "one jax.device_get before the loop",
                            symbol=fn.name))
        return findings
