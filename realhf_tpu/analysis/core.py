"""graft-lint engine: shared visitor core and checker registry.

AST checkers subclass :class:`AstChecker` and get one parsed
:class:`Module` per file; :class:`GraphChecker` subclasses
additionally receive a project-wide
:class:`~realhf_tpu.analysis.callgraph.ProjectIndex` (built over
``project_paths`` -- the whole package even when only a subset of
files is being reported on) before their per-file ``check`` runs;
project checkers subclass :class:`ProjectChecker` and run once per
invocation (the dfg-invariants pass imports experiment registries
instead of reading syntax). ``run_analysis`` walks the requested
paths, applies per-file suppressions, and returns the surviving
findings sorted by location.

Results are cacheable (:mod:`realhf_tpu.analysis.cache`): per-file
findings key on the file's content hash, interprocedural and
cacheable project findings key on a whole-tree stamp, and
``ENGINE_VERSION`` invalidates everything when the engine itself
changes behavior.
"""

import ast
import dataclasses
import hashlib
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from realhf_tpu.analysis.finding import Finding
from realhf_tpu.analysis.suppress import Suppressions

#: bump when checker/engine semantics change: every cache entry keyed
#: on an older version is discarded
ENGINE_VERSION = 2

#: directories never scanned (generated trees, VCS, caches)
SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
             ".claude", ".graft_lint_cache"}


@dataclasses.dataclass
class Module:
    """One parsed source file handed to AST checkers."""
    path: str          # absolute
    relpath: str       # repo-relative posix path (used in findings)
    source: str
    tree: ast.AST
    suppressions: Suppressions

    @classmethod
    def parse(cls, path: str, root: str) -> Optional["Module"]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            return None
        return cls.from_source(path, root, source)

    @classmethod
    def from_source(cls, path: str, root: str,
                    source: str) -> Optional["Module"]:
        try:
            tree = ast.parse(source, filename=path)
        except (SyntaxError, ValueError):
            return None  # unparseable files are not lint findings
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        return cls(path=path, relpath=rel, source=source, tree=tree,
                   suppressions=Suppressions(source))


class AstChecker:
    """Base of per-file checkers. Subclasses set ``name`` (family id)
    and implement ``check(module) -> List[Finding]``."""

    name: str = ""

    def applies_to(self, relpath: str) -> bool:
        """Default file filter. Only consulted for files inside the
        ``realhf_tpu`` package tree -- external trees (fixture dirs,
        explicit file arguments outside the package) always run every
        checker, which is what the fixture tests rely on."""
        return True

    def check(self, module: Module) -> List[Finding]:
        raise NotImplementedError

    def finding(self, module: Module, code: str, node, message: str,
                symbol: str = "") -> Finding:
        return Finding(
            checker=self.name, code=code, path=module.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message, symbol=symbol)


class GraphChecker(AstChecker):
    """Per-file checker that needs the whole-project call graph.

    ``prepare(index)`` is called once per run with the
    :class:`~realhf_tpu.analysis.callgraph.ProjectIndex` built over
    every scanned file; ``check(module)`` then reports findings for
    one file at a time. Findings are cached against the whole-tree
    stamp (any file change re-runs the family)."""

    def prepare(self, index) -> None:
        self.index = index


class ProjectChecker:
    """Base of import-time (whole-project) checkers."""

    name: str = ""
    #: True when ``check_project`` is a pure function of the scanned
    #: tree (cacheable under the tree stamp); import-time passes that
    #: execute project code stay False
    cacheable: bool = False

    def stamp_extra(self, root: str) -> str:
        """Extra cache-stamp material (e.g. a doc file's content hash)
        for cacheable checkers whose inputs go beyond the .py tree."""
        return ""

    def diff_relevant(self, changed: Sequence[str]) -> bool:
        """Whether ``--diff`` mode should still run this checker for
        the given changed repo-relative paths. Default False: most
        project passes don't decompose per file and are skipped in
        the fast pre-commit mode. Cacheable checkers with a narrow
        scope (wire, model) override this so protocol edits are
        checked before they are committed."""
        return False

    def check_project(self, root: str) -> List[Finding]:
        raise NotImplementedError


def iter_python_files(paths: Sequence[str], root: str) -> Iterable[str]:
    """Yield .py files under ``paths`` (files or directories),
    deterministically sorted so every host reports findings in the
    same order."""
    seen = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            if p not in seen:
                seen.add(p)
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    if fp not in seen:
                        seen.add(fp)
                        yield fp


def _in_package(relpath: str) -> bool:
    return relpath == "realhf_tpu" or relpath.startswith("realhf_tpu/")


def _sha1(data: str) -> str:
    return hashlib.sha1(data.encode("utf-8", "replace")).hexdigest()


def run_analysis(
    paths: Sequence[str],
    checkers: Sequence[object],
    root: Optional[str] = None,
    on_file: Optional[Callable[[str], None]] = None,
    project_paths: Optional[Sequence[str]] = None,
    cache=None,
) -> List[Finding]:
    """Run ``checkers`` over ``paths``; returns unsuppressed findings
    sorted by (path, line, code).

    ``project_paths`` (default: ``paths``) names the tree the
    interprocedural call graph is built over -- pass the full package
    when ``paths`` is a changed-files subset (``--diff``). ``cache``
    is an optional :class:`~realhf_tpu.analysis.cache.AnalysisCache`.
    """
    root = os.path.abspath(root or os.getcwd())
    ast_checkers = [c for c in checkers if isinstance(c, AstChecker)]
    graph_checkers = [c for c in ast_checkers
                      if isinstance(c, GraphChecker)]
    local_checkers = [c for c in ast_checkers
                      if not isinstance(c, GraphChecker)]
    project_checkers = [c for c in checkers
                        if isinstance(c, ProjectChecker)]

    scan_files = list(iter_python_files(paths, root))
    if project_paths is not None:
        all_files = list(iter_python_files(project_paths, root))
        for p in scan_files:
            if p not in all_files:
                all_files.append(p)
    else:
        all_files = list(scan_files)

    # read + hash every involved file once; unreadable files drop out
    sources: Dict[str, str] = {}
    shas: Dict[str, str] = {}
    for path in list(all_files):
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[path] = f.read()
        except OSError:
            all_files.remove(path)
            if path in scan_files:
                scan_files.remove(path)
            continue
        shas[path] = _sha1(sources[path])

    def rel(path: str) -> str:
        return os.path.relpath(path, root).replace(os.sep, "/")

    full_scan = set(scan_files) == set(all_files)
    scan_rels = {rel(p) for p in scan_files}

    # whole-tree stamp: any content change re-runs the graph families
    stamp_parts = [f"{rel(p)}:{shas[p]}" for p in sorted(all_files)]
    for c in project_checkers:
        extra = c.stamp_extra(root)
        if extra:
            stamp_parts.append(f"{c.name}:{extra}")
    stamp = _sha1("\n".join(stamp_parts))

    stamped_checkers = list(graph_checkers) + [
        c for c in project_checkers if c.cacheable]
    cached_stamped: Dict[str, List[Finding]] = {}
    if cache is not None:
        for c in stamped_checkers:
            hit = cache.get_project(stamp, c.name)
            if hit is None:
                cached_stamped = {}
                break
            cached_stamped[c.name] = hit
        cache.stats["project_hit"] = (
            bool(stamped_checkers) and len(cached_stamped)
            == len(stamped_checkers))
    stamped_hit = (cache is not None and stamped_checkers
                   and len(cached_stamped) == len(stamped_checkers))
    run_graph = bool(graph_checkers) and not stamped_hit

    # parse what this run actually needs
    modules: Dict[str, Module] = {}

    def module_for(path: str) -> Optional[Module]:
        if path not in modules:
            modules[path] = Module.from_source(path, root,
                                               sources[path])
        return modules[path]

    if run_graph:
        from realhf_tpu.analysis.callgraph import ProjectIndex
        parsed = [m for m in (module_for(p) for p in all_files)
                  if m is not None]
        index = ProjectIndex(parsed)
        for c in graph_checkers:
            c.prepare(index)

    findings: List[Finding] = []
    graph_fresh: Dict[str, List[Finding]] = {
        c.name: [] for c in graph_checkers}
    for path in scan_files:
        if on_file is not None:
            on_file(path)
        relpath = rel(path)
        in_pkg = _in_package(relpath)

        def want(checker) -> bool:
            return not in_pkg or checker.applies_to(relpath)

        pending = []
        for checker in local_checkers:
            hit = None if cache is None else cache.get_local(
                relpath, shas[path], checker.name)
            if hit is not None:
                findings.extend(hit)
            else:
                pending.append(checker)
        if pending or run_graph:
            module = module_for(path)
            if module is None:
                continue
            for checker in pending:
                result = module.suppressions.filter(
                    checker.check(module)) if want(checker) else []
                findings.extend(result)
                if cache is not None:
                    cache.put_local(relpath, shas[path], checker.name,
                                    result)
            if run_graph:
                for checker in graph_checkers:
                    result = module.suppressions.filter(
                        checker.check(module)) if want(checker) else []
                    findings.extend(result)
                    graph_fresh[checker.name].extend(result)
        if stamped_hit:
            for c in graph_checkers:
                findings.extend(f for f in cached_stamped[c.name]
                                if f.path == relpath)

    for checker in project_checkers:
        if checker.cacheable and stamped_hit:
            findings.extend(f for f in cached_stamped[checker.name]
                            if full_scan or f.path in scan_rels)
            continue
        result = checker.check_project(root)
        findings.extend(result)
        if (cache is not None and checker.cacheable and full_scan):
            cache.put_project(stamp, checker.name, result)
    if cache is not None and run_graph and full_scan:
        for name, fs in graph_fresh.items():
            cache.put_project(stamp, name, fs)
    if cache is not None:
        cache.save()

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code,
                                 f.message))
    return findings


# ----------------------------------------------------------------------
# Shared AST helpers used by several checker families.
# ----------------------------------------------------------------------
def dotted_name(node: ast.AST) -> str:
    """``jax.lax.scan`` for the matching Attribute/Name chain, ""
    otherwise (calls, subscripts, ... yield "")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def enclosing_symbols(tree: ast.AST) -> Dict[ast.AST, str]:
    """node -> qualname of the innermost enclosing def/class, for
    every node in ``tree`` (module-level nodes map to "")."""
    out: Dict[ast.AST, str] = {}

    def visit(node: ast.AST, qual: str):
        for child in ast.iter_child_nodes(node):
            child_qual = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_qual = (f"{qual}.{child.name}" if qual
                              else child.name)
            out[child] = child_qual
            visit(child, child_qual)
    out[tree] = ""
    visit(tree, "")
    return out
