"""graft-lint engine: shared visitor core and checker registry.

AST checkers subclass :class:`AstChecker` and get one parsed
:class:`Module` per file; project checkers subclass
:class:`ProjectChecker` and run once per invocation (the
dfg-invariants pass imports experiment registries instead of reading
syntax). ``run_analysis`` walks the requested paths, applies per-file
suppressions, and returns the surviving findings sorted by location.
"""

import ast
import dataclasses
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from realhf_tpu.analysis.finding import Finding
from realhf_tpu.analysis.suppress import Suppressions

#: directories never scanned (generated trees, VCS, caches)
SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
             ".claude"}


@dataclasses.dataclass
class Module:
    """One parsed source file handed to AST checkers."""
    path: str          # absolute
    relpath: str       # repo-relative posix path (used in findings)
    source: str
    tree: ast.AST
    suppressions: Suppressions

    @classmethod
    def parse(cls, path: str, root: str) -> Optional["Module"]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError, ValueError):
            return None  # unparseable files are not lint findings
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        return cls(path=path, relpath=rel, source=source, tree=tree,
                   suppressions=Suppressions(source))


class AstChecker:
    """Base of per-file checkers. Subclasses set ``name`` (family id)
    and implement ``check(module) -> List[Finding]``."""

    name: str = ""

    def applies_to(self, relpath: str) -> bool:
        """Default file filter. Only consulted for files inside the
        ``realhf_tpu`` package tree -- external trees (fixture dirs,
        explicit file arguments outside the package) always run every
        checker, which is what the fixture tests rely on."""
        return True

    def check(self, module: Module) -> List[Finding]:
        raise NotImplementedError

    def finding(self, module: Module, code: str, node, message: str,
                symbol: str = "") -> Finding:
        return Finding(
            checker=self.name, code=code, path=module.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message, symbol=symbol)


class ProjectChecker:
    """Base of import-time (whole-project) checkers."""

    name: str = ""

    def check_project(self, root: str) -> List[Finding]:
        raise NotImplementedError


def iter_python_files(paths: Sequence[str], root: str) -> Iterable[str]:
    """Yield .py files under ``paths`` (files or directories),
    deterministically sorted so every host reports findings in the
    same order."""
    seen = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            if p not in seen:
                seen.add(p)
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    if fp not in seen:
                        seen.add(fp)
                        yield fp


def _in_package(relpath: str) -> bool:
    return relpath == "realhf_tpu" or relpath.startswith("realhf_tpu/")


def run_analysis(
    paths: Sequence[str],
    checkers: Sequence[object],
    root: Optional[str] = None,
    on_file: Optional[Callable[[str], None]] = None,
) -> List[Finding]:
    """Run ``checkers`` over ``paths``; returns unsuppressed findings
    sorted by (path, line, code)."""
    root = os.path.abspath(root or os.getcwd())
    ast_checkers = [c for c in checkers if isinstance(c, AstChecker)]
    project_checkers = [c for c in checkers
                        if isinstance(c, ProjectChecker)]
    findings: List[Finding] = []
    for path in iter_python_files(paths, root):
        if on_file is not None:
            on_file(path)
        module = Module.parse(path, root)
        if module is None:
            continue
        for checker in ast_checkers:
            if (_in_package(module.relpath)
                    and not checker.applies_to(module.relpath)):
                continue
            findings.extend(
                module.suppressions.filter(checker.check(module)))
    for checker in project_checkers:
        findings.extend(checker.check_project(root))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code,
                                 f.message))
    return findings


# ----------------------------------------------------------------------
# Shared AST helpers used by several checker families.
# ----------------------------------------------------------------------
def dotted_name(node: ast.AST) -> str:
    """``jax.lax.scan`` for the matching Attribute/Name chain, ""
    otherwise (calls, subscripts, ... yield "")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def enclosing_symbols(tree: ast.AST) -> Dict[ast.AST, str]:
    """node -> qualname of the innermost enclosing def/class, for
    every node in ``tree`` (module-level nodes map to "")."""
    out: Dict[ast.AST, str] = {}

    def visit(node: ast.AST, qual: str):
        for child in ast.iter_child_nodes(node):
            child_qual = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_qual = (f"{qual}.{child.name}" if qual
                              else child.name)
            out[child] = child_qual
            visit(child, child_qual)
    out[tree] = ""
    visit(tree, "")
    return out
