"""dfg-invariants checker: import-time validation of experiment DFGs.

Unlike the AST families, this pass LOADS the registered experiment
configs (``realhf_tpu.experiments.ALL_EXPERIMENT_CLASSES``), builds
each spec with safe defaults, and statically validates the resulting
dataflow graph -- the invariants the paper's per-MFC-mesh execution
model rests on:

- ``dfg-build-failed``: the experiment's ``build()`` (or graph
  resolution) raises under defaults -- the config cannot even be
  validated.
- ``dfg-cycle`` / ``dfg-duplicate-key``: graph resolution errors
  (cyclic MFC dependencies, two producers for one data key).
- ``dfg-batch-mismatch``: an MFC's ``n_seqs`` violates the per-sample
  buffer contract. Producer and consumer n_seqs need only SHARE
  samples (the buffer assembles each MFC's batch from ready samples,
  spanning dataset batches), so the old pairwise-divisibility rule is
  gone; what must still hold is (a) every ``n_seqs`` > 0 and (b) no
  MFC asks for more samples than the buffer window can ever hold at
  once (``max_concurrent_batches * source n_seqs``) -- such an MFC
  could never assemble a full batch and would deadlock the dispatch
  loop short of the end-of-data flush.
- ``dfg-multiturn-batch``: an environment-in-the-loop generate MFC
  (interface declares ``max_turns > 1``, ``realhf_tpu/agentic/``)
  either is not a SOURCE node (episodes must enter the buffer from
  the dataset, not from upstream MFC outputs), or some MFC's
  ``n_seqs`` exceeds the episode window
  ``max_concurrent_batches * gen n_seqs`` -- one episode yields one
  buffer sample, so a larger batch can never assemble.
- ``dfg-mesh-mismatch``: two MFCs placed on the SAME worker group
  whose layouts multiply to different world sizes -- a group has a
  fixed device count, so all layouts on it must use all of it.
- ``dfg-bad-alloc``: allocation normalization errors (empty/duplicate
  worker groups, allocation naming an unknown MFC).
- ``dfg-realloc-order``: two MFCs of one role that carry distinct
  weight layouts (or explicit ParamReallocHooks) are CONCURRENT in
  the DAG -- reallocations of that role's weights would race; the
  realloc chain must be totally ordered, and the per-role orders must
  embed in one global topological order (guaranteed acyclic graph +
  per-role chains).
"""

import inspect
import os
from typing import List

from realhf_tpu.analysis.core import ProjectChecker
from realhf_tpu.analysis.finding import Finding


def _spec_location(cls, root: str):
    """(relpath, line) of an experiment config class."""
    try:
        path = inspect.getsourcefile(cls)
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        line = inspect.getsourcelines(cls)[1]
        return rel, line
    except (TypeError, OSError):
        return "realhf_tpu/experiments", 0


def build_default_spec(cls):
    """Instantiate an experiment config with lint-safe defaults and
    build its spec. Returns None for experiments with no DFG (serve)."""
    cfg = cls()
    cfg.experiment_name = "graft-lint"
    cfg.trial_name = "dfg-check"
    ds = getattr(cfg, "dataset", None)
    if ds is not None and hasattr(ds, "path") and not ds.path:
        ds.path = "/dev/null"
    spec = cfg.build()
    if not getattr(spec, "mfcs", None):
        return None
    return spec


def validate_spec(name: str, spec, path: str, line: int
                  ) -> List[Finding]:
    """Pure validation of one built ExperimentSpec's DFG."""
    import networkx as nx

    from realhf_tpu.api.dfg import ParamReallocHook, build_graph

    def finding(code, message, extra_line=0):
        return Finding(
            checker="dfg-invariants", code=code, path=path,
            line=extra_line or line, col=0, message=message,
            symbol=name)

    findings: List[Finding] = []
    try:
        G = build_graph(spec.mfcs)
    except ValueError as e:
        code = ("dfg-cycle" if "cycle" in str(e)
                else "dfg-duplicate-key" if "produced by both" in str(e)
                else "dfg-build-failed")
        return [finding(code, f"graph resolution failed: {e}")]

    # --- per-MFC n_seqs vs the per-sample buffer contract ---------------
    # (system/buffer.py): any positive n_seqs combination flows --
    # assemblies span dataset batches -- but an MFC whose n_seqs
    # exceeds the buffer window (capacity * source n_seqs samples)
    # can never assemble a full batch.
    sources = [n for n in spec.mfcs
               if not any(k in G.graph["data_producers"]
                          for k in n.input_keys)]
    src_n = min((n.n_seqs for n in sources), default=0)
    window = max(1, getattr(spec, "max_concurrent_batches", 1)) * src_n
    for node in spec.mfcs:
        if node.n_seqs <= 0:
            findings.append(finding(
                "dfg-batch-mismatch",
                f"MFC `{node.name}`: n_seqs={node.n_seqs} must be "
                "positive"))
        elif window > 0 and node.n_seqs > window:
            findings.append(finding(
                "dfg-batch-mismatch",
                f"MFC `{node.name}`: n_seqs={node.n_seqs} exceeds the "
                f"buffer window of {window} samples "
                f"(max_concurrent_batches="
                f"{getattr(spec, 'max_concurrent_batches', 1)} x "
                f"source n_seqs={src_n}) -- it can never assemble a "
                "full batch"))

    # --- multi-turn (agentic) MFCs vs the buffer window -----------------
    # An env-in-the-loop generate MFC (interface declares max_turns>1,
    # realhf_tpu/agentic/) emits exactly ONE buffer sample per episode,
    # so it must be the graph's sample entry point (a source: episodes
    # cannot be re-generated from upstream MFC outputs) and ITS n_seqs
    # -- not the min over all sources -- bounds the ready-pool window
    # every consumer draws from.
    for node in spec.mfcs:
        iargs = getattr(node.interface_impl, "args", None) or {}
        if int(iargs.get("max_turns") or 1) <= 1:
            continue
        if str(getattr(node.interface_type, "value",
                       node.interface_type)) != "generate":
            continue
        if any(k in G.graph["data_producers"] for k in node.input_keys):
            producers = sorted({
                G.graph["data_producers"][k].name
                for k in node.input_keys
                if k in G.graph["data_producers"]})
            findings.append(finding(
                "dfg-multiturn-batch",
                f"multi-turn MFC `{node.name}` consumes keys produced "
                f"by {producers} -- episodes must enter the per-sample "
                "buffer as a SOURCE (dataset-fed) MFC"))
            continue
        mt_window = max(1, getattr(spec, "max_concurrent_batches", 1)) \
            * node.n_seqs
        for other in spec.mfcs:
            if other.n_seqs > mt_window:
                findings.append(finding(
                    "dfg-multiturn-batch",
                    f"MFC `{other.name}`: n_seqs={other.n_seqs} "
                    f"exceeds the multi-turn episode window of "
                    f"{mt_window} samples (max_concurrent_batches="
                    f"{getattr(spec, 'max_concurrent_batches', 1)} x "
                    f"`{node.name}` n_seqs={node.n_seqs}) -- episodes "
                    "are produced one sample each, so it could never "
                    "assemble a full batch"))

    # --- allocations name real MFCs, normalize cleanly -----------------
    node_names = {n.name for n in spec.mfcs}
    for alloc_name in sorted(getattr(spec, "allocations", {}) or {}):
        if alloc_name not in node_names:
            findings.append(finding(
                "dfg-bad-alloc",
                f"allocation for unknown MFC `{alloc_name}`"))

    # --- same worker group => same world size --------------------------
    group_ws = {}
    for node in spec.mfcs:
        try:
            workers = tuple(spec.workers_of_node(node.name, node.role))
            alloc = spec.alloc_of(node.name)
        except ValueError as e:
            findings.append(finding(
                "dfg-bad-alloc",
                f"MFC `{node.name}`: bad worker group: {e}"))
            continue
        par = (alloc.parallel if alloc is not None
               else spec.models[node.role].parallel
               if node.role in spec.models else None)
        if par is None:
            findings.append(finding(
                "dfg-bad-alloc",
                f"MFC `{node.name}` references unknown model role "
                f"`{node.role}`"))
            continue
        ws = par.world_size
        prev = group_ws.get(workers)
        if prev is not None and prev[1] != ws:
            findings.append(finding(
                "dfg-mesh-mismatch",
                f"MFCs `{prev[0]}` (world={prev[1]}) and "
                f"`{node.name}` (world={ws}) share worker group "
                f"{list(workers)} but need different device counts"))
        else:
            group_ws.setdefault(workers, (node.name, ws))

    # --- weight-realloc total order per role ---------------------------
    for role in sorted({n.role for n in spec.mfcs}):
        nodes = [n for n in spec.mfcs if n.role == role]
        primary_par = (spec.models[role].parallel
                       if role in spec.models else None)
        realloc_nodes = []
        for n in nodes:
            alloc = spec.alloc_of(n.name)
            hooked = any(
                isinstance(h, ParamReallocHook)
                for h in (list(n._pre_hooks) + list(n._post_hooks)))
            distinct_layout = (
                alloc is not None and primary_par is not None
                and not _same_layout(alloc.parallel, primary_par))
            if hooked or distinct_layout:
                realloc_nodes.append(n)
        for i, a in enumerate(realloc_nodes):
            for b in realloc_nodes[i + 1:]:
                if (nx.has_path(G, a.name, b.name)
                        or nx.has_path(G, b.name, a.name)):
                    continue
                findings.append(finding(
                    "dfg-realloc-order",
                    f"role `{role}`: MFCs `{a.name}` and `{b.name}` "
                    "both trigger weight reallocation but are "
                    "concurrent in the DAG -- their reshards would "
                    "race; order them with a data dependency"))
    return findings


def _same_layout(a, b) -> bool:
    same = getattr(a, "same_layout", None)
    if callable(same):
        return a.same_layout(b)
    return a == b


class DfgInvariantsChecker(ProjectChecker):
    name = "dfg-invariants"

    def check_project(self, root: str) -> List[Finding]:
        try:
            from realhf_tpu.experiments import ALL_EXPERIMENT_CLASSES
        except Exception as e:  # noqa: BLE001 - import failure is a
            # finding, not a crash: the gate must report it
            return [Finding(
                checker=self.name, code="dfg-build-failed",
                path="realhf_tpu/experiments", line=0, col=0,
                message=f"experiment registry import failed: {e!r}",
                symbol="")]
        findings: List[Finding] = []
        for name in sorted(ALL_EXPERIMENT_CLASSES):
            cls = ALL_EXPERIMENT_CLASSES[name]
            path, line = _spec_location(cls, root)
            try:
                spec = build_default_spec(cls)
            except Exception as e:  # noqa: BLE001 - any build error
                # is exactly what this pass exists to surface
                findings.append(Finding(
                    checker=self.name, code="dfg-build-failed",
                    path=path, line=line, col=0,
                    message=(f"experiment `{name}` failed to build "
                             f"under defaults: {e!r}"),
                    symbol=name))
                continue
            if spec is None:
                continue  # no DFG (pure serving experiments)
            findings.extend(validate_spec(name, spec, path, line))
        return findings
