"""Per-function control-flow graphs with exceptional edges.

The substrate of graft-lint v2's path-sensitive checkers
(docs/static_analysis.md "The CFG/call-graph engine"). ``build_cfg``
turns one ``ast.FunctionDef`` into a :class:`CFG` of per-statement
nodes with two edge kinds:

- ``normal``: ordinary fall-through / branch / loop edges;
- ``exc``: an exception escaping the statement. Only statements that
  contain a call, a ``raise``, or an ``assert`` get one (attribute
  and subscript errors exist but modelling them drowns every checker
  in noise), and the edge carries the PRE-state of the statement --
  whatever the statement would have done is considered not to have
  happened.

Exits are explicit nodes: ``normal_exit`` (return / fall off the
end) and ``raise_exit`` (an exception leaving the function). What the
builder models precisely:

- ``try``/``except``/``else``/``finally``: body statements edge to a
  handler-dispatch node; an unmatched exception continues through the
  ``finally`` body (duplicated for the exceptional path) to the outer
  exception target; ``return``/``break``/``continue`` jumping out of
  a ``try`` run every enclosing ``finally`` body first (duplicated
  per jump site, like the bytecode compiler does).
- loops: header -> body -> header back-edge, ``break``/``continue``,
  and no fall-through exit edge for a literal ``while True`` without
  a break.
- ``with``: the header (context-manager construction) may raise; the
  body shares the surrounding exception target. ``__exit__`` is not
  modelled -- checkers treat ``with``-managed resources as safe by
  construction.

Nested ``def``/``class``/``lambda`` bodies are opaque single
statements: their code runs at some other time, on some other path.
"""

import ast
import dataclasses
from typing import Iterable, List, Optional, Tuple

#: edge kinds. TRUE/FALSE mark the two arms of an ``if``/loop header
#: so flow-sensitive checkers can refine state per branch (e.g. the
#: lifecycle family's ``if sock is not None: sock.close()`` guard);
#: checkers that don't care treat them like NORMAL.
NORMAL = "normal"
TRUE = "true"
FALSE = "false"
EXC = "exc"


@dataclasses.dataclass
class Node:
    """One CFG node: a statement, or a virtual entry/exit/dispatch."""
    idx: int
    stmt: Optional[ast.stmt]
    label: str = ""
    succs: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list)

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


class CFG:
    """Control-flow graph of one function."""

    def __init__(self, func):
        self.func = func
        self.nodes: List[Node] = []
        self.entry: int = self._new(None, "entry")
        self.normal_exit: int = self._new(None, "normal_exit")
        self.raise_exit: int = self._new(None, "raise_exit")

    def _new(self, stmt, label="") -> int:
        n = Node(idx=len(self.nodes), stmt=stmt, label=label)
        self.nodes.append(n)
        return n.idx

    def _edge(self, frm: int, to: int, kind: str):
        pair = (to, kind)
        if pair not in self.nodes[frm].succs:
            self.nodes[frm].succs.append(pair)

    def preds(self):
        """node idx -> list of (pred idx, kind)."""
        out = {n.idx: [] for n in self.nodes}
        for n in self.nodes:
            for to, kind in n.succs:
                out[to].append((n.idx, kind))
        return out


def _walk_no_nested(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class/
    lambda bodies (their statements run elsewhere)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def exec_parts(stmt: ast.stmt):
    """The sub-ASTs that execute AT a statement's own CFG node: the
    header expression for compound statements (their bodies are
    separate nodes), decorators/defaults for a nested ``def``, the
    whole statement otherwise."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # the def statement itself only evaluates decorators and
        # argument defaults; the body runs elsewhere
        return list(stmt.decorator_list) + [
            d for d in (stmt.args.defaults + stmt.args.kw_defaults)
            if d is not None]
    return [stmt]


def may_raise(stmt: ast.stmt) -> bool:
    """Whether the statement gets an exceptional edge."""
    for root in exec_parts(stmt):
        for node in _walk_no_nested(root):
            if isinstance(node, (ast.Call, ast.Raise, ast.Assert)):
                return True
    return False


# ----------------------------------------------------------------------
# frames: the lexical stack a jump (return/break/continue) unwinds
# ----------------------------------------------------------------------
@dataclasses.dataclass
class _FinallyFrame:
    body: list            # the finally suite (re-built per jump site)
    ctx: "_Ctx"           # context the finally body itself runs under


@dataclasses.dataclass
class _LoopFrame:
    brk: list             # collected (node, kind) preds of `break`
    cont: int             # loop header idx for `continue`


@dataclasses.dataclass
class _Ctx:
    exc: int              # where an escaping exception goes
    frames: tuple = ()    # innermost LAST


class _Builder:
    def __init__(self, func):
        self.cfg = CFG(func)

    def build(self) -> CFG:
        ctx = _Ctx(exc=self.cfg.raise_exit)
        ends = self._seq(self.cfg.func.body,
                         [(self.cfg.entry, NORMAL)], ctx)
        self._connect(ends, self.cfg.normal_exit)
        return self.cfg

    # ------------------------------------------------------------------
    def _connect(self, preds, to: int):
        for frm, kind in preds:
            self.cfg._edge(frm, to, kind)

    def _stmt_node(self, s: ast.stmt, preds, ctx: _Ctx) -> int:
        n = self.cfg._new(s)
        self._connect(preds, n)
        if may_raise(s):
            self.cfg._edge(n, ctx.exc, EXC)
        return n

    def _seq(self, stmts, preds, ctx: _Ctx):
        for s in stmts:
            if not preds:
                break  # unreachable code after return/raise/...
            preds = self._stmt(s, preds, ctx)
        return preds

    # ------------------------------------------------------------------
    def _unwind_finallies(self, preds, ctx: _Ctx,
                          stop_at: Optional[_LoopFrame] = None):
        """Run every enclosing ``finally`` body (innermost first) a
        jump crosses; ``stop_at`` bounds the unwind at a loop frame
        (break/continue stay inside their loop's outer finallies)."""
        for frame in reversed(ctx.frames):
            if frame is stop_at:
                return preds, frame
            if isinstance(frame, _LoopFrame):
                continue
            entry = self.cfg._new(None, "finally")
            self._connect(preds, entry)
            preds = self._seq(frame.body, [(entry, NORMAL)], frame.ctx)
        return preds, None

    def _innermost_loop(self, ctx: _Ctx) -> Optional[_LoopFrame]:
        for frame in reversed(ctx.frames):
            if isinstance(frame, _LoopFrame):
                return frame
        return None

    # ------------------------------------------------------------------
    def _stmt(self, s: ast.stmt, preds, ctx: _Ctx):
        if isinstance(s, ast.Return):
            n = self._stmt_node(s, preds, ctx)
            out, _ = self._unwind_finallies([(n, NORMAL)], ctx)
            self._connect(out, self.cfg.normal_exit)
            return []
        if isinstance(s, ast.Raise):
            n = self.cfg._new(s)
            self._connect(preds, n)
            self.cfg._edge(n, ctx.exc, EXC)
            return []
        if isinstance(s, ast.Break):
            n = self._stmt_node(s, preds, ctx)
            loop = self._innermost_loop(ctx)
            out, frame = self._unwind_finallies([(n, NORMAL)], ctx,
                                                stop_at=loop)
            if frame is not None:
                frame.brk.extend(out)
            return []
        if isinstance(s, ast.Continue):
            n = self._stmt_node(s, preds, ctx)
            loop = self._innermost_loop(ctx)
            out, frame = self._unwind_finallies([(n, NORMAL)], ctx,
                                                stop_at=loop)
            if frame is not None:
                self._connect(out, frame.cont)
            return []
        if isinstance(s, ast.If):
            hdr = self._stmt_node(s, preds, ctx)
            body_ends = self._seq(s.body, [(hdr, TRUE)], ctx)
            else_ends = self._seq(s.orelse, [(hdr, FALSE)], ctx) \
                if s.orelse else [(hdr, FALSE)]
            return body_ends + else_ends
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(s, preds, ctx)
        if isinstance(s, ast.Try):
            return self._try(s, preds, ctx)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            hdr = self._stmt_node(s, preds, ctx)
            return self._seq(s.body, [(hdr, NORMAL)], ctx)
        if isinstance(s, ast.Match):
            hdr = self._stmt_node(s, preds, ctx)
            ends = [(hdr, NORMAL)]  # no case may match
            for case in s.cases:
                ends += self._seq(case.body, [(hdr, NORMAL)], ctx)
            return ends
        # simple statements (incl. nested def/class: opaque)
        n = self._stmt_node(s, preds, ctx)
        return [(n, NORMAL)]

    def _loop(self, s, preds, ctx: _Ctx):
        hdr = self._stmt_node(s, preds, ctx)
        frame = _LoopFrame(brk=[], cont=hdr)
        body_ctx = _Ctx(exc=ctx.exc, frames=ctx.frames + (frame,))
        body_ends = self._seq(s.body, [(hdr, TRUE)], body_ctx)
        self._connect(body_ends, hdr)
        ends = list(frame.brk)
        infinite = (isinstance(s, ast.While)
                    and isinstance(s.test, ast.Constant)
                    and s.test.value is True)
        if not infinite:
            ends.append((hdr, FALSE))
        if s.orelse:
            ends = self._seq(s.orelse, ends, ctx)
        return ends

    def _try(self, s: ast.Try, preds, ctx: _Ctx):
        outer_frames = ctx.frames
        if s.finalbody:
            fin_ctx = _Ctx(exc=ctx.exc, frames=outer_frames)
            # exceptional copy of the finally body: runs, then the
            # exception continues to the outer target
            fin_exc_entry = self.cfg._new(None, "finally")
            fin_exc_ends = self._seq(s.finalbody,
                                     [(fin_exc_entry, NORMAL)], fin_ctx)
            for frm, kind in fin_exc_ends:
                self.cfg._edge(frm, ctx.exc, EXC)
            exc_after_handlers = fin_exc_entry
            frames = outer_frames + (
                _FinallyFrame(body=s.finalbody, ctx=fin_ctx),)
        else:
            exc_after_handlers = ctx.exc
            frames = outer_frames

        if s.handlers:
            dispatch = self.cfg._new(None, "except-dispatch")
            body_exc = dispatch
        else:
            dispatch = None
            body_exc = exc_after_handlers

        body_ctx = _Ctx(exc=body_exc, frames=frames)
        body_ends = self._seq(s.body, preds, body_ctx)
        if s.orelse:
            # else runs only on normal body completion; its exceptions
            # skip the handlers
            else_ctx = _Ctx(exc=exc_after_handlers, frames=frames)
            body_ends = self._seq(s.orelse, body_ends, else_ctx)

        ends = list(body_ends)
        if dispatch is not None:
            # statements that may raise inside the body edge here; the
            # dispatch itself may fail to match any handler -- unless
            # a handler is catch-all (bare / Exception / BaseException;
            # Exception counts pragmatically: flagging every cleanup
            # handler for the KeyboardInterrupt window is pure noise)
            if not any(_catches_all(h) for h in s.handlers):
                self.cfg._edge(dispatch, exc_after_handlers, EXC)
            h_ctx = _Ctx(exc=exc_after_handlers, frames=frames)
            for handler in s.handlers:
                ends += self._seq(handler.body, [(dispatch, EXC)],
                                  h_ctx)
        if s.finalbody:
            fin_ctx = _Ctx(exc=ctx.exc, frames=outer_frames)
            entry = self.cfg._new(None, "finally")
            self._connect(ends, entry)
            ends = self._seq(s.finalbody, [(entry, NORMAL)], fin_ctx)
        return ends


def _catches_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        parts = []
        while isinstance(n, ast.Attribute):
            parts.append(n.attr)
            n = n.value
        if isinstance(n, ast.Name):
            parts.append(n.id)
        if parts and parts[0] in ("BaseException", "Exception"):
            return True
    return False


def build_cfg(func) -> CFG:
    """CFG for one ``ast.FunctionDef`` / ``ast.AsyncFunctionDef``."""
    return _Builder(func).build()


def iter_functions(tree: ast.AST):
    """Yield every (qualname, FunctionDef) in the module, including
    methods; nested defs are yielded as their own units too."""
    def visit(node, qual):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                cq = f"{qual}.{child.name}" if qual else child.name
                yield cq, child
                yield from visit(child, cq)
            elif isinstance(child, ast.ClassDef):
                cq = f"{qual}.{child.name}" if qual else child.name
                yield from visit(child, cq)
            else:
                yield from visit(child, qual)
    yield from visit(tree, "")
