"""Packing variable-length sequences into static-shaped streams.

The bridge between the host data plane (SequenceSample: ragged packed
1D arrays) and XLA's static shapes: sequences are binned into
``n_streams`` token-balanced streams (first-fit decreasing, the same
balancing contract as reference ``datapack.min_abs_diff_partition``),
each stream is one row of a [S, L] matrix with segment ids, and L is
rounded up to a bucket multiple so recompilation is bounded.

The reference needs no such step because flash-attn consumes ragged
cu_seqlens directly (``docs/source/arch.rst`` "Data Packing"); on TPU
the segment-id matrix is the idiomatic equivalent (same zero-padding
waste bound: at most one bucket per stream).
"""

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

DEFAULT_BUCKET = 128


@dataclasses.dataclass
class PackInfo:
    """Where each sequence landed: parallel lists over sequences."""
    stream: List[int]
    offset: List[int]
    length: List[int]
    n_streams: int
    max_len: int

    @property
    def n_seqs(self) -> int:
        return len(self.stream)


def plan_packing(seqlens: Sequence[int], n_streams: int,
                 bucket: int = DEFAULT_BUCKET,
                 min_len: Optional[int] = None) -> PackInfo:
    """Assign sequences to streams, longest-first onto the emptiest
    stream (balanced token counts)."""
    seqlens = np.asarray(seqlens)
    if len(seqlens) == 0:
        raise ValueError("Cannot pack an empty sequence list.")
    # Fewer sequences than streams is fine: surplus streams stay
    # all-padding (seg_ids 0) and are masked out everywhere.
    stream_tokens = np.zeros(n_streams, np.int64)
    stream_of = np.zeros(len(seqlens), np.int32)
    offset_of = np.zeros(len(seqlens), np.int32)
    for i in np.argsort(seqlens)[::-1]:
        s = int(stream_tokens.argmin())
        stream_of[i] = s
        offset_of[i] = stream_tokens[s]
        stream_tokens[s] += seqlens[i]
    max_len = int(stream_tokens.max())
    max_len = ((max_len + bucket - 1) // bucket) * bucket
    if min_len is not None:
        max_len = max(max_len, min_len)
    return PackInfo(stream=stream_of.tolist(), offset=offset_of.tolist(),
                    length=[int(x) for x in seqlens], n_streams=n_streams,
                    max_len=max_len)


def pack_tokens(info: PackInfo, flat: np.ndarray,
                seqlens: Optional[Sequence[int]] = None,
                fill=0) -> np.ndarray:
    """Scatter a 1D packed per-token array (concatenated in sequence
    order) into the [S, L] stream layout. ``seqlens`` defaults to
    info.length; pass shorter ones for keys like logprobs (l-1)."""
    lens = list(seqlens) if seqlens is not None else info.length
    assert len(lens) == info.n_seqs
    out_shape = (info.n_streams, info.max_len) + flat.shape[1:]
    out = np.full(out_shape, fill, dtype=flat.dtype)
    src = 0
    for i, ln in enumerate(lens):
        s, off = info.stream[i], info.offset[i]
        out[s, off:off + ln] = flat[src:src + ln]
        src += ln
    assert src == len(flat), (src, len(flat))
    return out


def segment_ids(info: PackInfo) -> np.ndarray:
    """[S, L] int32 segment matrix: sequence i gets id i+1; pads 0."""
    out = np.zeros((info.n_streams, info.max_len), np.int32)
    for i, ln in enumerate(info.length):
        s, off = info.stream[i], info.offset[i]
        out[s, off:off + ln] = i + 1
    return out


def unpack_tokens(info: PackInfo, arr: np.ndarray,
                  seqlens: Optional[Sequence[int]] = None) -> np.ndarray:
    """Gather [S, L, ...] back into the flat packed 1D layout."""
    lens = list(seqlens) if seqlens is not None else info.length
    parts = []
    for i, ln in enumerate(lens):
        s, off = info.stream[i], info.offset[i]
        parts.append(arr[s, off:off + ln])
    return np.concatenate(parts, axis=0)


def per_seq_gather(info: PackInfo, arr: np.ndarray,
                   index_in_seq: Sequence[int]) -> np.ndarray:
    """Gather one element per sequence (e.g. the last token's value)."""
    out = []
    for i, idx in enumerate(index_in_seq):
        s, off = info.stream[i], info.offset[i]
        out.append(arr[s, off + idx])
    return np.stack(out, axis=0)


def left_padded_prompts(prompts: List[np.ndarray], pad_id: int,
                        bucket: int = DEFAULT_BUCKET
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build the generation prefill batch: [B, Lp] left-padded token
    matrix + segment ids (1 over content) + positions. Left padding
    keeps every stream's last prompt token at column Lp-1 so decode
    appends uniformly (reference pads KV likewise,
    real_llm_generate.py:179)."""
    b = len(prompts)
    lp = max(len(p) for p in prompts)
    lp = ((lp + bucket - 1) // bucket) * bucket
    ids = np.full((b, lp), pad_id, np.int32)
    seg = np.zeros((b, lp), np.int32)
    pos = np.zeros((b, lp), np.int32)
    for i, p in enumerate(prompts):
        ids[i, lp - len(p):] = p
        seg[i, lp - len(p):] = 1
        pos[i, lp - len(p):] = np.arange(len(p))
    return ids, seg, pos
