"""The per-model execution engine: train / inference / generate.

TPU-native replacement for the reference's `PipelinableEngine` ABC
(``realhf/api/core/model_api.py:305-463``) and its implementations
(``backend/inference.py:21``, ``backend/megatron.py:702``,
``backend/pipe_runner.py:779``): one class wraps a sharded parameter
pytree on the model's mesh and exposes

  - ``train_batch(microbatches, loss_fn)``: jitted value_and_grad with
    gradient accumulation over a scanned microbatch stack, global-norm
    clipping, optax update (AdamW + schedule). Grad accumulation over
    a scan replaces Megatron's DDP no_sync loop (megatron.py:726-797);
    mixed precision is bf16 compute over fp32 master params, so the
    loss-scaler machinery disappears.
  - ``forward(fn_name, ...)``: jitted inference helpers (logprobs,
    values, scores, hidden).
  - ``generate(...)``: the jitted KV-cache decode loop.

All methods consume/produce device arrays in [S, L] stream layout;
the algorithm interfaces do SequenceSample <-> stream packing.
"""

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from realhf_tpu.base import logging
from realhf_tpu.base.backend import pallas_enabled as _pallas_enabled
from realhf_tpu.engine import generation as gen_mod
from realhf_tpu.engine.optim import OptimizerConfig, make_optimizer
from realhf_tpu.models import sharding as shard_rules
from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.ops import functional as F
from realhf_tpu.ops.decode_attention import (
    mesh_nontrivial as _mesh_nontrivial,
)
from realhf_tpu.ops.sampling import GenerationHyperparameters
from realhf_tpu.parallel.mesh import MeshContext

logger = logging.getLogger("engine")

LossFn = Callable[[Any, Dict[str, jnp.ndarray]],
                  Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]


class Engine:

    def __init__(self,
                 cfg: TransformerConfig,
                 ctx: MeshContext,
                 params: Any,
                 optimizer: Optional[OptimizerConfig] = None,
                 total_train_steps: Optional[int] = None):
        self.cfg = cfg
        self.ctx = ctx
        self.mesh = ctx.mesh
        self.version = 0
        # Multi-controller operation: when the mesh spans >1 OS process
        # (one jax.distributed world across hosts, reference NCCL world
        # global_comm.py:44), every member process runs the SAME engine
        # calls. Host inputs must then be global arrays (replicated;
        # each process already holds the full batch) and array outputs
        # are jitted back to replicated so every member can read them.
        self._mesh_procs = sorted(
            {d.process_index for d in self.mesh.devices.flat})
        self._multiproc = len(self._mesh_procs) > 1
        # (read `engine.multiproc` from outside; collective-count
        # decisions in the runtime key on it)
        if self._multiproc:
            import jax as _jax
            mine = _jax.process_index()
            if mine not in self._mesh_procs:
                raise ValueError(
                    f"Engine mesh spans processes {self._mesh_procs} "
                    f"but this engine was built on process {mine}; "
                    "only group members may host the model.")

        # Pipeline parallelism: blocks layer-sharded over "pipe".
        # Training runs the schedule ParallelismConfig.pipeline_schedule
        # picks -- 1F1B by default (parallel/schedule.py: explicit
        # instruction streams, custom-VJP backward, bounded residuals)
        # with GPipe (parallel/pipeline.py) as the selectable fallback;
        # inference-only forwards always use the GPipe rotation (see
        # pipeline_ctx_infer -- there is no backward to schedule and
        # the rotation scan saves nothing).
        if ctx.pp_size > 1:
            from realhf_tpu.parallel.pipeline import PipelineContext
            from realhf_tpu.parallel.schedule import default_microbatches
            if cfg.n_layers % ctx.pp_size != 0:
                raise ValueError(
                    f"n_layers={cfg.n_layers} not divisible by "
                    f"pipeline_parallel_size={ctx.pp_size}")
            if ctx.parallel.context_parallel_size > 1:
                raise NotImplementedError(
                    "pipeline parallelism cannot be combined with "
                    "context parallelism (ring attention) yet; use "
                    "pp x tp x dp or cp x tp x dp.")
            sched = getattr(ctx.parallel, "pipeline_schedule", "") \
                or "1f1b"
            n_mb = ctx.parallel.pipeline_microbatches \
                or default_microbatches(ctx.pp_size, sched)
            self.pipeline_ctx = PipelineContext(
                mesh=self.mesh, n_stages=ctx.pp_size,
                n_microbatches=n_mb, schedule=sched)
        else:
            self.pipeline_ctx = None

        # Expert parallelism: expert weights E-sharded over the data
        # axis; this constraint turns dispatch/combine into all-to-alls
        # (models/sharding.py moe_ep_constraint). Validated BEFORE the
        # device_put below so invalid configs fail instantly with a
        # clear message instead of after a full-model transfer.
        self.moe_constraint = shard_rules.moe_ep_constraint(cfg, self.mesh)
        if self.moe_constraint is not None:
            from realhf_tpu.ops.moe import ragged_dispatch_enabled as _rde
            if _rde(cfg):
                raise ValueError(
                    "MoEConfig.expert_parallel requires the capacity "
                    "or dense dispatch mode (set capacity_factor, or "
                    "use_grouped_gemm=False); ragged grouped GEMMs "
                    "cannot shard the expert group dim.")
            if cfg.moe.num_experts % ctx.dp_size != 0:
                raise ValueError(
                    f"expert_parallel needs num_experts "
                    f"({cfg.moe.num_experts}) divisible by "
                    f"data_parallel_size ({ctx.dp_size}).")

        self._param_shardings = shard_rules.param_shardings(cfg, self.mesh)
        # Megatron-style vocab padding so wte/head shard over tp even
        # when vocab_size is not a tp multiple (re-padded if the source
        # carried another tp's padding).
        params = shard_rules.normalize_vocab_padding(cfg, params,
                                                     ctx.tp_size)
        params = self._cast_param_dtype(params)
        self.params = jax.device_put(params, self._param_shardings)
        self._constrain = shard_rules.activation_constraint(
            self.mesh, ctx.parallel.sequence_parallel)
        # Context parallelism: attention becomes a ring over the "ctx"
        # mesh axis; the rest of the model shards L via GSPMD.
        self.attention_fn_inference = None
        if ctx.parallel.context_parallel_size > 1:
            from realhf_tpu.ops.ring_attention import ring_attention
            mesh = self.mesh

            def _ring(q, k, v, seg, causal=True, scale=None,
                      sliding_window=None):
                return ring_attention(q, k, v, seg, mesh, "ctx",
                                      causal=causal, scale=scale,
                                      sliding_window=sliding_window)

            self.attention_fn = _ring
            # REALHF_TPU_FUSED_RING=1: single-Pallas-kernel ring with
            # the KV RDMA overlapped against flash compute
            # (ops/ring_attention_fused.py) -- INFERENCE jits only:
            # training keeps the shard_map formulation because a
            # side-effecting kernel cannot live inside the
            # jax.checkpoint regions gradient_checkpointing wraps
            # around every block. Off by default until validated on
            # multi-chip hardware; on CPU it runs the interpret-mode
            # emulation (CI wiring coverage).
            if os.environ.get("REALHF_TPU_FUSED_RING") == "1":
                from realhf_tpu.ops.ring_attention_fused import (
                    FUSED_RING_SUPPORTED,
                    FUSED_RING_UNSUPPORTED_REASON,
                    ring_attention_fused,
                )
                if not FUSED_RING_SUPPORTED:
                    raise RuntimeError(
                        "REALHF_TPU_FUSED_RING=1 requested but "
                        f"unavailable: {FUSED_RING_UNSUPPORTED_REASON}")
                interp = jax.default_backend() != "tpu"

                def _ring_fused(q, k, v, seg, causal=True, scale=None,
                                sliding_window=None):
                    return ring_attention_fused(
                        q, k, v, seg, mesh, "ctx", causal=causal,
                        scale=scale, sliding_window=sliding_window,
                        interpret=interp)

                self.attention_fn_inference = _ring_fused
        elif _pallas_enabled() and _mesh_nontrivial(self.mesh):
            if ctx.pp_size > 1:
                # Inside the pipe-manual shard_map a bare pallas_call
                # would force per-stage gathers; use the XLA path,
                # which GSPMD partitions natively.
                from realhf_tpu.ops.attention import packed_attention_xla

                def _xla_attn(q, k, v, seg, causal=True, scale=None,
                              sliding_window=None):
                    return packed_attention_xla(
                        q, k, v, seg, causal=causal, scale=scale,
                        sliding_window=sliding_window)

                self.attention_fn = _xla_attn
            else:
                # Partition the Pallas flash kernel over dp x tp: a
                # bare pallas_call has no GSPMD rule and would gather
                # full Q/K/V per device
                # (ops/attention.make_sharded_attention).
                from realhf_tpu.ops.attention import (
                    make_sharded_attention,
                )
                self.attention_fn = make_sharded_attention(self.mesh)
        else:
            self.attention_fn = None

        from realhf_tpu.ops.moe import ragged_dispatch_enabled
        if (cfg.mlp_type == "moe" and cfg.moe is not None
                and cfg.moe.capacity_factor is None
                and cfg.moe.num_experts > 4
                and not ragged_dispatch_enabled(cfg)):
            logger.warning(
                "MoE model running in dense dispatch (capacity_factor "
                "unset, grouped GEMM disabled): every expert processes "
                "every token -- %dx the FLOPs of top-%d routing. Set "
                "MoEConfig.use_grouped_gemm=True (ragged_dot) or "
                "capacity_factor (e.g. 1.25).",
                cfg.moe.num_experts // cfg.moe.top_k, cfg.moe.top_k)

        self.optimizer_config = optimizer
        if (optimizer is not None and optimizer.offload
                and self._multiproc):
            raise ValueError(
                "OptimizerConfig.offload moves the state to this "
                "process's CPU device and cannot be used on a mesh "
                "spanning multiple processes (shards on other hosts "
                "are not addressable here); disable offload or use a "
                "single-process group for this role.")
        if optimizer is not None and optimizer.type != "empty":
            # Mixed precision: non-fp32 params train against an fp32
            # master copy held INSIDE the optimizer state (reference
            # Megatron bf16 + fp32 master, megatron.py:823-940).
            master = jnp.dtype(cfg.param_dtype) != jnp.dtype(jnp.float32)
            self._tx = make_optimizer(optimizer, total_train_steps,
                                      master_weights=master)
            # ZeRO-1: Adam moments (and the fp32 master copy) shard
            # over the DATA axis on top of the params' tp/pp specs
            # (reference Megatron DistributedOptimizer,
            # backend/megatron.py:823-940; DeepSpeed ZeRO-1,
            # deepspeed.py:445). GSPMD inserts the reduce-scatter /
            # all-gather pair around the update.
            zero1 = getattr(optimizer, "zero1", True)
            state_shape = jax.eval_shape(self._tx.init, self.params)
            self._opt_shardings = shard_rules.opt_state_shardings(
                state_shape, cfg, self.mesh, zero1=zero1)
            self.opt_state = jax.jit(
                self._tx.init,
                out_shardings=self._opt_shardings)(self.params)
            # ZeRO-2-flavored grad accumulation: the fp32 grad
            # accumulator shards over DP too, turning the DP grad
            # all-reduce into a reduce-scatter (Megatron
            # DistributedOptimizer grad-buffer layout).
            if zero1:
                self._grad_shardings = jax.tree.map(
                    lambda sh, p: jax.sharding.NamedSharding(
                        self.mesh, shard_rules.zero1_moment_spec(
                            sh.spec, p.shape,
                            self.mesh.shape.get("data", 1))),
                    self._param_shardings, self.params)
            else:
                self._grad_shardings = None
        else:
            self._tx = None
            self.opt_state = None
            self._opt_shardings = None
            self._grad_shardings = None

        self._train_step_cache: Dict[Any, Callable] = {}
        self._generate_cache: Dict[Any, Callable] = {}
        # Generation view on pp/ctx meshes (decode_engine): a second
        # inference-only Engine on a collapsed dp x tp mesh over the
        # SAME devices; weights reshard into it when they change.
        self._decode_view: Optional["Engine"] = None
        self._decode_view_src: Any = None
        self._jit_forward_hidden = None
        self._gather_jit = None
        self._jit_logprobs = None
        self._jit_values = None

    # ------------------------------------------------------------------
    # Multi-process (worker-group) helpers
    # ------------------------------------------------------------------
    @property
    def multiproc(self) -> bool:
        """True when this engine's mesh spans >1 OS process; gathers /
        saves are then collectives every member must join."""
        return self._multiproc

    @property
    def _replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def _globalize(self, arr):
        """Host array -> device array usable by this engine's jits.

        Single-process: plain jnp.asarray (jit reshards under GSPMD).
        Multi-process mesh: build a REPLICATED global jax.Array from
        the process-local copy (every member fetched the same batch
        from the data plane), since jit on a cross-process mesh only
        accepts global arrays.
        """
        if not self._multiproc:
            return jnp.asarray(arr)
        a = np.asarray(arr)
        return jax.make_array_from_callback(
            a.shape, self._replicated_sharding, lambda idx: a[idx])

    def _globalize_tree(self, tree):
        """Host pytree -> device, ONE bundled transfer where possible.
        Per-leaf ``jnp.asarray`` costs a dispatch round-trip per leaf;
        on a relayed platform that fixed latency (~0.1s/call) dominates
        small uploads, so batch the whole tree into one device_put."""
        if not self._multiproc:
            return jax.device_put(tree)
        return jax.tree.map(self._globalize, tree)

    def _out_replicated(self):
        """out_shardings making jit outputs replicated (hence fully
        addressable on every member process); None single-process to
        let XLA choose."""
        return self._replicated_sharding if self._multiproc else None

    @property
    def _infer_attention_fn(self):
        """Attention for the inference-only jits (forward_hidden /
        forward_logprobs / forward_values): the fused-RDMA ring when
        enabled, else the same train-safe fn the loss closures
        capture. Generation never sees it -- on a ctx mesh it runs on
        the collapsed dp x tp decode view, where no ring exists."""
        return self.attention_fn_inference or self.attention_fn

    @property
    def pipeline_ctx_infer(self):
        """Pipeline context for inference-only forwards: always the
        GPipe rotation -- with no backward to schedule, the 1F1B
        machinery (input saving, custom VJP) is pure overhead."""
        if self.pipeline_ctx is None \
                or self.pipeline_ctx.schedule == "gpipe":
            return self.pipeline_ctx
        import dataclasses as _dc
        return _dc.replace(self.pipeline_ctx, schedule="gpipe")

    @property
    def n_streams(self) -> int:
        """Preferred [S, L] stream-batch rows: one per dp rank, times
        the pipeline microbatch count when pp > 1 (each pipeline
        microbatch then carries dp streams)."""
        if self.pipeline_ctx is not None:
            return self.ctx.dp_size * self.pipeline_ctx.n_microbatches
        return self.ctx.dp_size

    # ------------------------------------------------------------------
    # Training
    # ------------------------------------------------------------------
    def _train_step_body(self, loss_fn: LossFn) -> Callable:
        """The un-jitted one-optimizer-step body shared by
        ``_build_train_step`` (one minibatch per dispatch) and
        ``_build_train_seq`` (a lax.scan over minibatches inside one
        dispatch)."""

        def step(params, opt_state, mbs: Dict[str, jnp.ndarray],
                 mb_weights: jnp.ndarray):
            """mbs: dict of stacked arrays with leading dim n_mbs;
            mb_weights: [n_mbs] relative weight (e.g. token counts) used
            to average gradients exactly as one large batch would."""
            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            zero = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if self._grad_shardings is not None:
                zero = jax.tree.map(jax.lax.with_sharding_constraint,
                                    zero, self._grad_shardings)

            def accum(carry, x):
                gsum = carry
                mb, w = x
                (loss, stats), grads = grad_fn(params, mb)
                gsum = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) * w, gsum, grads)
                if self._grad_shardings is not None:
                    gsum = jax.tree.map(jax.lax.with_sharding_constraint,
                                        gsum, self._grad_shardings)
                return gsum, (loss, stats)

            wsum = mb_weights.sum()
            gsum, (losses, stats) = jax.lax.scan(
                accum, zero, (mbs, mb_weights / wsum))
            updates, new_opt = self._tx.update(gsum, opt_state, params)
            if self._opt_shardings is not None:
                # keep the ZeRO-1 moment shardings stable across steps
                # (donated buffers must alias exactly)
                new_opt = jax.tree.map(
                    lambda s, sh: jax.lax.with_sharding_constraint(s, sh),
                    new_opt, self._opt_shardings)
            new_params = optax.apply_updates(params, updates)
            gnorm = optax.global_norm(gsum)
            mean_stats = jax.tree.map(
                lambda s: (s * mb_weights / wsum).sum(), stats)
            # Reserved stat "__skip_update__": when any microbatch sets
            # it > 0, the whole optimizer step is discarded -- params,
            # optimizer moments, and step count stay untouched (PPO
            # early stopping must SKIP the update, not step with a
            # zeroed loss: AdamW weight decay and MoE aux grads would
            # otherwise still apply).
            skip = mean_stats.pop("__skip_update__", None)
            if skip is not None:
                keep_old = skip > 0
                new_params = jax.tree.map(
                    lambda n, o: jnp.where(keep_old, o, n),
                    new_params, params)
                new_opt = jax.tree.map(
                    lambda n, o: jnp.where(keep_old, o, n),
                    new_opt, opt_state)
                mean_stats["early_stop_skipped"] = keep_old.astype(
                    jnp.float32)
            mean_loss = (losses * mb_weights / wsum).sum()
            return new_params, new_opt, mean_loss, mean_stats, gnorm

        return step

    def _train_out_shardings(self, extra_outs: int):
        """Pin the params/opt-state OUTPUTS of a train jit to their
        input shardings. Without this XLA picks output shardings
        freely, the second call sees donated inputs whose shardings no
        longer match the first compilation, and the step silently
        compiles twice (measured: a full second compile on step 2).
        The scalar/stat outputs stay compiler-chosen."""
        return (self._param_shardings, self._opt_shardings) + \
            (None,) * extra_outs

    def _build_train_step(self, loss_fn: LossFn) -> Callable:
        return jax.jit(self._train_step_body(loss_fn),
                       donate_argnums=(0, 1),
                       out_shardings=self._train_out_shardings(3))

    def _build_train_seq(self, loss_fn: LossFn) -> Callable:
        """N SEQUENTIAL optimizer steps (e.g. the PPO minibatch loop,
        reference ppo_interface.py train_step's minibatch iteration) in
        ONE compiled dispatch: an outer lax.scan threads params and
        optimizer state through the per-minibatch step body, so a
        remote-attached chip pays one dispatch+sync round-trip for the
        whole loop instead of one per minibatch. Semantics (update
        order, early-stop skip, gradient weighting) are identical to
        calling train_batch once per minibatch."""
        body = self._train_step_body(loss_fn)

        def seq(params, opt_state, all_mbs, all_weights):
            def outer(carry, x):
                p, o = carry
                mbs, w = x
                p, o, loss, stats, gnorm = body(p, o, mbs, w)
                return (p, o), (loss, stats, gnorm)

            (params, opt_state), (losses, stats, gnorms) = jax.lax.scan(
                outer, (params, opt_state), (all_mbs, all_weights))
            return params, opt_state, losses, stats, gnorms

        return jax.jit(seq, donate_argnums=(0, 1),
                       out_shardings=self._train_out_shardings(3))

    def train_batch(self, microbatches: List[Dict[str, np.ndarray]],
                    loss_fn: LossFn,
                    loss_weights: Optional[List[float]] = None,
                    loss_fn_key: Optional[str] = None) -> Dict[str, float]:
        """Run one optimizer step over the microbatches.

        All microbatches must share array shapes (the packer pads them
        to a common bucket); they are stacked and scanned on-device.

        ``loss_fn_key`` caches the compiled step: it MUST uniquely
        identify the loss closure INCLUDING every hyperparameter the
        closure captures (temperature, clip ranges, ...) -- use a tuple
        like ("ppo_actor", temp, eps_clip). Two closures sharing a key
        silently reuse the first compilation.

        ``loss_fn`` may return the reserved stat ``__skip_update__``
        (0/1 scalar); if any microbatch sets it, the optimizer update
        is discarded for this call (see _build_train_step).
        """
        if self._tx is None:
            raise RuntimeError("Engine has no optimizer (inference-only).")
        if getattr(self, "_opt_offloaded", False):
            # optimizer offload (reference DeepSpeed zero-offload,
            # deepspeed.py:445): state lives on host between steps
            self.opt_state = jax.device_put(self.opt_state,
                                            self._opt_shardings)
            self._opt_offloaded = False
        key = loss_fn_key or loss_fn
        if key not in self._train_step_cache:
            self._train_step_cache[key] = self._build_train_step(loss_fn)
        step = self._train_step_cache[key]

        if loss_weights is None:
            loss_weights = [1.0] * len(microbatches)
        host_batch = {
            k: np.stack([np.asarray(mb[k]) for mb in microbatches])
            for k in microbatches[0]
        }
        stacked, weights = self._globalize_tree(
            (host_batch, np.asarray(loss_weights, np.float32)))

        self.params, self.opt_state, loss, stats, gnorm = step(
            self.params, self.opt_state, stacked, weights)
        self.version += 1
        if self._decode_view is not None:
            # the view's gen-layout weight copy is now stale (params
            # identity moved) and would otherwise sit in HBM through
            # the memory-peak train phase; the next rollout reshards
            # fresh weights into the view anyway
            self._decode_view.params = None
            self._decode_view_src = None
        if (self.optimizer_config is not None
                and self.optimizer_config.offload):
            cpu = jax.devices("cpu")[0]
            self.opt_state = jax.device_put(self.opt_state, cpu)
            jax.block_until_ready(self.opt_state)
            self._opt_offloaded = True
        # ONE batched host fetch for all scalar stats: converting each
        # scalar with float() would issue a separate blocking D2H
        # round trip, which dominates step time on remote-attached
        # TPUs (measured 2078 -> 391 ms/step on a tunneled v5e).
        loss, stats, gnorm = jax.device_get((loss, stats, gnorm))
        out = {k: float(v) for k, v in stats.items()}
        out["loss"] = float(loss)
        out["grad_norm"] = float(gnorm)
        return out

    def train_minibatches(self,
                          minibatches: List[List[Dict[str, np.ndarray]]],
                          loss_fn: LossFn,
                          loss_weights: Optional[List[List[float]]] = None,
                          loss_fn_key: Optional[str] = None
                          ) -> List[Dict[str, float]]:
        """N sequential optimizer steps -- one per minibatch, each
        accumulating gradients over its microbatches -- in ONE jitted
        dispatch (the PPO minibatch loop fused; see _build_train_seq).
        Array shapes must match across ALL microbatches of ALL
        minibatches (``pad_stream_batches`` over the union). Returns
        one stats dict per minibatch, exactly what the same sequence
        of ``train_batch`` calls would have returned."""
        if self._tx is None:
            raise RuntimeError("Engine has no optimizer (inference-only).")
        if len(minibatches) == 1:
            return [self.train_batch(minibatches[0], loss_fn,
                                     loss_weights[0] if loss_weights
                                     else None, loss_fn_key)]
        if getattr(self, "_opt_offloaded", False):
            self.opt_state = jax.device_put(self.opt_state,
                                            self._opt_shardings)
            self._opt_offloaded = False
        key = ("__seq__", loss_fn_key or loss_fn)
        if key not in self._train_step_cache:
            self._train_step_cache[key] = self._build_train_seq(loss_fn)
        step = self._train_step_cache[key]

        if loss_weights is None:
            loss_weights = [[1.0] * len(m) for m in minibatches]
        host_batch = {
            k: np.stack([np.stack([np.asarray(mb[k]) for mb in m])
                         for m in minibatches])
            for k in minibatches[0][0]
        }
        stacked, weights = self._globalize_tree(
            (host_batch, np.asarray(loss_weights, np.float32)))

        self.params, self.opt_state, losses, stats, gnorms = step(
            self.params, self.opt_state, stacked, weights)
        self.version += len(minibatches)
        if self._decode_view is not None:
            self._decode_view.params = None
            self._decode_view_src = None
        if (self.optimizer_config is not None
                and self.optimizer_config.offload):
            cpu = jax.devices("cpu")[0]
            self.opt_state = jax.device_put(self.opt_state, cpu)
            jax.block_until_ready(self.opt_state)
            self._opt_offloaded = True
        losses, stats, gnorms = jax.device_get((losses, stats, gnorms))
        out = []
        for i in range(len(minibatches)):
            d = {k: float(v[i]) for k, v in stats.items()}
            d["loss"] = float(losses[i])
            d["grad_norm"] = float(gnorms[i])
            out.append(d)
        return out

    # ------------------------------------------------------------------
    # Inference
    # ------------------------------------------------------------------
    def forward_hidden(self, input_ids, seg_ids):
        if self._jit_forward_hidden is None:
            def f(params, ids, seg):
                h, _ = T.forward(self.cfg, params, ids, seg,
                                 activation_constraint=self._constrain,
                                 attention_fn=self._infer_attention_fn,
                                 moe_constraint=self.moe_constraint,
                                 pipeline=self.pipeline_ctx_infer)
                return h
            self._jit_forward_hidden = jax.jit(
                f, out_shardings=self._out_replicated())
        ids, seg = self._globalize_tree((input_ids, seg_ids))
        return self._jit_forward_hidden(self.params, ids, seg)

    def forward_logprobs(self, input_ids, seg_ids, temperature: float = 1.0,
                         logits_mask=None):
        """Next-token logprobs [S, L] (the reference's `inference` MFC
        on actor/ref models, ppo_interface.py:255)."""
        if self._jit_logprobs is None:
            def f(params, ids, seg, mask, temp, has_mask):
                h, _ = T.forward(self.cfg, params, ids, seg,
                                 activation_constraint=self._constrain,
                                 attention_fn=self._infer_attention_fn,
                                 moe_constraint=self.moe_constraint,
                                 pipeline=self.pipeline_ctx_infer)
                return F.shifted_logprobs_from_hidden(
                    self.cfg, params, h, ids, seg, temperature=temp,
                    logits_mask=mask if has_mask else None)
            self._jit_logprobs = jax.jit(
                f, static_argnames=("temp", "has_mask"),
                out_shardings=self._out_replicated())
        ids, seg, mask = self._globalize_tree(
            (input_ids, seg_ids,
             logits_mask if logits_mask is not None
             else np.zeros((1,), bool)))
        return self._jit_logprobs(self.params, ids, seg, mask,
                                  temp=temperature,
                                  has_mask=logits_mask is not None)

    def forward_values(self, input_ids, seg_ids):
        """Critic/reward scalar outputs [S, L]."""
        assert self.cfg.is_critic
        if self._jit_values is None:
            def f(params, ids, seg):
                h, _ = T.forward(self.cfg, params, ids, seg,
                                 activation_constraint=self._constrain,
                                 attention_fn=self._infer_attention_fn,
                                 moe_constraint=self.moe_constraint,
                                 pipeline=self.pipeline_ctx_infer)
                return T.critic_values(self.cfg, params, h)
            self._jit_values = jax.jit(
                f, out_shardings=self._out_replicated())
        ids, seg = self._globalize_tree((input_ids, seg_ids))
        return self._jit_values(self.params, ids, seg)

    # ------------------------------------------------------------------
    # Generation
    # ------------------------------------------------------------------
    def decode_engine(self) -> "Engine":
        """The engine generation should run on.

        dp/tp meshes decode in place (returns self). On a pipeline- or
        context-parallel mesh, decoding against layer-sharded (pipe) or
        ring-attention (ctx) weights has no efficient schedule -- the
        reference streams tokens through PP stages instead
        (``pipe_runner.py:847``, ``static_schedule.py:195``
        GenerateSchedule). The TPU-first equivalent: reshard the weights
        onto a collapsed dp x tp mesh over the SAME devices (one
        cross-mesh ``device_put`` riding ICI, amortized over the whole
        rollout and refreshed only when the weights change) and run the
        fast dp/tp decode there. ``ParallelismConfig.gen_tp_size``
        ("g" in the allocation shorthand, e.g. ``d2t2p2g4``) picks the
        decode tensor-parallel degree; default is the train tp, giving
        pp*dp-way decode data parallelism for free.
        """
        gen_tp = self.ctx.parallel.gen_tp_size or self.ctx.tp_size
        if (self.pipeline_ctx is None
                and self.ctx.parallel.context_parallel_size == 1
                and gen_tp == self.ctx.tp_size):
            return self
        if self._decode_view is None:
            from realhf_tpu.parallel.mesh import (
                MeshContext, ParallelismConfig, make_mesh,
            )
            devices = list(self.mesh.devices.flat)
            tp = gen_tp
            if len(devices) % tp != 0:
                raise ValueError(
                    f"gen_tp_size={tp} does not divide the mesh's "
                    f"{len(devices)} devices.")
            par = ParallelismConfig(
                data_parallel_size=len(devices) // tp,
                tensor_parallel_size=tp,
                sequence_parallel=self.ctx.parallel.sequence_parallel)
            view_ctx = MeshContext(self.ctx.model_name,
                                   make_mesh(par, devices), par)
            logger.info("Building decode view %s for %s mesh %s",
                        par, self.ctx.model_name, self.ctx.parallel)
            self._decode_view = Engine(self.cfg, view_ctx, self.params,
                                       optimizer=None)
            self._decode_view_src = self.params
        elif self._decode_view_src is not self.params:
            # train_batch donates + replaces self.params; set_params
            # installs a realloc'd pytree -- either way identity moved.
            # Drop the view's stale copy FIRST: holding it through the
            # reshard would transiently keep old+new gen-layout copies
            # resident (2x 2*n_params/gen_tp per chip -- an OOM at the
            # 70B scale this path exists for).
            self._decode_view.params = None
            self._decode_view.set_params(self.params)
            self._decode_view_src = self.params
        return self._decode_view

    def drop_decode_view(self):
        """Free the decode view's weight copy.

        On a pp/ctx mesh the view holds a second full copy of the
        weights (2*n_params/gen_tp bytes per chip) between rollouts;
        at the 70B scale that steady-state cost is the OOM frontier.
        Dropping returns HBM to one resident copy; the next rollout
        pays one cross-mesh reshard to rebuild the view. Policy knob:
        ``ModelSpec.drop_decode_view_after_rollout`` (applied by
        ModelHost after each generate MFC)."""
        if self._decode_view is not None:
            self._decode_view.params = None
            self._decode_view_src = None

    def decode_view_param_bytes(self) -> int:
        """MESH-WIDE bytes the decode view's weights currently hold
        (0 when absent or dropped) -- the quantity ``drop_decode_view``
        frees. One logical copy shards over the view's tp and
        REPLICATES over its dp groups, so this is
        ``n_params * itemsize * view_dp`` (per chip:
        ``n_params * itemsize / view_tp``)."""
        if self._decode_view is None or self._decode_view.params is None:
            return 0
        logical = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(self._decode_view.params))
        return logical * self._decode_view.ctx.dp_size

    def set_gen_tp(self, gen_tp: int):
        """Install a decode-view TP override (the allocation
        shorthand's "g"), validating against the mesh NOW rather than
        at the first rollout mid-experiment."""
        ndev = len(self.mesh.devices.flat)
        if gen_tp and ndev % gen_tp != 0:
            raise ValueError(
                f"gen_tp_size={gen_tp} does not divide the mesh's "
                f"{ndev} devices.")
        if gen_tp == self.ctx.parallel.gen_tp_size:
            return
        import dataclasses as _dc
        self.ctx.parallel = _dc.replace(self.ctx.parallel,
                                        gen_tp_size=gen_tp)
        self._decode_view = None
        self._decode_view_src = None

    def generate(self, prompt_ids, prompt_seg, prompt_pos, key,
                 gconfig: GenerationHyperparameters,
                 eos_token_id: Optional[int], pad_token_id: int
                 ) -> gen_mod.GenerationOutput:
        view = self.decode_engine()
        if view is not self:
            return view.generate(prompt_ids, prompt_seg, prompt_pos,
                                 key, gconfig, eos_token_id, pad_token_id)
        cache_key = (gconfig, eos_token_id, pad_token_id)
        if cache_key not in self._generate_cache:
            self._generate_cache[cache_key] = gen_mod.build_generate_fn(
                self.cfg, gconfig, eos_token_id, pad_token_id,
                activation_constraint=self._constrain,
                moe_constraint=self.moe_constraint,
                out_sharding=self._out_replicated(),
                mesh=self.mesh, attention_fn=self.attention_fn)
        fn = self._generate_cache[cache_key]
        ids, seg, pos, key = self._globalize_tree(
            (prompt_ids, prompt_seg, prompt_pos, key))
        return fn(self.params, ids, seg, pos, key)

    # ------------------------------------------------------------------
    def _cast_param_dtype(self, params):
        """Cast leaves to cfg.param_dtype (bf16 models may be fed fp32
        checkpoints; the fp32 master then lives in the opt state)."""
        pdt = jnp.dtype(self.cfg.param_dtype)
        return jax.tree.map(
            lambda a: a if a.dtype == pdt else a.astype(pdt), params)

    def set_params(self, params, already_sharded: bool = False):
        """Install new weights (parameter reallocation landing point)."""
        if already_sharded:
            self.params = params
        else:
            params = shard_rules.normalize_vocab_padding(
                self.cfg, params, self.ctx.tp_size)
            params = self._cast_param_dtype(params)
            self.params = jax.device_put(params, self._param_shardings)

    def params_numpy(self):
        """Host copy with vocab padding stripped (checkpoint layout).

        On a multi-process mesh this is a COLLECTIVE: every member
        process must call it together. The gather runs LEAF BY LEAF
        (one replicating jit per parameter, copied to host before the
        next) so peak HBM overhead is one unsharded leaf, not the whole
        model -- the motivating case is a model sharded across hosts
        precisely because it does not fit one host's devices."""
        params = self.params
        if self._multiproc:
            if self._gather_jit is None:
                rep = jax.sharding.NamedSharding(
                    self.ctx.mesh, jax.sharding.PartitionSpec())
                self._gather_jit = jax.jit(lambda x: x, out_shardings=rep)

            def gather_leaf(x):
                return np.asarray(self._gather_jit(x))

            params = jax.tree.map(gather_leaf, params)
            return shard_rules.unpad_vocab(
                self.cfg, jax.tree.map(np.asarray, params))
        # single-process: ONE bundled D2H fetch for the whole tree
        # (leaf-by-leaf np.asarray pays a sync round-trip per leaf,
        # ~100 trips for even a small model on a tunneled chip)
        return shard_rules.unpad_vocab(self.cfg, jax.device_get(params))

    def opt_state_numpy(self) -> list:
        """Host copy of the optimizer-state leaves (tree order).
        COLLECTIVE on a multi-process mesh (same discipline as
        params_numpy: leaf-by-leaf replicating gathers)."""
        return list(self.iter_opt_state_numpy())

    def iter_opt_state_numpy(self):
        """Yield optimizer-state leaves as host arrays ONE AT A TIME
        (tree order) -- the streaming form of :meth:`opt_state_numpy`:
        peak extra host memory is one unsharded leaf, the difference
        between fitting host RAM and not when the fp32 Adam state is
        ~3x the model. COLLECTIVE per leaf on a multi-process mesh;
        every group member must drain the iterator in step."""
        assert self.opt_state is not None
        leaves = jax.tree.leaves(self.opt_state)
        if self._multiproc:
            if self._gather_jit is None:
                rep = jax.sharding.NamedSharding(
                    self.ctx.mesh, jax.sharding.PartitionSpec())
                self._gather_jit = jax.jit(lambda x: x, out_shardings=rep)
            for l in leaves:
                # per-leaf transfer IS the point: bounds host memory
                # to one unsharded leaf
                yield np.asarray(self._gather_jit(l))  # graft-lint: disable=purity-sync-in-loop
        else:
            for l in leaves:
                yield np.asarray(l)  # graft-lint: disable=purity-sync-in-loop

    def load_opt_state(self, host_leaves: list):
        """Install gathered host leaves back onto the state shardings
        (recovery path; see engine/opt_checkpoint.py)."""
        assert self.opt_state is not None
        treedef = jax.tree.structure(self.opt_state)
        shard_leaves = jax.tree.leaves(self._opt_shardings)
        self.opt_state = jax.tree.unflatten(
            treedef,
            [jax.device_put(l, s)
             for l, s in zip(host_leaves, shard_leaves)])
        self._opt_offloaded = False

    def inc_version(self):
        self.version += 1

    # ------------------------------------------------------------------
    # Offload (reference async_offload/wait_for_offload,
    # real_llm_api.py:274-308: pinned-CPU weight offload between uses)
    # ------------------------------------------------------------------
    @property
    def offloaded(self) -> bool:
        return getattr(self, "_offloaded", False)

    def offload(self):
        """Move weights to host memory, freeing HBM until the next use."""
        if self.offloaded:
            return
        # the decode view holds a second full weight copy in the gen
        # layout; drop it too (rebuilt on the next pp/ctx generate; the
        # jit cache survives via XLA's compilation cache)
        self._decode_view = None
        self._decode_view_src = None
        cpu = jax.devices("cpu")[0]
        self.params = jax.device_put(self.params, cpu)
        jax.block_until_ready(self.params)
        self._offloaded = True

    def ensure_on_device(self):
        """Reload offloaded weights onto this engine's mesh shardings
        (the pre-use reload the reference runs in
        model_worker.handle_all_pre_hooks)."""
        if not self.offloaded:
            return
        self.params = jax.device_put(self.params, self._param_shardings)
        self._offloaded = False
