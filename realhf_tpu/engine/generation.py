"""Jitted autoregressive generation with KV cache + sampling.

TPU-native replacement for reference ``realhf/impl/model/nn/
real_llm_generate.py`` (generate:252) and its CUDA-graph decode
(cuda_graph.py): prefill + a `lax.scan` decode loop compiled once per
(batch, prompt-bucket, max_new_tokens) shape -- the XLA executable IS
the captured graph. Supports temperature / top-k / top-p, greedy,
min/max new tokens, EOS+pad handling, per-step sampled logprobs, and
the logits-mask output PPO replays later (genstep:131-136).
"""

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.ops.sampling import (
    NEG_INF,
    GenerationHyperparameters,
    top_k_top_p_logits,
)

# Test hook: force the fixed-trip-count scan driver even when EOS
# early exit applies (parity tests compare the two paths).
_DISABLE_EARLY_EXIT = False


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GenerationOutput:
    """Results in [B, max_new_tokens] layout; `lengths` counts the
    generated tokens per stream (including the EOS if emitted)."""
    tokens: jnp.ndarray          # int32 [B, T], pad_id beyond lengths
    logprobs: jnp.ndarray        # fp32 [B, T] of the sampled tokens
    logits_mask: Optional[jnp.ndarray]  # bool [B, T, V], True = allowed
    lengths: jnp.ndarray         # int32 [B]
    no_eos_mask: jnp.ndarray     # bool [B]: True if never emitted EOS

    def to_host(self) -> "GenerationOutput":
        """All fields as host numpy via ONE bundled ``jax.device_get``.
        Field-by-field ``np.asarray`` costs one device sync round-trip
        per field; on a relayed/tunneled platform each round-trip is
        ~0.1s of fixed latency, so the bundle matters. The class is a
        registered pytree, so device_get covers every field (including
        ones added later) and a None logits_mask passes through."""
        return jax.device_get(self)


def generate(
    cfg: TransformerConfig,
    params,
    prompt_ids: jnp.ndarray,   # [B, Lp] left-padded
    prompt_seg: jnp.ndarray,   # [B, Lp] 1 over content, 0 over pads
    prompt_pos: jnp.ndarray,   # [B, Lp]
    key: jax.Array,
    gconfig: GenerationHyperparameters,
    *,
    eos_token_id: Optional[int],
    pad_token_id: int,
    activation_constraint=None,
    moe_constraint=None,
    mesh=None,  # partitions the pallas decode kernels on dp x tp meshes
    attention_fn=None,  # sharded prefill attention on dp x tp meshes
) -> GenerationOutput:
    """Functional generation; wrap in jax.jit with gconfig/eos/pad
    static. See `build_generate_fn` for the cached jitted wrapper."""
    b, lp = prompt_ids.shape
    prompt_lens = (prompt_seg != 0).sum(-1).astype(jnp.int32)

    hidden, cache = T.prefill(cfg, params, prompt_ids, prompt_seg, prompt_pos,
                              total_len=lp + gconfig.max_new_tokens,
                              activation_constraint=activation_constraint,
                              attention_fn=attention_fn,
                              moe_constraint=moe_constraint)
    last_hidden = hidden[:, -1]  # left padding => last column is last token

    def sample_step(logits, step_idx, unfinished, k):
        logits = logits.astype(jnp.float32)
        eos_suppress = None
        if eos_token_id is not None and gconfig.min_new_tokens > 0:
            eos_suppress = (
                (step_idx < gconfig.min_new_tokens)
                & (jnp.arange(logits.shape[-1])[None, :] == eos_token_id))
            logits = jnp.where(eos_suppress, NEG_INF, logits)
        if gconfig.greedy:
            warped = logits
            tokens = jnp.argmax(warped, -1).astype(jnp.int32)
        else:
            warped = top_k_top_p_logits(logits / gconfig.temperature,
                                        gconfig.top_k, gconfig.top_p)
            if eos_suppress is not None:
                # Re-pin after temperature scaling so the mask threshold
                # below classifies the suppressed EOS as disallowed.
                warped = jnp.where(eos_suppress, NEG_INF, warped)
            tokens = jax.random.categorical(k, warped, -1).astype(jnp.int32)
        logp = jax.nn.log_softmax(warped, -1)
        logprob = jnp.take_along_axis(logp, tokens[:, None], -1)[:, 0]
        mask = warped > NEG_INF / 2
        tokens = jnp.where(unfinished, tokens, pad_token_id)
        if eos_token_id is not None:
            unfinished = unfinished & (tokens != eos_token_id)
        return tokens, logprob, mask, unfinished

    t_max = gconfig.max_new_tokens
    keys = jax.random.split(key, t_max)

    def step_once(last_hidden, cache, unfinished, emitted, step_idx, k):
        """One decode step, shared by the scan and while-loop drivers."""
        logits = T.lm_logits(cfg, params, last_hidden)
        was_unfinished = unfinished
        tokens, logprob, mask, unfinished = sample_step(
            logits, step_idx, unfinished, k)
        emitted = emitted + was_unfinished.astype(jnp.int32)
        pos = prompt_lens + step_idx
        # all streams share the padded prompt length, so cache writes
        # land in one uniform slot (dynamic_update_slice fast path)
        new_hidden, cache = T.decode_step(cfg, params, cache, tokens, pos,
                                          moe_constraint, uniform_slot=True,
                                          mesh=mesh)
        return new_hidden, cache, unfinished, emitted, tokens, logprob, mask

    want_mask = not gconfig.force_no_logits_mask
    early_exit = (not _DISABLE_EARLY_EXIT
                  and eos_token_id is not None
                  and gconfig.min_new_tokens < t_max)
    if early_exit:
        # EOS can end every stream before t_max: a while_loop stops
        # decoding the moment no stream is unfinished, writing into
        # preallocated output buffers. The reference terminates its
        # genstep loop the same way (real_llm_generate.py genstep
        # terminate check); lax.scan cannot early-exit.
        tokens_buf = jnp.full((b, t_max), pad_token_id, jnp.int32)
        logp_buf = jnp.zeros((b, t_max), jnp.float32)
        mask_buf = (jnp.zeros((b, t_max, cfg.vocab_size), bool)
                    if want_mask else jnp.zeros((1,), bool))

        def w_cond(c):
            step = c[0]
            unfinished = c[3]
            return (step < t_max) & jnp.any(unfinished)

        def w_body(c):
            step, last_hidden, cache, unfinished, emitted, bufs = c
            tb, lb, mb = bufs
            last_hidden, cache, unfinished, emitted, tok, lp, mask = \
                step_once(last_hidden, cache, unfinished, emitted,
                          step, keys[step])
            tb = jax.lax.dynamic_update_slice(tb, tok[:, None],
                                              (0, step))
            lb = jax.lax.dynamic_update_slice(lb, lp[:, None], (0, step))
            if want_mask:
                mb = jax.lax.dynamic_update_slice(
                    mb, mask[:, None, :], (0, step, 0))
            return (step + 1, last_hidden, cache, unfinished, emitted,
                    (tb, lb, mb))

        init = (jnp.int32(0), last_hidden, cache, jnp.ones((b,), bool),
                jnp.zeros((b,), jnp.int32),
                (tokens_buf, logp_buf, mask_buf))
        (_, _, _, unfinished, emitted,
         (tokens, logprobs, logits_mask)) = jax.lax.while_loop(
             w_cond, w_body, init)
        if not want_mask:
            logits_mask = None
    else:
        def body(carry, x):
            last_hidden, cache, unfinished, emitted = carry
            step_idx, k = x
            last_hidden, cache, unfinished, emitted, tok, lp, mask = \
                step_once(last_hidden, cache, unfinished, emitted,
                          step_idx, k)
            out = (tok, lp, mask) if want_mask else (tok, lp)
            return (last_hidden, cache, unfinished, emitted), out

        init = (last_hidden, cache, jnp.ones((b,), bool),
                jnp.zeros((b,), jnp.int32))
        (_, _, unfinished, emitted), outs = jax.lax.scan(
            body, init, (jnp.arange(t_max), keys))
        if want_mask:
            tokens, logprobs, logits_mask = outs
            logits_mask = logits_mask.swapaxes(0, 1)  # [B, T, V]
        else:
            tokens, logprobs = outs
            logits_mask = None
        tokens = tokens.T  # [B, T]
        logprobs = logprobs.T
    return GenerationOutput(
        tokens=tokens,
        logprobs=logprobs,
        logits_mask=logits_mask,
        lengths=emitted,
        no_eos_mask=unfinished,
    )


def build_generate_fn(cfg: TransformerConfig,
                      gconfig: GenerationHyperparameters,
                      eos_token_id: Optional[int], pad_token_id: int,
                      activation_constraint=None, moe_constraint=None,
                      out_sharding=None, mesh=None, attention_fn=None):
    """Jitted generate closure; XLA caches compilations per
    batch/bucket shape. Engines build this once and reuse it."""
    fn = functools.partial(generate, cfg, gconfig=gconfig,
                           eos_token_id=eos_token_id,
                           pad_token_id=pad_token_id,
                           activation_constraint=activation_constraint,
                           moe_constraint=moe_constraint,
                           mesh=mesh, attention_fn=attention_fn)

    def run(params, prompt_ids, prompt_seg, prompt_pos, key):
        return fn(params, prompt_ids, prompt_seg, prompt_pos, key)

    # out_sharding: replicated outputs on multi-process meshes so every
    # worker-group member can read the generated tokens.
    return jax.jit(run, out_shardings=out_sharding)
