"""Continuous (inflight) batching for generation.

TPU-native counterpart of the reference's InflightBatchingGenerator
prototype (``real_llm_generate.py:664``, shipped unwired there): a
fixed set of decode SLOTS runs a jitted chunked decode loop; whenever
a slot's sequence finishes (EOS or max_new_tokens), the host harvests
it and refills the slot by prefilling the next queued prompt into that
slot's KV-cache rows, while the other slots keep decoding. Short
sequences therefore never wait for the batch's longest one -- the
throughput property vLLM-style serving is built on -- while every
device computation keeps static shapes:

- ``decode_chunk``: `lax.scan` over ``chunk_size`` steps for all slots
  (one compiled program, reused forever),
- ``prefill_into_slot``: batch-1 prefill at a bucketed prompt length,
  scattered into the slot's cache rows (one compilation per bucket).

Host<->device sync happens once per chunk, not per token. The
logits-mask replay of PPO is intentionally unsupported here (use the
batch ``generate`` path); inflight mode targets throughput-oriented
rollout generation (GRPO / ReMax / gen experiments).
"""

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.engine import kv_pool as _kvp
from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.obs import tracing
from realhf_tpu.ops.sampling import (
    NEG_INF,
    GenerationHyperparameters,
    top_k_top_p_logits,
)

logger = logging.getLogger("engine.inflight")


def _bucket(n: int, buckets=(64, 128, 256, 512, 1024, 2048, 4096)) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n


#: finer ladder for the partial-prefill (prefix-cache hit) path: the
#: donor window and the uncached suffix each get their own bucket, so
#: a coarse floor would waste most of the win -- a 95%-hit request
#: must pay a SMALL suffix bucket, not the full-prompt one
_PARTIAL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


@dataclasses.dataclass
class FinishedSequence:
    request_id: int
    tokens: np.ndarray     # [len] generated ids (incl. EOS if emitted)
    logprobs: np.ndarray   # [len]
    no_eos: bool           # True iff the sequence never emitted EOS
                           # (hit max_new_tokens), matching the batch
                           # path's seq_no_eos_mask semantics.
    #: speculative-decoding accounting for THIS sequence (0 when the
    #: drafter is off): drafts proposed / drafts accepted by verify
    spec_proposed: int = 0
    spec_accepted: int = 0
    #: host copies of the sequence's KV rows ([nl, nkv, len(prompt)+
    #: len(tokens), hd] each), present only for ``harvest(
    #: export_kv=True)`` -- the serving scheduler publishes them into
    #: the radix prefix cache (serving/prefix_cache.py)
    kv: Optional[Tuple[np.ndarray, np.ndarray]] = None
    #: paged backends (``harvest(export_blocks=True)``): the KV pool
    #: blocks holding this sequence's rows, each carrying ONE extra
    #: pool reference owned by the receiver -- publish them into the
    #: pooled prefix cache (which increfs what it keeps), then
    #: ``pool.free(blocks)``. ``n_rows`` = valid token rows covered.
    blocks: Optional[Tuple[int, ...]] = None
    n_rows: int = 0


class InflightBatchingGenerator:
    """Slot-machine generation over a queue of prompts."""

    #: the serving scheduler feature-detects the prefix-cache fill /
    #: KV-export extensions on this attribute (test fakes may lack it)
    supports_prefix_fill = True

    def __init__(self, cfg: TransformerConfig, params,
                 gconfig: GenerationHyperparameters,
                 *, n_slots: int, max_prompt_len: int,
                 eos_token_id: Optional[int], pad_token_id: int,
                 chunk_size: int = 32, moe_constraint=None,
                 mesh=None, attention_fn=None,
                 spec_decode_k: int = 0, drafter=None,
                 kv_pool=None, kv_cache_dtype: Optional[str] = None,
                 bucket_pair_cap: int = 24):
        if not gconfig.force_no_logits_mask:
            raise ValueError(
                "inflight batching does not produce the PPO logits "
                "mask; set force_no_logits_mask=True or use the batch "
                "generate path.")
        self.cfg = cfg
        self.params = params
        self.g = gconfig
        self.n_slots = n_slots
        self.eos = eos_token_id
        self.pad = pad_token_id
        self.chunk = chunk_size
        self.cache_len = T.round_cache_len(
            max_prompt_len + gconfig.max_new_tokens)
        # ---- KV substrate: dense per-slot windows (default) or the
        # block-granular paged pool (engine/kv_pool.py) --------------
        self.kv_pool = kv_pool
        if kv_cache_dtype is not None \
                and kv_cache_dtype not in _kvp.KV_CACHE_DTYPES:
            raise ValueError(
                f"kv_cache_dtype must be one of {_kvp.KV_CACHE_DTYPES}")
        if kv_cache_dtype == "int8" and kv_pool is None:
            raise ValueError(
                "kv_cache_dtype='int8' requires a paged KV pool "
                "(dequant-on-read lives in the pool gather path); "
                "pass kv_pool=KVPool(..., dtype='int8').")
        if kv_pool is not None:
            if kv_pool.cfg is None:
                raise ValueError("paged decoding needs a device-"
                                 "backed KVPool (not host_only)")
            self._blen = kv_pool.block_len
            self._max_blocks = -(-self.cache_len // self._blen)
            self._slot_blocks: List[List[int]] = [
                [] for _ in range(n_slots)]
            self._bt_host = np.zeros((n_slots, self._max_blocks),
                                     np.int32)
            self._bt_dev = None  # refreshed lazily on table changes
            #: upper bound of window rows a slot may have written
            #: (exact at fill/spec-round/harvest, +chunk per plain
            #: decode chunk) -- capacity reservation never needs a
            #: blocking device readback
            self._slot_rows_ub = [0] * n_slots
            self._slot_prompt_n = [0] * n_slots
            self._paged_fill_jit = jax.jit(functools.partial(
                _paged_prefill, cfg, moe_constraint, attention_fn,
                kv_pool.meta))
            self._paged_suffix_jit = jax.jit(functools.partial(
                _paged_prefill_suffix, cfg, moe_constraint,
                kv_pool.meta))
            self._paged_decode_jit = jax.jit(functools.partial(
                _paged_decode_chunk, cfg, gconfig, eos_token_id,
                pad_token_id, chunk_size, moe_constraint, mesh,
                kv_pool.meta))
            self._paged_verify_jit = None  # built with spec below
        #: distinct (donor, suffix) bucket pairs the partial-prefill
        #: path has compiled; capped (satellite: the (c_b, s_b)
        #: ladder product is 81 pairs -- silent unbounded jit-cache
        #: growth without this)
        self.bucket_pair_cap = int(bucket_pair_cap)
        self._bucket_pairs = set()
        self._bucket_cap_warned = False
        # jax.jit retraces per prompt-bucket shape on its own; one
        # jitted function covers every bucket.
        self._prefill = jax.jit(functools.partial(
            _prefill_into_slot, self.cfg, self.cache_len,
            moe_constraint, attention_fn))
        # partial-prefill entry for radix prefix-cache hits: donor KV
        # seeds rows [0, c_b) and only the uncached suffix runs the
        # forward (one compilation per (donor-bucket, suffix-bucket))
        self._prefill_suffix = jax.jit(functools.partial(
            _prefill_suffix_into_slot, self.cfg, self.cache_len,
            moe_constraint))

        # prompt-lookup speculative decoding (greedy-exact verify):
        # k drafts per round, all verified in ONE forward over the
        # carry. Sampling-based generation falls back to the plain
        # decode loop -- acceptance is only exact under argmax.
        self._spec_k = int(spec_decode_k or 0)
        if self._spec_k > 0 and not gconfig.greedy:
            logger.warning(
                "spec_decode_k=%d requested but gconfig.greedy is "
                "False; speculative decoding is greedy-exact only -- "
                "disabling.", self._spec_k)
            self._spec_k = 0
        self._drafter = None
        self._verify = None
        if self._spec_k > 0:
            if drafter is None:
                from realhf_tpu.engine.drafter import NGramDrafter
                drafter = NGramDrafter(self._spec_k)
            self._drafter = drafter
            self._verify = jax.jit(functools.partial(
                _verify_chunk, cfg, gconfig, eos_token_id,
                self._spec_k, moe_constraint))
            if self.kv_pool is not None:
                self._paged_verify_jit = jax.jit(functools.partial(
                    _paged_verify, cfg, gconfig, eos_token_id,
                    self._spec_k, moe_constraint, self.kv_pool.meta))

        nm = gconfig.max_new_tokens
        if self.kv_pool is not None:
            # paged: the pool owns the KV rows; per-slot state keeps
            # only the write index ("length" in window coordinates --
            # compacted, so row j holds token j and validity is just
            # j < length)
            kv_state = dict(length=jnp.zeros((n_slots,), jnp.int32))
        else:
            dense_dt = {None: None, "fp32": jnp.float32,
                        "bf16": jnp.bfloat16}[kv_cache_dtype]
            kv_state = dict(cache=T.init_kv_cache(
                cfg, n_slots, self.cache_len, dtype=dense_dt))
        self.state = dict(
            **kv_state,
            last_hidden=jnp.zeros((n_slots, cfg.hidden_dim),
                                  jnp.dtype(cfg.compute_dtype)),
            prompt_len=jnp.zeros((n_slots,), jnp.int32),
            emitted=jnp.zeros((n_slots,), jnp.int32),
            active=jnp.zeros((n_slots,), bool),
            unfinished=jnp.zeros((n_slots,), bool),
            hit_eos=jnp.zeros((n_slots,), bool),
            out_tokens=jnp.full((n_slots, nm), pad_token_id, jnp.int32),
            out_logprobs=jnp.zeros((n_slots, nm), jnp.float32),
            spec_proposed=jnp.zeros((n_slots,), jnp.int32),
            spec_accepted=jnp.zeros((n_slots,), jnp.int32),
        )
        self._slot_req = [-1] * n_slots  # host: request id per slot
        #: host copy of each slot's prompt: the n-gram drafter needs
        #: the full history, and the scheduler needs it to key KV
        #: publications
        self._slot_prompt: List[Optional[np.ndarray]] = [None] * n_slots
        #: how the last fill_slot was lowered (bucket REGRESSION
        #: surface: a 95%-cached prompt must compile/pay the SUFFIX
        #: bucket, not the full-prompt one)
        self.last_fill: Dict = {}
        self.fill_stats = dict(prefill_tokens=0, prefill_tokens_saved=0,
                               bucket_pairs=0, bucket_pairs_capped=0)
        self.spec_stats = dict(rounds=0)

        self._decode_chunk = jax.jit(functools.partial(
            _decode_chunk, cfg, gconfig, eos_token_id, pad_token_id,
            chunk_size, moe_constraint, mesh))

    # ------------------------------------------------------------------
    # Slot-level step API. The serving subsystem
    # (``realhf_tpu/serving/scheduler.py``) drives these directly to
    # interleave admission, decoding, and harvesting at iteration
    # granularity; ``generate_all`` below is the run-to-completion
    # composition of the same primitives.
    # ------------------------------------------------------------------
    def free_slots(self) -> List[int]:
        """Slot indices with no request bound to them."""
        return [s for s, r in enumerate(self._slot_req) if r < 0]

    @property
    def n_live(self) -> int:
        """Slots currently bound to a request (decoding or awaiting
        harvest)."""
        return sum(1 for r in self._slot_req if r >= 0)

    def decode_chunk(self, key: jax.Array):
        """Advance every live slot by up to ``chunk_size`` decode
        steps (one host<->device sync). With ``spec_decode_k > 0``
        (greedy only) the chunk runs speculative verify rounds
        instead: each round drafts k tokens per slot on the host
        (prompt lookup) and verifies them in ONE forward, emitting
        1..k+1 tokens per live slot per device call.

        Paged backends reserve pool blocks for the chunk's worst-case
        growth FIRST (host arithmetic, no device sync) and may raise
        :class:`~realhf_tpu.engine.kv_pool.KVPoolOOM` -- the serving
        scheduler relieves pool pressure (prefix-cache eviction, then
        sequence eviction) and retries."""
        if self._spec_k > 0 and self.n_live:
            self._spec_chunk()
        elif self.kv_pool is not None:
            self._paged_chunk(key)
        else:
            self.state = self._decode_chunk(self.params, self.state,
                                            key)

    # -- paged-mode internals (engine/kv_pool.py) ----------------------
    def _win_for(self, need: int) -> int:
        """Gather-window length for the paged compute path: the
        maximum live length rounded up on the cache-row multiple, so
        the chunk compiles O(cache_len / 128) window shapes -- one
        per bucket, as the dense path does -- instead of one per
        distinct length."""
        if need <= 0:
            return 0
        m = T._CACHE_LEN_MULTIPLE
        return min(self.cache_len, -(-need // m) * m)

    def _bt_device(self):
        if self._bt_dev is None:
            self._bt_dev = jax.device_put(self._bt_host)
        return self._bt_dev

    def _ensure_capacity(self, growth: int) -> int:
        """Reserve pool blocks so every live slot can append up to
        ``growth`` rows without a mid-chunk allocation (block tables
        are frozen inside jit). Raises :class:`KVPoolOOM` on
        exhaustion; earlier slots keep their new reservations (they
        are real and freed at harvest). Returns the gather-window
        length covering the post-chunk worst case."""
        nm = self.g.max_new_tokens
        need_max = 0
        for slot in range(self.n_slots):
            if self._slot_req[slot] < 0:
                continue
            n = self._slot_prompt_n[slot]
            cap_rows = min(self._slot_rows_ub[slot] + growth,
                           n + nm, self.cache_len)
            have = len(self._slot_blocks[slot])
            need = self.kv_pool.blocks_for_rows(cap_rows) - have
            if need > 0:
                new = self.kv_pool.alloc(need)  # may raise KVPoolOOM
                self._slot_blocks[slot].extend(new)
                self._bt_host[slot, have:have + len(new)] = new
                self._bt_dev = None
            self._slot_rows_ub[slot] = cap_rows
            need_max = max(need_max, cap_rows)
        return self._win_for(need_max)

    def _paged_chunk(self, key):
        win = self._ensure_capacity(self.chunk)
        if win == 0:
            return
        warange = jnp.arange(win, dtype=jnp.int32)
        arrays, self.state = self._paged_decode_jit(
            self.params, self.kv_pool.arrays(), self.state,
            self._bt_device(), warange, key)
        self.kv_pool.update(arrays)

    def kv_pool_stats(self) -> Dict:
        """Pool accounting plus this generator's own row usage; the
        serving scheduler adds the prefix cache's rows on top to get
        the pool-wide fragmentation ratio."""
        s = self.kv_pool.stats()
        s["rows_in_use"] = sum(
            self._slot_rows_ub[i] for i in range(self.n_slots)
            if self._slot_req[i] >= 0)
        return s

    def admission_blocks_needed(self, prompt_len: int,
                                cached_len: int = 0) -> int:
        """Free-list blocks a fill of this shape will consume
        (aliased prefix blocks are shared, not allocated), plus one
        headroom block for the first decode chunk. The scheduler
        admission gate compares this against the pool's free count."""
        c = max(0, min(int(cached_len), int(prompt_len) - 1))
        c -= c % self._blen
        return (self.kv_pool.blocks_for_rows(prompt_len)
                - c // self._blen + 1)

    def _spec_chunk(self):
        """ceil(chunk / (k+1)) verify rounds == the plain chunk's
        token budget when every draft is accepted. Each round pays one
        bundled D2H (the drafter consumes the history on the host) and
        one verify forward -- versus ``chunk`` sequential forwards on
        the plain path."""
        nm = self.g.max_new_tokens
        rounds = -(-self.chunk // (self._spec_k + 1))
        for _ in range(rounds):
            # host drafting needs the emitted tokens each round; this
            # is the one bundled readback the speculative loop is
            # built around (it replaces k+1 sequential forwards)
            host = self._host_view()  # graft-lint: disable=purity-sync-in-loop
            drafts = np.zeros((self.n_slots, self._spec_k), np.int32)
            n_live = 0
            for slot in range(self.n_slots):
                if (self._slot_req[slot] < 0
                        or not host["active"][slot]
                        or not host["unfinished"][slot]
                        or host["emitted"][slot] >= nm):
                    continue
                n_live += 1
                e = int(host["emitted"][slot])
                hist = np.concatenate(
                    [self._slot_prompt[slot],
                     host["out_tokens"][slot, :e].astype(np.int64)])
                drafts[slot] = self._drafter.propose(hist)
            if n_live == 0:
                break
            with tracing.span("serve:spec_verify", n_live=n_live,
                              k=self._spec_k):
                if self.kv_pool is not None:
                    # the per-round host view gives EXACT lengths --
                    # tighten the row upper bounds before reserving
                    # this round's worst-case growth (k+1 rows/slot)
                    for slot in range(self.n_slots):
                        if self._slot_req[slot] >= 0:
                            self._slot_rows_ub[slot] = (
                                self._slot_prompt_n[slot]
                                + int(host["emitted"][slot]))
                    win = self._ensure_capacity(self._spec_k + 1)
                    warange = jnp.arange(win, dtype=jnp.int32)
                    arrays, self.state = self._paged_verify_jit(
                        self.params, self.kv_pool.arrays(),
                        self.state, self._bt_device(), warange,
                        jnp.asarray(drafts))
                    self.kv_pool.update(arrays)
                else:
                    self.state = self._verify(self.params, self.state,
                                              jnp.asarray(drafts))
            self.spec_stats["rounds"] += 1

    def swap_params(self, params):
        """Hot-swap the weights used from the next decode/prefill on.
        Safe between ``decode_chunk`` calls: the jitted programs take
        params as an argument, so no recompilation happens as long as
        shapes/dtypes match."""
        self.params = params

    def release_slot(self, slot: int):
        """Abort the sequence in ``slot`` (cancellation/eviction): the
        slot immediately becomes free and the partial output is
        dropped. Paged backends return the slot's pool blocks to the
        free list (aliased prefix blocks just drop one reference)."""
        self._slot_req[slot] = -1
        self._slot_prompt[slot] = None
        if self.kv_pool is not None and self._slot_blocks[slot]:
            self.kv_pool.free(self._slot_blocks[slot])
            self._slot_blocks[slot] = []
            self._bt_host[slot, :] = 0
            self._bt_dev = None
            self._slot_rows_ub[slot] = 0
            self._slot_prompt_n[slot] = 0
        self.state["active"] = self.state["active"].at[slot].set(False)

    def _host_view(self) -> Dict[str, np.ndarray]:
        """ONE bundled D2H fetch of every per-slot output/status
        array. Per-slot ``np.asarray`` reads pay a blocking sync
        round-trip each (~0.1s fixed latency per transfer on a
        relayed/tunneled platform); harvesting N finished slots that
        way costs 4N transfers per chunk -- the decode hot path's
        dominant host overhead (docs/perf.md). The bundle is a few
        n_slots x max_new_tokens int/float arrays, so downloading all
        of it beats per-slot slicing as soon as more than one value is
        read."""
        return jax.device_get({
            k: self.state[k]
            for k in ("active", "unfinished", "emitted", "hit_eos",
                      "out_tokens", "out_logprobs", "spec_proposed",
                      "spec_accepted")})

    def snapshot_slot(self, slot: int):
        """(tokens_so_far, logprobs_so_far) of the sequence in
        ``slot`` -- the incremental-streaming read. One device sync;
        use :meth:`snapshot_slots` to read several slots per chunk."""
        return self.snapshot_slots([slot])[slot]

    def snapshot_slots(self, slots: List[int]) -> Dict[int, tuple]:
        """slot -> (tokens_so_far, logprobs_so_far) for every
        requested slot via ONE bundled device fetch (the serving
        scheduler streams every live slot after each chunk; per-slot
        reads would pay one sync round-trip each)."""
        if not slots:
            return {}
        host = self._host_view()
        out: Dict[int, tuple] = {}
        for slot in slots:
            n = int(host["emitted"][slot])
            out[slot] = (host["out_tokens"][slot, :n],
                         host["out_logprobs"][slot, :n])
        return out

    def harvest(self, export_kv: bool = False,
                export_blocks: bool = False) -> List[FinishedSequence]:
        """Collect every finished sequence and free its slot (one
        bundled host transfer, not four per finished slot).

        ``export_kv=True`` additionally downloads each finished slot's
        KV rows (prompt + generated, in token order) in ONE bundled
        fetch and attaches them as ``FinishedSequence.kv`` so the
        serving scheduler can publish them into the radix prefix
        cache. This is a full slot-cache D2H -- only ask for it when a
        prefix cache is actually configured.

        ``export_blocks=True`` (paged backends only) attaches each
        finished slot's pool block ids instead -- ZERO device
        transfer: publication into the pooled prefix cache is pure
        refcount bookkeeping. Each listed block carries one extra
        pool reference owned by the caller, who must
        ``kv_pool.free(fs.blocks)`` once done publishing."""
        out: List[FinishedSequence] = []
        if self.n_live == 0:
            return out
        host = self._host_view()
        slots: List[int] = []
        for slot in range(self.n_slots):
            rid = self._slot_req[slot]
            if rid < 0 or (host["active"][slot]
                           and host["unfinished"][slot]):
                continue
            n = int(host["emitted"][slot])
            out.append(FinishedSequence(
                request_id=rid,
                tokens=host["out_tokens"][slot, :n],
                logprobs=host["out_logprobs"][slot, :n],
                no_eos=not bool(host["hit_eos"][slot]),
                spec_proposed=int(host["spec_proposed"][slot]),
                spec_accepted=int(host["spec_accepted"][slot])))
            slots.append(slot)
        if export_blocks and slots:
            if self.kv_pool is None:
                raise ValueError(
                    "export_blocks requires a paged (KV-pool) backend")
            for fs, slot in zip(out, slots):
                blocks = tuple(self._slot_blocks[slot])
                self.kv_pool.incref(blocks)  # receiver-owned refs
                fs.blocks = blocks
                fs.n_rows = (self._slot_prompt_n[slot]
                             + int(host["emitted"][slot]))
        if export_kv and slots:
            if self.kv_pool is not None:
                self._export_pool_kv(out, slots, host)
            else:
                idx = jnp.asarray(slots)
                cache = self.state["cache"]
                kv = jax.device_get(dict(k=cache["k"][:, idx],
                                         v=cache["v"][:, idx],
                                         valid=cache["valid"][idx]))
                for i, fs in enumerate(out):
                    # valid rows in row order ARE token order: donor
                    # prefix rows, then the left-padded suffix's real
                    # tail, then sequentially appended decode rows
                    rows = np.flatnonzero(kv["valid"][i])
                    fs.kv = (np.ascontiguousarray(
                                 kv["k"][:, i][:, :, rows, :]),
                             np.ascontiguousarray(
                                 kv["v"][:, i][:, :, rows, :]))
        for slot in slots:
            self.release_slot(slot)
        return out

    def _export_pool_kv(self, out: List[FinishedSequence],
                        slots: List[int], host):
        """Paged counterpart of the dense KV export: one bundled D2H
        of every finished slot's pool rows, dequantized on the host
        for int8 pools (the host radix cache stores values)."""
        blen = self._blen
        flats, counts = [], []
        for slot in slots:
            rows = (self._slot_prompt_n[slot]
                    + int(host["emitted"][slot]))
            w = np.arange(rows)
            flats.append(self._bt_host[slot, w // blen] * blen
                         + w % blen)
            counts.append(rows)
        all_rows = np.concatenate(flats) if flats else np.zeros(0, int)
        arrays = self.kv_pool.arrays()
        fetch = dict(k=arrays["k"][:, :, all_rows],
                     v=arrays["v"][:, :, all_rows])
        if self.kv_pool.meta.quant:
            fetch["ks"] = arrays["k_scale"][:, :, all_rows]
            fetch["vs"] = arrays["v_scale"][:, :, all_rows]
        got = jax.device_get(fetch)
        k, v = got["k"], got["v"]
        if self.kv_pool.meta.quant:
            k = k.astype(np.float32) * got["ks"][..., None]
            v = v.astype(np.float32) * got["vs"][..., None]
        off = 0
        for fs, rows in zip(out, counts):
            fs.kv = (np.ascontiguousarray(k[:, :, off:off + rows, :]),
                     np.ascontiguousarray(v[:, :, off:off + rows, :]))
            off += rows

    @property
    def max_prompt_len(self) -> int:
        """Longest admissible prompt: the cache row minus the decode
        budget. Admission layers (serving.RequestQueue) check this so
        oversized prompts are rejected before reaching a slot."""
        return self.cache_len - self.g.max_new_tokens

    # ------------------------------------------------------------------
    def fill_slot(self, slot: int, request_id: int,
                  prompt: np.ndarray, cached_len: int = 0,
                  prefix_kv=None, cached_blocks=None):
        """Prefill ``prompt`` into ``slot``. With ``cached_len > 0``
        the first ``cached_len`` positions are seeded from ``prefix_kv``
        (``(k, v)``, each ``[nl, nkv, >=cached_len, hd]`` host arrays
        from the radix prefix cache) and ONLY the uncached suffix runs
        the forward -- bucketed by SUFFIX length, so a 95%-hit request
        compiles and pays the small bucket, not the full-prompt one.

        Paged backends take ``cached_blocks`` (pool block ids from the
        POOLED prefix cache) instead of ``prefix_kv``: whole cached
        blocks are aliased into the slot's block table -- a refcount
        bump, zero KV copy -- and only the suffix runs the forward."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = len(prompt)
        max_prompt = self.max_prompt_len
        if n > max_prompt:
            raise ValueError(
                f"prompt of {n} tokens exceeds max_prompt_len "
                f"{max_prompt}")
        if self.kv_pool is not None:
            if prefix_kv is not None:
                raise ValueError(
                    "paged backends alias pool blocks; pass "
                    "cached_blocks (not prefix_kv)")
            self._fill_slot_paged(slot, request_id, prompt,
                                  int(cached_len), cached_blocks)
            return
        if cached_blocks is not None:
            raise ValueError("cached_blocks requires a paged (KV-"
                             "pool) backend")
        c = int(cached_len)
        if c > 0 and prefix_kv is None:
            raise ValueError("cached_len > 0 requires prefix_kv")
        # the hidden state feeding the first decode step is NOT in the
        # KV cache: at least one real token must always prefill
        c = min(c, n - 1)
        nm = self.g.max_new_tokens
        c_b = s_b = 0
        while c > 0:
            # donor rows are padded to their own bucket so jit sees a
            # bounded set of (donor, suffix) shapes instead of one
            # compilation per distinct cached_len
            c_b = _bucket(c, _PARTIAL_BUCKETS)
            s_b = _bucket(n - c, _PARTIAL_BUCKETS)
            if c_b + s_b + nm <= self.cache_len:
                break
            # donor rounding overflows the cache row: TRIM the donor
            # to the next-lower bucket boundary (a shorter cached
            # prefix is still a valid prefix) rather than throwing
            # the whole hit away
            smaller = [b for b in _PARTIAL_BUCKETS if b < c_b]
            c = smaller[-1] if smaller else 0
        if c > 0 and not self._pair_admit(c_b, s_b):
            c = 0  # compile-cache cap: fall back to full prefill
        if c <= 0:
            lp = min(_bucket(n), max_prompt)
            ids = np.full((1, lp), self.pad, np.int32)
            seg = np.zeros((1, lp), np.int32)
            pos = np.zeros((1, lp), np.int32)
            ids[0, lp - n:] = prompt          # left padding
            seg[0, lp - n:] = 1
            pos[0, lp - n:] = np.arange(n)
            # one bundled upload (a relayed platform pays fixed
            # latency per transfer; see Engine._globalize_tree).
            # `slot` keeps its host int for the list index below --
            # indexing with a device scalar would force a blocking
            # D2H readback per fill.
            with tracing.span("serve:prefill", slot=slot,
                              prompt_len=n, bucket=lp):
                dev_slot, ids, seg, pos = jax.device_put(
                    (slot, ids, seg, pos))
                self.state = self._prefill(self.params, self.state,
                                           dev_slot, ids, seg, pos)
            self.last_fill = dict(bucket=lp, prompt_len=n,
                                  cached_len=0, prefilled=n)
            self.fill_stats["prefill_tokens"] += n
        else:
            s = n - c
            kdtype = self.state["cache"]["k"].dtype
            dk = np.zeros((self.cfg.n_layers, self.cfg.n_kv_heads,
                           c_b, self.cfg.head_dim), kdtype)
            dv = np.zeros_like(dk)
            dk[:, :, :c] = np.asarray(prefix_kv[0])[:, :, :c]
            dv[:, :, :c] = np.asarray(prefix_kv[1])[:, :, :c]
            dvalid = np.arange(c_b) < c
            ids = np.full((1, s_b), self.pad, np.int32)
            seg = np.zeros((1, s_b), np.int32)
            pos = np.zeros((1, s_b), np.int32)
            ids[0, s_b - s:] = prompt[c:]        # left padding within
            seg[0, s_b - s:] = 1                 # the suffix window
            pos[0, s_b - s:] = c + np.arange(s)
            with tracing.span("serve:prefill", slot=slot,
                              prompt_len=n, bucket=s_b, cached_len=c):
                dev = jax.device_put((slot, dk, dv, dvalid, ids, seg,
                                      pos))
                self.state = self._prefill_suffix(self.params,
                                                  self.state, *dev)
            self.last_fill = dict(bucket=s_b, prompt_len=n,
                                  cached_len=c, prefilled=s)
            self.fill_stats["prefill_tokens"] += s
            self.fill_stats["prefill_tokens_saved"] += c
        self._slot_req[slot] = request_id
        self._slot_prompt[slot] = prompt

    def _pair_admit(self, c_b: int, s_b: int) -> bool:
        """Admission to the partial-prefill compile cache (satellite:
        the ``(c_b, s_b)`` ladder product is 81 shapes -- each one a
        full jit compile -- and nothing bounded it). Known pairs pass;
        new pairs past ``bucket_pair_cap`` fall back to full prefill
        with one explicit warning, counted in ``fill_stats``."""
        pair = (c_b, s_b)
        if pair in self._bucket_pairs:
            return True
        if len(self._bucket_pairs) >= self.bucket_pair_cap:
            if not self._bucket_cap_warned:
                logger.warning(
                    "partial-prefill compile cache hit its cap (%d "
                    "distinct (donor, suffix) bucket pairs); further "
                    "new shapes fall back to full prefill instead of "
                    "growing the jit cache unboundedly. Raise "
                    "bucket_pair_cap if the traffic mix really needs "
                    "more shapes.", self.bucket_pair_cap)
                self._bucket_cap_warned = True
            self.fill_stats["bucket_pairs_capped"] += 1
            return False
        self._bucket_pairs.add(pair)
        self.fill_stats["bucket_pairs"] = len(self._bucket_pairs)
        return True

    def _fill_slot_paged(self, slot: int, request_id: int,
                         prompt: np.ndarray, cached_len: int,
                         cached_blocks):
        """Paged fill: alias whole cached blocks (refcount bump, zero
        copy), allocate own blocks for the rest of the window, then
        run either the full prefill or the suffix forward, scattering
        the computed rows into the pool. May raise
        :class:`~realhf_tpu.engine.kv_pool.KVPoolOOM`."""
        n = len(prompt)
        blen = self._blen
        # whole-block aliasing only: a partial tail block would be
        # appended into by this sequence and corrupt the shared copy,
        # so the hit is trimmed to the block boundary (< blen tokens
        # of re-prefill, by construction)
        c = max(0, min(int(cached_len), n - 1))
        c -= c % blen
        c_b = s_b = 0
        if c > 0 and cached_blocks is None:
            raise ValueError(
                "cached_len > 0 requires cached_blocks on a paged "
                "backend")
        if c > 0:
            c_b = _bucket(c, _PARTIAL_BUCKETS)
            s_b = _bucket(n - c, _PARTIAL_BUCKETS)
            if not self._pair_admit(c_b, s_b):
                c = 0
        n_alias = c // blen
        if c > 0 and len(cached_blocks) < n_alias:
            raise ValueError(
                f"cached_blocks covers {len(cached_blocks)} block(s) "
                f"but cached_len {c} spans {n_alias}")
        own = self.kv_pool.alloc(
            self.kv_pool.blocks_for_rows(n) - n_alias)
        try:
            alias = [int(b) for b in cached_blocks[:n_alias]] \
                if c > 0 else []
            if alias:
                self.kv_pool.incref(alias)
        except BaseException:
            # a bad alias chain (stale cached block id) must not leak
            # the freshly-allocated blocks: nothing references them
            # yet, so release_slot could never reclaim them
            self.kv_pool.free(own)
            raise
        blocks = alias + own
        self._slot_blocks[slot] = blocks
        self._bt_host[slot, :] = 0
        self._bt_host[slot, :len(blocks)] = blocks
        self._bt_dev = None
        self._slot_rows_ub[slot] = n
        self._slot_prompt_n[slot] = n
        # bind BEFORE the forward so a failure below leaves a state
        # release_slot() fully cleans up (blocks included)
        self._slot_req[slot] = request_id
        self._slot_prompt[slot] = prompt
        bt_row = self._bt_host[slot]
        if c <= 0:
            lp = min(_bucket(n), self.max_prompt_len)
            ids = np.full((1, lp), self.pad, np.int32)
            seg = np.zeros((1, lp), np.int32)
            pos = np.zeros((1, lp), np.int32)
            ids[0, lp - n:] = prompt          # left padding
            seg[0, lp - n:] = 1
            pos[0, lp - n:] = np.arange(n)
            warange = np.arange(lp, dtype=np.int32)
            with tracing.span("serve:prefill", slot=slot,
                              prompt_len=n, bucket=lp, paged=True):
                dev = jax.device_put((ids, seg, pos, bt_row, warange))
                arrays, self.state = self._paged_fill_jit(
                    self.params, self.kv_pool.arrays(), self.state,
                    jnp.int32(slot), *dev)
            self.kv_pool.update(arrays)
            self.last_fill = dict(bucket=lp, prompt_len=n,
                                  cached_len=0, prefilled=n)
            self.fill_stats["prefill_tokens"] += n
        else:
            s = n - c
            ids = np.full((1, s_b), self.pad, np.int32)
            seg = np.zeros((1, s_b), np.int32)
            pos = np.zeros((1, s_b), np.int32)
            ids[0, s_b - s:] = prompt[c:]        # left padding within
            seg[0, s_b - s:] = 1                 # the suffix window
            pos[0, s_b - s:] = c + np.arange(s)
            warange_c = np.arange(c_b, dtype=np.int32)
            with tracing.span("serve:prefill", slot=slot,
                              prompt_len=n, bucket=s_b, cached_len=c,
                              paged=True):
                dev = jax.device_put(
                    (ids, seg, pos, bt_row, warange_c, np.int32(c)))
                ids_d, seg_d, pos_d, bt_d, wc_d, c_d = dev
                arrays, self.state = self._paged_suffix_jit(
                    self.params, self.kv_pool.arrays(), self.state,
                    jnp.int32(slot), bt_d, wc_d, c_d, ids_d, seg_d,
                    pos_d)
            self.kv_pool.update(arrays)
            self.last_fill = dict(bucket=s_b, prompt_len=n,
                                  cached_len=c, prefilled=s)
            self.fill_stats["prefill_tokens"] += s
            self.fill_stats["prefill_tokens_saved"] += c

    # ------------------------------------------------------------------
    def generate_all(self, prompts: List[np.ndarray], key: jax.Array
                     ) -> List[FinishedSequence]:
        """Run the queue to completion; results in request order."""
        queue = list(enumerate(prompts))[::-1]  # pop() takes req 0 first
        results: Dict[int, FinishedSequence] = {}

        while queue or self.n_live:
            for slot in self.free_slots():
                if not queue:
                    break
                rid, p = queue.pop()
                self.fill_slot(slot, rid, p)
            key, sub = jax.random.split(key)
            self.decode_chunk(sub)
            # host sync once per chunk: harvest finished slots
            for fs in self.harvest():
                results[fs.request_id] = fs
        return [results[i] for i in range(len(prompts))]


# ----------------------------------------------------------------------
# jitted pieces
# ----------------------------------------------------------------------
def _prefill_into_slot(cfg, cache_len, moe_constraint, attention_fn,
                       params, state, slot, ids, seg, pos):
    """Batch-1 prefill scattered into `slot`'s cache rows + state."""
    # total_len=cache_len: the prefill cache comes back already padded
    # to the slot's row length (cache_len is round_cache_len-aligned by
    # the constructor, so prefill's own rounding is a no-op).
    hidden, pcache = T.prefill(cfg, params, ids, seg, pos,
                               total_len=cache_len,
                               attention_fn=attention_fn,
                               moe_constraint=moe_constraint)
    lp = ids.shape[1]
    pad_s = cache_len - lp

    cache = dict(state["cache"])
    cache["k"] = cache["k"].at[:, slot].set(pcache["k"][:, 0])
    cache["v"] = cache["v"].at[:, slot].set(pcache["v"][:, 0])
    cache["valid"] = cache["valid"].at[slot].set(
        jnp.pad(seg[0] != 0, (0, pad_s)))
    plen = (seg[0] != 0).sum().astype(jnp.int32)
    cache["length"] = cache["length"].at[slot].set(lp)  # write index
    new = dict(state)
    new["cache"] = cache
    new["last_hidden"] = state["last_hidden"].at[slot].set(hidden[0, -1])
    new["prompt_len"] = state["prompt_len"].at[slot].set(plen)
    new["emitted"] = state["emitted"].at[slot].set(0)
    new["active"] = state["active"].at[slot].set(True)
    new["unfinished"] = state["unfinished"].at[slot].set(True)
    new["hit_eos"] = state["hit_eos"].at[slot].set(False)
    new["out_tokens"] = state["out_tokens"].at[slot].set(
        jnp.full((state["out_tokens"].shape[1],), 0, jnp.int32))
    new["out_logprobs"] = state["out_logprobs"].at[slot].set(0.0)
    new["spec_proposed"] = state["spec_proposed"].at[slot].set(0)
    new["spec_accepted"] = state["spec_accepted"].at[slot].set(0)
    return new


def _extend_rows(cfg, moe_constraint, params, k_all, v_all, valid0,
                 tokens, positions, rows, tok_mask):
    """Multi-token carry extension: run ``m`` new tokens per stream
    through the transformer IN ONE forward against the existing KV
    rows -- the shared primitive under partial prefill (suffix after a
    radix-cache donor) and speculative verify (k drafts + 1 committed
    token). ``decode_step`` is the ``m == 1`` special case of this.

    k_all/v_all: [nl, B, nkv, S, hd] rows (the full slot batch for
    verify; a batch-1 local window for suffix prefill).
    valid0: [B, S] validity BEFORE the new tokens.
    tokens/positions/rows: [B, m]; ``rows`` are the cache rows the new
    tokens write (pre-clamped to S-1 by the caller).
    tok_mask: [B, m] -- False lanes (padding / capped lanes) neither
    write KV nor count; their hidden outputs are garbage and must not
    be read.

    Returns (hidden [B, m, H] after the final norm, k_all, v_all).
    Attention is the plain XLA einsum path (scores masked per query:
    old valid rows plus new rows i <= j); on TPU meshes GSPMD
    partitions it like any other einsum -- the Pallas single-query
    decode kernels stay on the one-token hot path."""
    cdt = jnp.dtype(cfg.compute_dtype)
    b, m = tokens.shape
    s_len = valid0.shape[1]

    x = params["embed"]["wte"].astype(cdt)[tokens]  # [B, m, H]
    if cfg.uses_absolute_position:
        x = x + params["embed"]["wpe"].astype(cdt)[
            positions + cfg.abs_position_embedding_offset]
    if cfg.normalize_embed:
        x = x * jnp.asarray(cfg.hidden_dim ** 0.5, dtype=cdt)

    if cfg.apply_rotary:
        cos, sin = T.rotary_freqs(positions, cfg.head_dim,
                                  cfg.rotary_base, cfg.rotary_scaling,
                                  cfg.rotary_scaling_type,
                                  cfg.n_positions)
    else:
        half = cfg.head_dim // 2
        cos = jnp.ones((b, m, half), jnp.float32)
        sin = jnp.zeros((b, m, half), jnp.float32)

    # per-query attendable rows: everything valid before this call,
    # plus new rows written at lane i <= the query's lane j
    written = ((rows[:, :, None] == jnp.arange(s_len)[None, None, :])
               & tok_mask[:, :, None])                     # [B, m, S]
    upto = jnp.cumsum(written.astype(jnp.int32), axis=1) > 0
    qmask = valid0[:, None, :] | upto
    if cfg.sliding_window is not None:
        idx = jnp.arange(s_len, dtype=jnp.int32)[None, None, :]
        qmask = qmask & ((rows[:, :, None] - idx) < cfg.sliding_window)

    barr = jnp.arange(b)[:, None]
    group = cfg.n_q_heads // cfg.n_kv_heads

    def layer_body(x, k_all, v_all, lp, layer_idx, static_l=None):
        ln1 = T._norm(cfg, x, lp["ln1"]["scale"], lp["ln1"].get("bias"))
        q, k, v = T._qkv(cfg, lp, ln1)  # q [B,m,nq,hd]; k/v [B,m,nkv,hd]
        if cfg.apply_rotary:
            q = T.apply_rotary(q, cos, sin, cfg.rotary_interleaved)
            k = T.apply_rotary(k, cos, sin, cfg.rotary_interleaved)
        l = layer_idx if static_l is None else static_l
        k_l = k_all[l]  # [B, nkv, S, hd]
        v_l = v_all[l]
        # masked scatter of the new rows: padded lanes share clamped
        # row indices, so their writes must keep the existing values
        kw = k.astype(k_l.dtype)
        vw = v.astype(v_l.dtype)
        keep = tok_mask[:, :, None, None]
        cur_k = k_l[barr, :, rows]      # [B, m, nkv, hd]
        cur_v = v_l[barr, :, rows]
        k_l = k_l.at[barr, :, rows].set(jnp.where(keep, kw, cur_k))
        v_l = v_l.at[barr, :, rows].set(jnp.where(keep, vw, cur_v))
        k_all = k_all.at[l].set(k_l)
        v_all = v_all.at[l].set(v_l)
        base = cfg.head_dim ** -0.5 if cfg.scale_attn_weights else 1.0
        if not cfg.scale_attn_by_inverse_layer_idx:
            scale = base
        elif static_l is not None:
            scale = base / (static_l + 1)
        else:
            scale = T._attn_scale(cfg, layer_idx)
        qg = q.reshape(b, m, cfg.n_kv_heads, group, cfg.head_dim)
        scores = jnp.einsum("bmhgd,bhsd->bmhgs", qg, k_l,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(qmask[:, :, None, None, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bmhgs,bhsd->bmhgd",
                          probs.astype(v_l.dtype), v_l)
        proj = attn.reshape(b, m, -1) @ lp["attn"]["wo"].astype(x.dtype)
        if "bo" in lp["attn"]:
            proj = proj + lp["attn"]["bo"].astype(x.dtype)
        x = x + proj
        ln2 = T._norm(cfg, x, lp["ln2"]["scale"], lp["ln2"].get("bias"))
        x = x + T._mlp(cfg, lp, ln2, moe_constraint)
        return x, k_all, v_all

    if cfg.n_layers <= T._DECODE_UNROLL_MAX_LAYERS:
        for li in range(cfg.n_layers):
            lp = jax.tree_util.tree_map(lambda a: a[li],
                                        params["blocks"])
            x, k_all, v_all = layer_body(x, k_all, v_all, lp, li,
                                         static_l=li)
    else:
        def body(carry, layer):
            xc, kc, vc = carry
            lp, layer_idx = layer
            xc, kc, vc = layer_body(xc, kc, vc, lp, layer_idx)
            return (xc, kc, vc), None

        layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
        (x, k_all, v_all), _ = jax.lax.scan(
            body, (x, k_all, v_all), (params["blocks"], layer_ids))
    x = T._norm(cfg, x, params["ln_f"]["scale"],
                params["ln_f"].get("bias"))
    return x, k_all, v_all


def _prefill_suffix_into_slot(cfg, cache_len, moe_constraint, params,
                              state, slot, donor_k, donor_v,
                              donor_valid, ids, seg, pos):
    """Partial prefill for a radix prefix-cache hit: donor KV seeds a
    local window's rows [0, c_b); the left-padded suffix runs
    :func:`_extend_rows` against it (rows [c_b, c_b + s_b)); the
    finished window then scatters into ``slot``'s cache rows. One
    compilation per (c_b, s_b) bucket pair."""
    nl, nkv, c_b, hd = donor_k.shape
    s_b = ids.shape[1]
    win = c_b + s_b
    kdt = state["cache"]["k"].dtype
    local_k = jnp.concatenate(
        [donor_k[:, None].astype(kdt),
         jnp.zeros((nl, 1, nkv, s_b, hd), kdt)], axis=3)
    local_v = jnp.concatenate(
        [donor_v[:, None].astype(kdt),
         jnp.zeros((nl, 1, nkv, s_b, hd), kdt)], axis=3)
    valid0 = jnp.concatenate(
        [donor_valid[None, :], jnp.zeros((1, s_b), bool)], axis=1)
    rows = (c_b + jnp.arange(s_b, dtype=jnp.int32))[None, :]
    tok_mask = seg != 0
    hidden, local_k, local_v = _extend_rows(
        cfg, moe_constraint, params, local_k, local_v, valid0, ids,
        pos, rows, tok_mask)

    full_valid = jnp.zeros((cache_len,), bool)
    full_valid = full_valid.at[:c_b].set(donor_valid)
    full_valid = full_valid.at[c_b:win].set(seg[0] != 0)
    plen = (donor_valid.sum() + (seg[0] != 0).sum()).astype(jnp.int32)

    cache = dict(state["cache"])
    cache["k"] = cache["k"].at[:, slot, :, :win].set(local_k[:, 0])
    cache["v"] = cache["v"].at[:, slot, :, :win].set(local_v[:, 0])
    cache["valid"] = cache["valid"].at[slot].set(full_valid)
    cache["length"] = cache["length"].at[slot].set(win)  # write index
    new = dict(state)
    new["cache"] = cache
    new["last_hidden"] = state["last_hidden"].at[slot].set(
        hidden[0, -1])
    new["prompt_len"] = state["prompt_len"].at[slot].set(plen)
    new["emitted"] = state["emitted"].at[slot].set(0)
    new["active"] = state["active"].at[slot].set(True)
    new["unfinished"] = state["unfinished"].at[slot].set(True)
    new["hit_eos"] = state["hit_eos"].at[slot].set(False)
    new["out_tokens"] = state["out_tokens"].at[slot].set(
        jnp.full((state["out_tokens"].shape[1],), 0, jnp.int32))
    new["out_logprobs"] = state["out_logprobs"].at[slot].set(0.0)
    new["spec_proposed"] = state["spec_proposed"].at[slot].set(0)
    new["spec_accepted"] = state["spec_accepted"].at[slot].set(0)
    return new


# ----------------------------------------------------------------------
# paged (KV-pool) jitted pieces: gather the live window from the pool,
# run the SAME dense compute above on it, scatter written rows back.
# The compute path is therefore byte-identical math to the dense one
# (the fp32 bit-exactness guarantee); the pool only changes where rows
# LIVE, not how they are used. One gather/scatter pair per device call
# (chunk / verify round / fill), amortized over the chunk's steps.
# ----------------------------------------------------------------------
def _paged_window(meta, pool, bt, warange, length, cdt):
    """(flat_rows [B, win], dense cache dict) for the pool-backed
    window: row ``j < length[b]`` of sequence ``b`` is valid (windows
    are compacted -- token ``j`` lives at window row ``j``)."""
    rows = _kvp.window_rows(bt, warange, meta.block_len)
    k, v = _kvp.pool_gather(meta, pool, rows, cdt)
    valid = warange[None, :] < length[:, None]
    return rows, dict(k=k, v=v, valid=valid, length=length)


def _scatter_written(meta, pool, rows, cache, len0, m, mask_extra=None):
    """Write back the rows a chunk appended: window rows
    ``[len0, len0 + m)`` per sequence, masked to the actually-written
    count. Rolled-back (spec-rejected) rows scatter too -- they are
    invalid by ``length`` and will be overwritten, but their block is
    already owned, so this is harmless and keeps the mask simple."""
    win = rows.shape[1]
    j = jnp.arange(m, dtype=jnp.int32)[None, :]
    wrows = jnp.clip(len0[:, None] + j, 0, win - 1)
    mask = (len0[:, None] + j) < win
    if mask_extra is not None:
        mask = mask & mask_extra
    idx = wrows[None, :, None, :, None]
    kw = jnp.take_along_axis(cache["k"], idx, axis=3)
    vw = jnp.take_along_axis(cache["v"], idx, axis=3)
    flat = jnp.take_along_axis(rows, wrows, axis=1)
    return _kvp.pool_scatter(meta, pool, flat, kw, vw, mask)


def _paged_decode_chunk(cfg, g, eos, pad, chunk, moe_constraint, mesh,
                        meta, params, pool, state, bt, warange, key):
    """Paged decode chunk: gather -> dense ``_decode_chunk`` -> scatter
    the <= ``chunk`` new rows per slot back into the pool."""
    cdt = jnp.dtype(cfg.compute_dtype)
    rows, cache = _paged_window(meta, pool, bt, warange,
                                state["length"], cdt)
    st = {k2: v2 for k2, v2 in state.items() if k2 != "length"}
    st["cache"] = cache
    st = _decode_chunk(cfg, g, eos, pad, chunk, moe_constraint, mesh,
                       params, st, key)
    cache = st.pop("cache")
    len0 = state["length"]
    len1 = cache["length"]
    j = jnp.arange(chunk, dtype=jnp.int32)[None, :]
    written = j < (len1 - len0)[:, None]
    pool = _scatter_written(meta, pool, rows, cache, len0, chunk,
                            mask_extra=written)
    st["length"] = len1
    return pool, st


def _paged_verify(cfg, g, eos, k_spec, moe_constraint, meta, params,
                  pool, state, bt, warange, drafts):
    """Paged speculative round: gather -> dense ``_verify_chunk`` ->
    scatter the round's <= k+1 rows per live slot back."""
    cdt = jnp.dtype(cfg.compute_dtype)
    rows, cache = _paged_window(meta, pool, bt, warange,
                                state["length"], cdt)
    st = {k2: v2 for k2, v2 in state.items() if k2 != "length"}
    st["cache"] = cache
    st = _verify_chunk(cfg, g, eos, k_spec, moe_constraint, params,
                       st, drafts)
    cache = st.pop("cache")
    live = (state["active"] & state["unfinished"]
            & (state["emitted"] < g.max_new_tokens))
    pool = _scatter_written(meta, pool, rows, cache, state["length"],
                            1 + k_spec, mask_extra=live[:, None])
    st["length"] = cache["length"]
    return pool, st


def _paged_prefill(cfg, moe_constraint, attention_fn, meta, params,
                   pool, state, slot, ids, seg, pos, bt_row, warange):
    """Full prefill into pool blocks. The batch-1 forward is the SAME
    left-padded bucketed ``T.prefill`` the dense path runs; its rows
    are then COMPACTED on scatter (window row ``p`` holds token ``p``)
    so every sequence shares the position->block-offset invariant the
    radix cache's whole-block aliasing depends on."""
    hidden, pcache = T.prefill(cfg, params, ids, seg, pos,
                               attention_fn=attention_fn,
                               moe_constraint=moe_constraint)
    lp = ids.shape[1]
    blen = meta.block_len
    n = (seg[0] != 0).sum().astype(jnp.int32)
    # prefill put token p at row lp - n + p (left padding); strip it
    src = jnp.clip(warange + (lp - n), 0, pcache["k"].shape[3] - 1)
    kc = pcache["k"][:, 0][:, :, src]            # [nl, nkv, lp, hd]
    vc = pcache["v"][:, 0][:, :, src]
    rows = (bt_row[warange // blen] * blen + warange % blen)[None, :]
    mask = (warange < n)[None, :]
    pool = _kvp.pool_scatter(meta, pool, rows, kc[:, None],
                             vc[:, None], mask)
    new = dict(state)
    new["length"] = state["length"].at[slot].set(n)
    new["last_hidden"] = state["last_hidden"].at[slot].set(hidden[0, -1])
    new["prompt_len"] = state["prompt_len"].at[slot].set(n)
    new["emitted"] = state["emitted"].at[slot].set(0)
    new["active"] = state["active"].at[slot].set(True)
    new["unfinished"] = state["unfinished"].at[slot].set(True)
    new["hit_eos"] = state["hit_eos"].at[slot].set(False)
    new["out_tokens"] = state["out_tokens"].at[slot].set(
        jnp.full((state["out_tokens"].shape[1],), 0, jnp.int32))
    new["out_logprobs"] = state["out_logprobs"].at[slot].set(0.0)
    new["spec_proposed"] = state["spec_proposed"].at[slot].set(0)
    new["spec_accepted"] = state["spec_accepted"].at[slot].set(0)
    return pool, new


def _paged_prefill_suffix(cfg, moe_constraint, meta, params, pool,
                          state, slot, bt_row, warange_c, c, ids, seg,
                          pos):
    """Partial prefill after whole-block aliasing: the donor rows are
    ALREADY in the slot's table (rows [0, c) -- a refcount bump put
    them there, no copy); gather them into a local window, run the
    suffix through :func:`_extend_rows` at window rows [c, c + s),
    and scatter only the suffix rows back. One compile per
    (donor-bucket, suffix-bucket) pair, same ladder as dense."""
    blen = meta.block_len
    c_b = warange_c.shape[0]
    s_b = ids.shape[1]
    cdt = jnp.dtype(cfg.compute_dtype)
    drows = (bt_row[warange_c // blen] * blen
             + warange_c % blen)[None, :]
    dk, dv = _kvp.pool_gather(meta, pool, drows, cdt)
    nl, _, nkv, _, hd = dk.shape
    local_k = jnp.concatenate(
        [dk, jnp.zeros((nl, 1, nkv, s_b, hd), cdt)], axis=3)
    local_v = jnp.concatenate(
        [dv, jnp.zeros((nl, 1, nkv, s_b, hd), cdt)], axis=3)
    valid0 = jnp.concatenate(
        [(warange_c < c)[None, :], jnp.zeros((1, s_b), bool)], axis=1)
    s = (seg[0] != 0).sum().astype(jnp.int32)
    lane = jnp.arange(s_b, dtype=jnp.int32)
    wrow = jnp.clip(c + lane - (s_b - s), 0,
                    c_b + s_b - 1)[None, :]       # suffix target rows
    tok_mask = seg != 0
    hidden, lk, lv = _extend_rows(cfg, moe_constraint, params,
                                  local_k, local_v, valid0, ids, pos,
                                  wrow, tok_mask)
    # window coords == local coords for the suffix (donor is [0, c)
    # in both): read the written lanes back out and scatter them into
    # the slot's own (freshly allocated, block-aligned) pool rows
    idx = wrow[None, :, None, :, None]
    kw = jnp.take_along_axis(lk, idx, axis=3)
    vw = jnp.take_along_axis(lv, idx, axis=3)
    flat = bt_row[wrow[0] // blen] * blen + wrow[0] % blen
    pool = _kvp.pool_scatter(meta, pool, flat[None, :], kw, vw,
                             tok_mask)
    plen = (c + s).astype(jnp.int32)
    new = dict(state)
    new["length"] = state["length"].at[slot].set(plen)
    new["last_hidden"] = state["last_hidden"].at[slot].set(
        hidden[0, -1])
    new["prompt_len"] = state["prompt_len"].at[slot].set(plen)
    new["emitted"] = state["emitted"].at[slot].set(0)
    new["active"] = state["active"].at[slot].set(True)
    new["unfinished"] = state["unfinished"].at[slot].set(True)
    new["hit_eos"] = state["hit_eos"].at[slot].set(False)
    new["out_tokens"] = state["out_tokens"].at[slot].set(
        jnp.full((state["out_tokens"].shape[1],), 0, jnp.int32))
    new["out_logprobs"] = state["out_logprobs"].at[slot].set(0.0)
    new["spec_proposed"] = state["spec_proposed"].at[slot].set(0)
    new["spec_accepted"] = state["spec_accepted"].at[slot].set(0)
    return pool, new


def _verify_chunk(cfg, g, eos, k_spec, moe_constraint, params, state,
                  drafts):
    """One speculative round: commit the greedy token from
    ``last_hidden`` (free -- no forward needed), then verify the k
    host-drafted tokens behind it in ONE :func:`_extend_rows` forward.
    Greedy-exact: a draft is accepted iff it equals the argmax the
    plain decode loop would have produced at that position, so the
    emitted stream is token-for-token identical to non-speculative
    greedy decoding; rejected tails are rolled back (rows invalidated,
    ``length`` rewound)."""
    nm = g.max_new_tokens
    m = 1 + k_spec
    st = state
    cache = st["cache"]
    s_len = cache["valid"].shape[1]
    b = drafts.shape[0]
    barr = jnp.arange(b)

    live = st["active"] & st["unfinished"] & (st["emitted"] < nm)

    # the committed token: identical math to _decode_chunk's body()
    logits0 = T.lm_logits(cfg, params, st["last_hidden"]) \
        .astype(jnp.float32)
    if eos is not None and g.min_new_tokens > 0:
        suppress = ((st["emitted"] < g.min_new_tokens)[:, None]
                    & (jnp.arange(logits0.shape[-1])[None, :] == eos))
        logits0 = jnp.where(suppress, NEG_INF, logits0)
    f0 = jnp.argmax(logits0, -1).astype(jnp.int32)
    logp0 = jnp.take_along_axis(jax.nn.log_softmax(logits0, -1),
                                f0[:, None], -1)[:, 0]

    tokens_seq = jnp.concatenate(
        [f0[:, None], drafts.astype(jnp.int32)], axis=1)  # [B, m]
    j = jnp.arange(m, dtype=jnp.int32)[None, :]
    allowed = jnp.clip(nm - st["emitted"], 0, m)           # [B]
    write_mask = live[:, None] & (j < allowed[:, None])
    rows = jnp.minimum(st["cache"]["length"][:, None] + j, s_len - 1)
    positions = st["prompt_len"][:, None] + st["emitted"][:, None] + j

    hidden, k_all, v_all = _extend_rows(
        cfg, moe_constraint, params, cache["k"], cache["v"],
        cache["valid"], tokens_seq, positions, rows, write_mask)

    logits = T.lm_logits(cfg, params, hidden).astype(jnp.float32)
    if eos is not None and g.min_new_tokens > 0:
        # position j's candidate is sampled with emitted0 + j + 1
        # tokens already out -- same suppression rule as the loop
        sup = ((st["emitted"][:, None] + j + 1 < g.min_new_tokens)
               [:, :, None]
               & (jnp.arange(logits.shape[-1])[None, None, :] == eos))
        logits = jnp.where(sup, NEG_INF, logits)
    cand = jnp.argmax(logits, -1).astype(jnp.int32)        # [B, m]
    # draft i (tokens_seq[:, i+1]) was sampled from position i's
    # logits (the state after consuming tokens_seq[0..i])
    logp_steps = jnp.take_along_axis(
        jax.nn.log_softmax(logits[:, :-1], -1),
        tokens_seq[:, 1:, None], -1)[:, :, 0]              # [B, k]
    # shift: draft i must equal the model's choice AFTER consuming
    # tokens_seq[0..i] (cand[:, i]); acceptance is prefix-closed
    draft_ok = tokens_seq[:, 1:] == cand[:, :-1]
    acc = jnp.cumprod(draft_ok.astype(jnp.int32), axis=1)
    n_emit = jnp.minimum(acc.sum(1) + 1, allowed)
    n_emit = jnp.where(live, n_emit, 0)
    hit_now = jnp.zeros((b,), bool)
    if eos is not None:
        is_eos = (tokens_seq == eos) & (j < n_emit[:, None])
        hit_now = is_eos.any(axis=1)
        first_eos = jnp.argmax(is_eos, axis=1)
        n_emit = jnp.where(hit_now,
                           jnp.minimum(n_emit, first_eos + 1), n_emit)

    emit_mask = j < n_emit[:, None]
    lps = jnp.concatenate([logp0[:, None], logp_steps], axis=1)
    # write emitted lanes into out[emitted0 : emitted0 + n_emit]
    # as a gather + where over the whole output row -- a scatter
    # would clamp out-of-range lanes onto live indices and the
    # duplicate-index write order is unspecified
    p = jnp.arange(st["out_tokens"].shape[1], dtype=jnp.int32)[None, :]
    rel = p - st["emitted"][:, None]                       # [B, nm]
    take = (rel >= 0) & (rel < n_emit[:, None])
    gidx = jnp.clip(rel, 0, m - 1)
    out_tokens = jnp.where(
        take, jnp.take_along_axis(tokens_seq, gidx, axis=1),
        st["out_tokens"])
    out_logprobs = jnp.where(
        take, jnp.take_along_axis(lps, gidx, axis=1),
        st["out_logprobs"])

    emitted = st["emitted"] + n_emit
    unfinished = st["unfinished"] & ~hit_now & (emitted < nm)
    hit_eos = st["hit_eos"] | hit_now

    # cache rollback: only the emitted lanes' rows stay valid; the
    # rejected tail's rows are overwritten by the next rounds anyway
    kept = ((rows[:, :, None] == jnp.arange(s_len)[None, None, :])
            & emit_mask[:, :, None]).any(axis=1)
    valid = cache["valid"] | kept
    length = cache["length"] + n_emit
    last_hidden = jnp.where(
        live[:, None],
        hidden[barr, jnp.maximum(n_emit - 1, 0)], st["last_hidden"])

    new_cache = dict(cache, k=k_all, v=v_all, valid=valid,
                     length=length)
    return dict(
        st, cache=new_cache, last_hidden=last_hidden, emitted=emitted,
        unfinished=unfinished, hit_eos=hit_eos, out_tokens=out_tokens,
        out_logprobs=out_logprobs,
        spec_proposed=st["spec_proposed"]
        + jnp.where(live, k_spec, 0).astype(jnp.int32),
        spec_accepted=st["spec_accepted"]
        + jnp.maximum(n_emit - 1, 0).astype(jnp.int32))


def _decode_chunk(cfg, g, eos, pad, chunk, moe_constraint, mesh, params,
                  state, key):
    """`chunk` decode steps over every slot (inactive/finished slots
    keep stepping on pad tokens but write nothing)."""
    nm = g.max_new_tokens

    def body(st, k):
        live = st["active"] & st["unfinished"] \
            & (st["emitted"] < nm)
        logits = T.lm_logits(cfg, params, st["last_hidden"]) \
            .astype(jnp.float32)
        if eos is not None and g.min_new_tokens > 0:
            suppress = ((st["emitted"] < g.min_new_tokens)[:, None]
                        & (jnp.arange(logits.shape[-1])[None, :] == eos))
            logits = jnp.where(suppress, NEG_INF, logits)
        if g.greedy:
            warped = logits
            tokens = jnp.argmax(warped, -1).astype(jnp.int32)
        else:
            warped = top_k_top_p_logits(logits / g.temperature,
                                        g.top_k, g.top_p)
            tokens = jax.random.categorical(k, warped, -1) \
                .astype(jnp.int32)
        logp = jax.nn.log_softmax(warped, -1)
        logprob = jnp.take_along_axis(logp, tokens[:, None], -1)[:, 0]
        tokens = jnp.where(live, tokens, pad)

        idx = jnp.minimum(st["emitted"], nm - 1)
        rows = jnp.arange(tokens.shape[0])
        out_tokens = jnp.where(
            live[:, None],
            st["out_tokens"].at[rows, idx].set(tokens),
            st["out_tokens"])
        out_logprobs = jnp.where(
            live[:, None],
            st["out_logprobs"].at[rows, idx].set(logprob),
            st["out_logprobs"])
        emitted = st["emitted"] + live.astype(jnp.int32)
        unfinished = st["unfinished"]
        hit_eos = st["hit_eos"]
        if eos is not None:
            hit_eos = hit_eos | (live & (tokens == eos))
            unfinished = unfinished & (~live | (tokens != eos))
        unfinished = unfinished & (emitted < nm)

        pos = st["prompt_len"] + st["emitted"]
        new_hidden, cache = T.decode_step(cfg, params, st["cache"],
                                          tokens, pos, moe_constraint,
                                          mesh=mesh)
        st = dict(st, cache=cache, last_hidden=new_hidden,
                  emitted=emitted, unfinished=unfinished,
                  hit_eos=hit_eos, out_tokens=out_tokens,
                  out_logprobs=out_logprobs)
        return st, None

    keys = jax.random.split(key, chunk)

    # Early exit within the chunk: when every slot has finished (EOS
    # or max tokens), the remaining steps would decode pads and write
    # nothing -- stop instead of burning them (mirrors the batch
    # path's EOS early-exit while_loop, engine/generation.py).
    def w_cond(c):
        i, st = c
        live_any = jnp.any(st["active"] & st["unfinished"]
                           & (st["emitted"] < nm))
        return (i < chunk) & live_any

    def w_body(c):
        i, st = c
        st, _ = body(st, keys[i])
        return (i + 1, st)

    _, state = jax.lax.while_loop(w_cond, w_body,
                                  (jnp.int32(0), state))
    return state
