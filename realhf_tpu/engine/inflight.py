"""Continuous (inflight) batching for generation.

TPU-native counterpart of the reference's InflightBatchingGenerator
prototype (``real_llm_generate.py:664``, shipped unwired there): a
fixed set of decode SLOTS runs a jitted chunked decode loop; whenever
a slot's sequence finishes (EOS or max_new_tokens), the host harvests
it and refills the slot by prefilling the next queued prompt into that
slot's KV-cache rows, while the other slots keep decoding. Short
sequences therefore never wait for the batch's longest one -- the
throughput property vLLM-style serving is built on -- while every
device computation keeps static shapes:

- ``decode_chunk``: `lax.scan` over ``chunk_size`` steps for all slots
  (one compiled program, reused forever),
- ``prefill_into_slot``: batch-1 prefill at a bucketed prompt length,
  scattered into the slot's cache rows (one compilation per bucket).

Host<->device sync happens once per chunk, not per token. The
logits-mask replay of PPO is intentionally unsupported here (use the
batch ``generate`` path); inflight mode targets throughput-oriented
rollout generation (GRPO / ReMax / gen experiments).
"""

import dataclasses
import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from realhf_tpu.models import transformer as T
from realhf_tpu.models.config import TransformerConfig
from realhf_tpu.obs import tracing
from realhf_tpu.ops.sampling import (
    NEG_INF,
    GenerationHyperparameters,
    top_k_top_p_logits,
)


def _bucket(n: int, buckets=(64, 128, 256, 512, 1024, 2048, 4096)) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n


@dataclasses.dataclass
class FinishedSequence:
    request_id: int
    tokens: np.ndarray     # [len] generated ids (incl. EOS if emitted)
    logprobs: np.ndarray   # [len]
    no_eos: bool           # True iff the sequence never emitted EOS
                           # (hit max_new_tokens), matching the batch
                           # path's seq_no_eos_mask semantics.


class InflightBatchingGenerator:
    """Slot-machine generation over a queue of prompts."""

    def __init__(self, cfg: TransformerConfig, params,
                 gconfig: GenerationHyperparameters,
                 *, n_slots: int, max_prompt_len: int,
                 eos_token_id: Optional[int], pad_token_id: int,
                 chunk_size: int = 32, moe_constraint=None,
                 mesh=None, attention_fn=None):
        if not gconfig.force_no_logits_mask:
            raise ValueError(
                "inflight batching does not produce the PPO logits "
                "mask; set force_no_logits_mask=True or use the batch "
                "generate path.")
        self.cfg = cfg
        self.params = params
        self.g = gconfig
        self.n_slots = n_slots
        self.eos = eos_token_id
        self.pad = pad_token_id
        self.chunk = chunk_size
        self.cache_len = T.round_cache_len(
            max_prompt_len + gconfig.max_new_tokens)
        # jax.jit retraces per prompt-bucket shape on its own; one
        # jitted function covers every bucket.
        self._prefill = jax.jit(functools.partial(
            _prefill_into_slot, self.cfg, self.cache_len,
            moe_constraint, attention_fn))

        nm = gconfig.max_new_tokens
        self.state = dict(
            cache=T.init_kv_cache(cfg, n_slots, self.cache_len),
            last_hidden=jnp.zeros((n_slots, cfg.hidden_dim),
                                  jnp.dtype(cfg.compute_dtype)),
            prompt_len=jnp.zeros((n_slots,), jnp.int32),
            emitted=jnp.zeros((n_slots,), jnp.int32),
            active=jnp.zeros((n_slots,), bool),
            unfinished=jnp.zeros((n_slots,), bool),
            hit_eos=jnp.zeros((n_slots,), bool),
            out_tokens=jnp.full((n_slots, nm), pad_token_id, jnp.int32),
            out_logprobs=jnp.zeros((n_slots, nm), jnp.float32),
        )
        self._slot_req = [-1] * n_slots  # host: request id per slot

        self._decode_chunk = jax.jit(functools.partial(
            _decode_chunk, cfg, gconfig, eos_token_id, pad_token_id,
            chunk_size, moe_constraint, mesh))

    # ------------------------------------------------------------------
    # Slot-level step API. The serving subsystem
    # (``realhf_tpu/serving/scheduler.py``) drives these directly to
    # interleave admission, decoding, and harvesting at iteration
    # granularity; ``generate_all`` below is the run-to-completion
    # composition of the same primitives.
    # ------------------------------------------------------------------
    def free_slots(self) -> List[int]:
        """Slot indices with no request bound to them."""
        return [s for s, r in enumerate(self._slot_req) if r < 0]

    @property
    def n_live(self) -> int:
        """Slots currently bound to a request (decoding or awaiting
        harvest)."""
        return sum(1 for r in self._slot_req if r >= 0)

    def decode_chunk(self, key: jax.Array):
        """Advance every live slot by up to ``chunk_size`` decode
        steps (one host<->device sync)."""
        self.state = self._decode_chunk(self.params, self.state, key)

    def swap_params(self, params):
        """Hot-swap the weights used from the next decode/prefill on.
        Safe between ``decode_chunk`` calls: the jitted programs take
        params as an argument, so no recompilation happens as long as
        shapes/dtypes match."""
        self.params = params

    def release_slot(self, slot: int):
        """Abort the sequence in ``slot`` (cancellation/eviction): the
        slot immediately becomes free and the partial output is
        dropped."""
        self._slot_req[slot] = -1
        self.state["active"] = self.state["active"].at[slot].set(False)

    def _host_view(self) -> Dict[str, np.ndarray]:
        """ONE bundled D2H fetch of every per-slot output/status
        array. Per-slot ``np.asarray`` reads pay a blocking sync
        round-trip each (~0.1s fixed latency per transfer on a
        relayed/tunneled platform); harvesting N finished slots that
        way costs 4N transfers per chunk -- the decode hot path's
        dominant host overhead (docs/perf.md). The bundle is a few
        n_slots x max_new_tokens int/float arrays, so downloading all
        of it beats per-slot slicing as soon as more than one value is
        read."""
        return jax.device_get({
            k: self.state[k]
            for k in ("active", "unfinished", "emitted", "hit_eos",
                      "out_tokens", "out_logprobs")})

    def snapshot_slot(self, slot: int):
        """(tokens_so_far, logprobs_so_far) of the sequence in
        ``slot`` -- the incremental-streaming read. One device sync;
        use :meth:`snapshot_slots` to read several slots per chunk."""
        return self.snapshot_slots([slot])[slot]

    def snapshot_slots(self, slots: List[int]) -> Dict[int, tuple]:
        """slot -> (tokens_so_far, logprobs_so_far) for every
        requested slot via ONE bundled device fetch (the serving
        scheduler streams every live slot after each chunk; per-slot
        reads would pay one sync round-trip each)."""
        if not slots:
            return {}
        host = self._host_view()
        out: Dict[int, tuple] = {}
        for slot in slots:
            n = int(host["emitted"][slot])
            out[slot] = (host["out_tokens"][slot, :n],
                         host["out_logprobs"][slot, :n])
        return out

    def harvest(self) -> List[FinishedSequence]:
        """Collect every finished sequence and free its slot (one
        bundled host transfer, not four per finished slot)."""
        out: List[FinishedSequence] = []
        if self.n_live == 0:
            return out
        host = self._host_view()
        for slot in range(self.n_slots):
            rid = self._slot_req[slot]
            if rid < 0 or (host["active"][slot]
                           and host["unfinished"][slot]):
                continue
            n = int(host["emitted"][slot])
            out.append(FinishedSequence(
                request_id=rid,
                tokens=host["out_tokens"][slot, :n],
                logprobs=host["out_logprobs"][slot, :n],
                no_eos=not bool(host["hit_eos"][slot])))
            self.release_slot(slot)
        return out

    @property
    def max_prompt_len(self) -> int:
        """Longest admissible prompt: the cache row minus the decode
        budget. Admission layers (serving.RequestQueue) check this so
        oversized prompts are rejected before reaching a slot."""
        return self.cache_len - self.g.max_new_tokens

    # ------------------------------------------------------------------
    def fill_slot(self, slot: int, request_id: int,
                  prompt: np.ndarray):
        max_prompt = self.max_prompt_len
        if len(prompt) > max_prompt:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds max_prompt_len "
                f"{max_prompt}")
        lp = min(_bucket(len(prompt)), max_prompt)
        ids = np.full((1, lp), self.pad, np.int32)
        seg = np.zeros((1, lp), np.int32)
        pos = np.zeros((1, lp), np.int32)
        ids[0, lp - len(prompt):] = prompt          # left padding
        seg[0, lp - len(prompt):] = 1
        pos[0, lp - len(prompt):] = np.arange(len(prompt))
        # one bundled upload (a relayed platform pays fixed latency
        # per transfer; see Engine._globalize_tree). `slot` keeps its
        # host int for the list index below -- indexing with a device
        # scalar would force a blocking D2H readback per fill.
        with tracing.span("serve:prefill", slot=slot,
                          prompt_len=len(prompt), bucket=lp):
            dev_slot, ids, seg, pos = jax.device_put((slot, ids, seg,
                                                      pos))
            self.state = self._prefill(self.params, self.state,
                                       dev_slot, ids, seg, pos)
        self._slot_req[slot] = request_id

    # ------------------------------------------------------------------
    def generate_all(self, prompts: List[np.ndarray], key: jax.Array
                     ) -> List[FinishedSequence]:
        """Run the queue to completion; results in request order."""
        queue = list(enumerate(prompts))[::-1]  # pop() takes req 0 first
        results: Dict[int, FinishedSequence] = {}

        while queue or self.n_live:
            for slot in self.free_slots():
                if not queue:
                    break
                rid, p = queue.pop()
                self.fill_slot(slot, rid, p)
            key, sub = jax.random.split(key)
            self.decode_chunk(sub)
            # host sync once per chunk: harvest finished slots
            for fs in self.harvest():
                results[fs.request_id] = fs
        return [results[i] for i in range(len(prompts))]


# ----------------------------------------------------------------------
# jitted pieces
# ----------------------------------------------------------------------
def _prefill_into_slot(cfg, cache_len, moe_constraint, attention_fn,
                       params, state, slot, ids, seg, pos):
    """Batch-1 prefill scattered into `slot`'s cache rows + state."""
    # total_len=cache_len: the prefill cache comes back already padded
    # to the slot's row length (cache_len is round_cache_len-aligned by
    # the constructor, so prefill's own rounding is a no-op).
    hidden, pcache = T.prefill(cfg, params, ids, seg, pos,
                               total_len=cache_len,
                               attention_fn=attention_fn,
                               moe_constraint=moe_constraint)
    lp = ids.shape[1]
    pad_s = cache_len - lp

    cache = dict(state["cache"])
    cache["k"] = cache["k"].at[:, slot].set(pcache["k"][:, 0])
    cache["v"] = cache["v"].at[:, slot].set(pcache["v"][:, 0])
    cache["valid"] = cache["valid"].at[slot].set(
        jnp.pad(seg[0] != 0, (0, pad_s)))
    plen = (seg[0] != 0).sum().astype(jnp.int32)
    cache["length"] = cache["length"].at[slot].set(lp)  # write index
    new = dict(state)
    new["cache"] = cache
    new["last_hidden"] = state["last_hidden"].at[slot].set(hidden[0, -1])
    new["prompt_len"] = state["prompt_len"].at[slot].set(plen)
    new["emitted"] = state["emitted"].at[slot].set(0)
    new["active"] = state["active"].at[slot].set(True)
    new["unfinished"] = state["unfinished"].at[slot].set(True)
    new["hit_eos"] = state["hit_eos"].at[slot].set(False)
    new["out_tokens"] = state["out_tokens"].at[slot].set(
        jnp.full((state["out_tokens"].shape[1],), 0, jnp.int32))
    new["out_logprobs"] = state["out_logprobs"].at[slot].set(0.0)
    return new


def _decode_chunk(cfg, g, eos, pad, chunk, moe_constraint, mesh, params,
                  state, key):
    """`chunk` decode steps over every slot (inactive/finished slots
    keep stepping on pad tokens but write nothing)."""
    nm = g.max_new_tokens

    def body(st, k):
        live = st["active"] & st["unfinished"] \
            & (st["emitted"] < nm)
        logits = T.lm_logits(cfg, params, st["last_hidden"]) \
            .astype(jnp.float32)
        if eos is not None and g.min_new_tokens > 0:
            suppress = ((st["emitted"] < g.min_new_tokens)[:, None]
                        & (jnp.arange(logits.shape[-1])[None, :] == eos))
            logits = jnp.where(suppress, NEG_INF, logits)
        if g.greedy:
            warped = logits
            tokens = jnp.argmax(warped, -1).astype(jnp.int32)
        else:
            warped = top_k_top_p_logits(logits / g.temperature,
                                        g.top_k, g.top_p)
            tokens = jax.random.categorical(k, warped, -1) \
                .astype(jnp.int32)
        logp = jax.nn.log_softmax(warped, -1)
        logprob = jnp.take_along_axis(logp, tokens[:, None], -1)[:, 0]
        tokens = jnp.where(live, tokens, pad)

        idx = jnp.minimum(st["emitted"], nm - 1)
        rows = jnp.arange(tokens.shape[0])
        out_tokens = jnp.where(
            live[:, None],
            st["out_tokens"].at[rows, idx].set(tokens),
            st["out_tokens"])
        out_logprobs = jnp.where(
            live[:, None],
            st["out_logprobs"].at[rows, idx].set(logprob),
            st["out_logprobs"])
        emitted = st["emitted"] + live.astype(jnp.int32)
        unfinished = st["unfinished"]
        hit_eos = st["hit_eos"]
        if eos is not None:
            hit_eos = hit_eos | (live & (tokens == eos))
            unfinished = unfinished & (~live | (tokens != eos))
        unfinished = unfinished & (emitted < nm)

        pos = st["prompt_len"] + st["emitted"]
        new_hidden, cache = T.decode_step(cfg, params, st["cache"],
                                          tokens, pos, moe_constraint,
                                          mesh=mesh)
        st = dict(st, cache=cache, last_hidden=new_hidden,
                  emitted=emitted, unfinished=unfinished,
                  hit_eos=hit_eos, out_tokens=out_tokens,
                  out_logprobs=out_logprobs)
        return st, None

    keys = jax.random.split(key, chunk)

    # Early exit within the chunk: when every slot has finished (EOS
    # or max tokens), the remaining steps would decode pads and write
    # nothing -- stop instead of burning them (mirrors the batch
    # path's EOS early-exit while_loop, engine/generation.py).
    def w_cond(c):
        i, st = c
        live_any = jnp.any(st["active"] & st["unfinished"]
                           & (st["emitted"] < nm))
        return (i < chunk) & live_any

    def w_body(c):
        i, st = c
        st, _ = body(st, keys[i])
        return (i + 1, st)

    _, state = jax.lax.while_loop(w_cond, w_body,
                                  (jnp.int32(0), state))
    return state
