"""Optimizer construction (optax) + LR schedules.

Parity with reference ``realhf/api/quickstart/model.py:62``
(OptimizerConfig) and ``base/timeutil.py:118-216`` (LR schedulers) +
Megatron's OptimizerParamScheduler usage (backend/megatron.py:158).
The reference's ZeRO-1 DistributedOptimizer is unnecessary machinery
here: optimizer state is a pytree that shards exactly like params
(GSPMD), and can additionally be sharded over the DP axis.
"""

import dataclasses
from typing import Optional

import optax


@dataclasses.dataclass
class OptimizerConfig:
    """Mirrors reference OptimizerConfig field-by-field (type "empty"
    means no optimizer -- inference-only model)."""
    type: str = "adam"  # adam | empty
    lr: float = 1e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-5
    min_lr_ratio: float = 0.0
    lr_scheduler_type: str = "cosine"  # linear | cosine | constant
    warmup_steps_proportion: float = 0.02
    gradient_clipping: float = 1.0
    # fp16 loss scaling is irrelevant on TPU (bf16 training); kept for
    # config-surface parity and ignored.
    initial_loss_scale: float = 2 ** 32
    offload: bool = False


def lr_schedule(cfg: OptimizerConfig, total_steps: int) -> optax.Schedule:
    warmup = int(cfg.warmup_steps_proportion * total_steps)
    decay_steps = max(1, total_steps - warmup)
    end = cfg.lr * cfg.min_lr_ratio
    if cfg.lr_scheduler_type == "constant":
        decay = optax.constant_schedule(cfg.lr)
    elif cfg.lr_scheduler_type == "linear":
        decay = optax.linear_schedule(cfg.lr, end, decay_steps)
    elif cfg.lr_scheduler_type == "cosine":
        alpha = cfg.min_lr_ratio
        decay = optax.cosine_decay_schedule(cfg.lr, decay_steps, alpha=alpha)
    else:
        raise NotImplementedError(cfg.lr_scheduler_type)
    if warmup <= 0:
        # no warmup: the FIRST step must already use the full lr
        # (linear_schedule(0, lr, 1) would silently zero it out)
        return decay
    return optax.join_schedules(
        [optax.linear_schedule(0.0, cfg.lr, warmup), decay], [warmup])


def make_optimizer(cfg: OptimizerConfig,
                   total_steps: Optional[int] = None
                   ) -> optax.GradientTransformation:
    if cfg.type == "empty":
        return optax.identity()
    if cfg.type != "adam":
        raise NotImplementedError(f"Optimizer type {cfg.type}")
    sched = lr_schedule(cfg, total_steps or 10 ** 9)
    chain = []
    if cfg.gradient_clipping and cfg.gradient_clipping > 0:
        chain.append(optax.clip_by_global_norm(cfg.gradient_clipping))
    # Decay only matrix-shaped params (norm scales/biases excluded),
    # matching Megatron's no-weight-decay param groups.
    def decay_mask(params):
        import jax
        return jax.tree.map(lambda p: p.ndim >= 2, params)

    chain.append(optax.adamw(
        learning_rate=sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
        weight_decay=cfg.weight_decay, mask=decay_mask))
    return optax.chain(*chain)
