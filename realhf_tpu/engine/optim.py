"""Optimizer construction (optax) + LR schedules.

Parity with reference ``realhf/api/quickstart/model.py:62``
(OptimizerConfig) and ``base/timeutil.py:118-216`` (LR schedulers) +
Megatron's OptimizerParamScheduler usage (backend/megatron.py:158).
The reference's ZeRO-1 DistributedOptimizer is unnecessary machinery
here: optimizer state is a pytree that shards exactly like params
(GSPMD), and can additionally be sharded over the DP axis.
"""

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass
class OptimizerConfig:
    """Mirrors reference OptimizerConfig field-by-field (type "empty"
    means no optimizer -- inference-only model)."""
    type: str = "adam"  # adam | empty
    lr: float = 1e-5
    weight_decay: float = 0.05
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-5
    min_lr_ratio: float = 0.0
    lr_scheduler_type: str = "cosine"  # linear | cosine | constant
    warmup_steps_proportion: float = 0.02
    gradient_clipping: float = 1.0
    # fp16 loss scaling is irrelevant on TPU (bf16 training); kept for
    # config-surface parity and ignored.
    initial_loss_scale: float = 2 ** 32
    # Keep optimizer state on host between train steps (reference
    # DeepSpeed zero-offload, deepspeed.py:445): frees
    # master+moments HBM for colocated MFCs at the cost of a
    # host<->device round trip per step (engine.train_batch).
    offload: bool = False
    # ZeRO-1-equivalent optimizer-state sharding over the DP axis
    # (reference Megatron DistributedOptimizer / DeepSpeed zero_stage=1,
    # always on in the reference's Megatron backend). Adam moments
    # shard as params x DATA; disable to replicate moments across DP.
    zero1: bool = True


def lr_schedule(cfg: OptimizerConfig, total_steps: int) -> optax.Schedule:
    warmup = int(cfg.warmup_steps_proportion * total_steps)
    decay_steps = max(1, total_steps - warmup)
    end = cfg.lr * cfg.min_lr_ratio
    if cfg.lr_scheduler_type == "constant":
        decay = optax.constant_schedule(cfg.lr)
    elif cfg.lr_scheduler_type == "linear":
        decay = optax.linear_schedule(cfg.lr, end, decay_steps)
    elif cfg.lr_scheduler_type == "cosine":
        alpha = cfg.min_lr_ratio
        decay = optax.cosine_decay_schedule(cfg.lr, decay_steps, alpha=alpha)
    else:
        raise NotImplementedError(cfg.lr_scheduler_type)
    if warmup <= 0:
        # no warmup: the FIRST step must already use the full lr
        # (linear_schedule(0, lr, 1) would silently zero it out)
        return decay
    return optax.join_schedules(
        [optax.linear_schedule(0.0, cfg.lr, warmup), decay], [warmup])


class MasterWeightsState(NamedTuple):
    """fp32 master copy + the wrapped optimizer's state. Both live in
    the optimizer state pytree, so ZeRO-1 shards them over DP
    (models/sharding.py:opt_state_shardings) -- the reference's
    Megatron DistributedOptimizer layout (megatron.py:823-940: bf16
    weights everywhere, fp32 master + moments sharded across DP)."""
    master: Any
    inner: Any


def with_master_weights(inner: optax.GradientTransformation
                        ) -> optax.GradientTransformation:
    """Mixed-precision wrapper: params stay in their compute dtype
    (bf16); the update runs in fp32 against a master copy kept in the
    state. The emitted update is the fp32 delta ``new_master - p``, so
    ``optax.apply_updates`` (which adds in promoted fp32 then casts to
    the param dtype) lands exactly ``round_bf16(new_master)``."""

    def init(params):
        master = jax.tree.map(lambda p: jnp.asarray(p, jnp.float32),
                              params)
        return MasterWeightsState(master, inner.init(master))

    def update(grads, state, params=None):
        assert params is not None, "master-weights update needs params"
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        upd, inner_state = inner.update(g32, state.inner, state.master)
        new_master = optax.apply_updates(state.master, upd)
        delta = jax.tree.map(
            lambda nm, p: nm - p.astype(jnp.float32), new_master, params)
        return delta, MasterWeightsState(new_master, inner_state)

    return optax.GradientTransformation(init, update)


def make_optimizer(cfg: OptimizerConfig,
                   total_steps: Optional[int] = None,
                   master_weights: bool = False
                   ) -> optax.GradientTransformation:
    if cfg.type == "empty":
        return optax.identity()
    if cfg.type != "adam":
        raise NotImplementedError(f"Optimizer type {cfg.type}")
    sched = lr_schedule(cfg, total_steps or 10 ** 9)
    chain = []
    if cfg.gradient_clipping and cfg.gradient_clipping > 0:
        chain.append(optax.clip_by_global_norm(cfg.gradient_clipping))
    # Decay only matrix-shaped params (norm scales/biases excluded),
    # matching Megatron's no-weight-decay param groups.
    def decay_mask(params):
        return jax.tree.map(lambda p: p.ndim >= 2, params)

    chain.append(optax.adamw(
        learning_rate=sched, b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps,
        weight_decay=cfg.weight_decay, mask=decay_mask))
    tx = optax.chain(*chain)
    if master_weights:
        tx = with_master_weights(tx)
    return tx
