"""Prompt-lookup (n-gram) drafting for speculative decoding.

The cheapest useful drafter (Saxena 2023 "prompt lookup decoding";
the self-speculative family of Leviathan et al. 2023): instead of a
small draft model, propose the continuation that followed the SAME
recent n-gram earlier in the request's own prompt + generated
history. On extractive/templated workloads (summarization, code
edits, RAG with quoted context) the model frequently copies spans
from its context, so this pure-host drafter reaches useful accept
rates at zero device cost. Proposals are *guesses*: the verify pass
(``engine/inflight.py::_verify_chunk``) accepts exactly the tokens
greedy decoding would have produced, so a bad drafter only costs
wasted verify lanes, never correctness.
"""

from typing import Optional

import numpy as np


class NGramDrafter:
    """Propose ``k`` draft tokens by prompt lookup.

    Tries the longest suffix n-gram first (``max_ngram`` down to
    ``min_ngram``): find its most recent *earlier* occurrence in the
    history and propose the ``k`` tokens that followed it. With no
    match anywhere, falls back to repeating the last token (a decent
    guess for runs/whitespace, free to verify).
    """

    def __init__(self, k: int, max_ngram: int = 3, min_ngram: int = 1,
                 fallback_token: Optional[int] = None):
        if k <= 0:
            raise ValueError("drafter k must be positive")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.k = k
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.fallback_token = fallback_token

    def propose(self, history: np.ndarray) -> np.ndarray:
        """history: [n] int token ids (prompt + generated so far).
        Returns [k] int32 draft tokens."""
        h = np.asarray(history).reshape(-1)
        n = len(h)
        out = np.empty((self.k,), np.int32)
        if n == 0:
            out[:] = 0 if self.fallback_token is None \
                else self.fallback_token
            return out
        for ng in range(min(self.max_ngram, n - 1), self.min_ngram - 1,
                        -1):
            tail = h[n - ng:]
            # most recent earlier occurrence of the suffix n-gram
            # (vectorized sliding-window compare; the final window is
            # the suffix itself, excluded)
            wins = np.lib.stride_tricks.sliding_window_view(h, ng)
            starts = np.flatnonzero((wins[:-1] == tail).all(axis=1))
            if len(starts) == 0:
                continue
            start = int(starts[-1])
            cont = h[start + ng:start + ng + self.k]
            if len(cont) == 0:
                continue  # nothing follows the match
            out[:len(cont)] = cont
            if len(cont) < self.k:
                out[len(cont):] = cont[-1]
            return out
        fb = h[-1] if self.fallback_token is None else self.fallback_token
        out[:] = fb
        return out
