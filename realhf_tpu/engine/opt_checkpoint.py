"""Optimizer-state checkpointing (EXCEEDS the reference, which
restarts from weights + step counters only -- SURVEY §5.4 "Optimizer
state is not checkpointed"; Adam moments and the fp32 master copy then
re-warm from zero after every recovery, bending the training curve).

Format: one ``optimizer_state.npz`` next to the HF weights. Leaves are
stored flat in tree order; bfloat16 leaves travel as uint16 views
(numpy's npz cannot round-trip ml_dtypes). A structure fingerprint
(leaf count + shapes + dtypes) guards against loading a state built
for a different optimizer/zero1/master-weights configuration -- on
mismatch the load is skipped with a warning (fresh state, reference
behavior)."""

import json
import os
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from realhf_tpu.base import logging

logger = logging.getLogger("opt_checkpoint")

FILENAME = "optimizer_state.npz"


def _to_savable(a: np.ndarray):
    if a.dtype == jnp.bfloat16:
        return a.view(np.uint16), "bfloat16"
    return a, str(a.dtype)


def save_opt_state(path: str, host_leaves: List[np.ndarray]) -> str:
    """Write gathered host leaves (Engine.opt_state_numpy()) to
    ``path/optimizer_state.npz``."""
    arrays = {}
    dtypes = []
    for i, a in enumerate(host_leaves):
        arr, dt = _to_savable(np.asarray(a))
        arrays[f"l{i}"] = arr
        dtypes.append(dt)
    arrays["__meta__"] = np.frombuffer(
        json.dumps({"n": len(host_leaves), "dtypes": dtypes})
        .encode(), dtype=np.uint8)
    out = os.path.join(path, FILENAME)
    np.savez(out, **arrays)
    return out


def load_opt_state(path: str) -> Optional[List[np.ndarray]]:
    """Read ``path/optimizer_state.npz`` -> host leaves, or None."""
    f = os.path.join(path, FILENAME)
    if not os.path.exists(f):
        return None
    with np.load(f) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        leaves = []
        for i in range(meta["n"]):
            a = z[f"l{i}"]
            if meta["dtypes"][i] == "bfloat16":
                a = a.view(jnp.bfloat16)
            leaves.append(a)
    return leaves


def restore_engine_opt_state(engine, path: str) -> bool:
    """Install a saved state into an engine if the structure matches.
    Collective on multi-process meshes (every member reads the same
    file from the shared FS). Returns True when restored."""
    if engine.opt_state is None:
        return False
    leaves = load_opt_state(path)
    if leaves is None:
        return False
    cur = jax.tree.leaves(engine.opt_state)
    ok = len(cur) == len(leaves) and all(
        c.shape == tuple(l.shape) and c.dtype == l.dtype
        for c, l in zip(cur, leaves))
    if not ok:
        logger.warning(
            "Saved optimizer state at %s does not match the engine's "
            "structure (%d vs %d leaves); starting fresh.", path,
            len(leaves), len(cur))
        return False
    engine.load_opt_state(leaves)
    logger.info("Restored optimizer state from %s (%d leaves).", path,
                len(leaves))
    return True
