"""Optimizer-state checkpointing (EXCEEDS the reference, which
restarts from weights + step counters only -- SURVEY §5.4 "Optimizer
state is not checkpointed"; Adam moments and the fp32 master copy then
re-warm from zero after every recovery, bending the training curve).

Format: one ``optimizer_state.npz`` next to the HF weights. Leaves are
stored flat in tree order; bfloat16 leaves travel as uint16 views
(numpy's npz cannot round-trip ml_dtypes). A structure fingerprint
(leaf count + shapes + dtypes) guards against loading a state built
for a different optimizer/zero1/master-weights configuration -- on
mismatch the load is skipped with a warning (fresh state, reference
behavior)."""

import json
import os
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from realhf_tpu.base import logging

logger = logging.getLogger("opt_checkpoint")

FILENAME = "optimizer_state.npz"


def _to_savable(a: np.ndarray):
    if a.dtype == jnp.bfloat16:
        return a.view(np.uint16), "bfloat16"
    return a, str(a.dtype)


def save_opt_state(path: str, host_leaves: List[np.ndarray]) -> str:
    """Write gathered host leaves (Engine.opt_state_numpy()) to
    ``path/optimizer_state.npz``."""
    return save_opt_state_iter(path, iter(host_leaves))


def save_opt_state_iter(path: str, leaves) -> str:
    """Streaming form of :func:`save_opt_state`: consumes an iterator
    of leaves and writes each straight into the npz (a zip of .npy
    members, same layout ``np.savez`` produces and ``load_opt_state``
    reads), so only ONE leaf is ever host-resident. On single-process
    meshes the caller feeds ``np.asarray(leaf)`` per device leaf --
    the optimizer state is ~3x the model in fp32, the difference
    between fitting host RAM and not at the 70B scale."""
    import zipfile

    from numpy.lib import format as npformat

    out = os.path.join(path, FILENAME)
    dtypes = []
    n = 0
    with zipfile.ZipFile(out, "w", zipfile.ZIP_STORED,
                         allowZip64=True) as zf:
        for a in leaves:
            # streaming save: one leaf host-resident at a time is the
            # design, so the per-iteration transfer is intentional
            arr, dt = _to_savable(np.asarray(a))  # graft-lint: disable=purity-sync-in-loop
            dtypes.append(dt)
            with zf.open(f"l{n}.npy", "w", force_zip64=True) as fh:
                # NOT ascontiguousarray: it promotes 0-d leaves (optax
                # step counters) to 1-d, breaking the restore's
                # structure check
                npformat.write_array(fh, np.asarray(arr, order="C"))  # graft-lint: disable=purity-sync-in-loop
            n += 1
        meta = np.frombuffer(
            json.dumps({"n": n, "dtypes": dtypes}).encode(),
            dtype=np.uint8)
        with zf.open("__meta__.npy", "w", force_zip64=True) as fh:
            npformat.write_array(fh, meta)
    return out


def load_opt_state_checked(path: str) -> Tuple[
        Optional[List[np.ndarray]], Optional[str]]:
    """Read ``path/optimizer_state.npz`` -> (host leaves, None), or
    (None, reason). A corrupt/truncated/short file must name WHY the
    state is unusable -- the shard path, expected vs actual leaf count
    -- instead of silently degrading to fresh optimizer moments."""
    f = os.path.join(path, FILENAME)
    if not os.path.exists(f):
        return None, f"no optimizer state at {f}"
    try:
        with np.load(f) as z:
            if "__meta__" not in z:
                raise ValueError("missing __meta__ member")
            meta = json.loads(bytes(z["__meta__"]).decode())
            expected = int(meta["n"])
            leaves = []
            for i in range(expected):
                if f"l{i}" not in z:
                    raise ValueError(
                        f"short file: {len(leaves)} of {expected} "
                        "leaves present")
                a = z[f"l{i}"]
                if meta["dtypes"][i] == "bfloat16":
                    a = a.view(jnp.bfloat16)
                leaves.append(a)
    except Exception as e:  # noqa: BLE001 - reason surfaces to caller
        reason = (f"unreadable optimizer state shard {f}: "
                  f"{type(e).__name__}: {e}")
        logger.warning("%s", reason)
        return None, reason
    return leaves, None


def load_opt_state(path: str) -> Optional[List[np.ndarray]]:
    """Read ``path/optimizer_state.npz`` -> host leaves, or None (the
    failure reason is logged; use :func:`load_opt_state_checked` to
    receive it programmatically)."""
    leaves, _reason = load_opt_state_checked(path)
    return leaves


def restore_engine_opt_state(engine, path: str) -> bool:
    """Install a saved state into an engine if the structure matches.
    Collective on multi-process meshes (every member reads the same
    file from the shared FS). Returns True when restored."""
    if engine.opt_state is None:
        return False
    leaves, reason = load_opt_state_checked(path)
    if leaves is None:
        if reason is not None and "no optimizer state" not in reason:
            logger.warning("Optimizer state NOT restored: %s", reason)
        return False
    cur = jax.tree.leaves(engine.opt_state)
    ok = len(cur) == len(leaves) and all(
        c.shape == tuple(l.shape) and c.dtype == l.dtype
        for c, l in zip(cur, leaves))
    if not ok:
        logger.warning(
            "Saved optimizer state at %s does not match the engine's "
            "structure (%d vs %d leaves); starting fresh.", path,
            len(leaves), len(cur))
        return False
    engine.load_opt_state(leaves)
    logger.info("Restored optimizer state from %s (%d leaves).", path,
                len(leaves))
    return True
