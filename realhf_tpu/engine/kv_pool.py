"""Block-granular paged KV pool: the shared KV substrate for decode
slots AND the radix prefix cache (ISSUE 14, ROADMAP item 4).

The dense design it replaces gives every decode slot a private
``[cache_len]`` KV window sized for the WORST case (max prompt + max
new tokens), so a replica's concurrency is fixed at construction and
short sequences strand most of their reservation. This module is the
vLLM-style fix: one device-resident arena of fixed-size **blocks**
(``block_len`` token rows each), a host-side free list with per-block
refcounts, and per-sequence **block tables** mapping a sequence's
window row ``j`` to pool row ``table[j // block_len] * block_len +
j % block_len``. Sequences allocate blocks lazily as they grow and
free them at harvest, so live-KV bytes track actual tokens, not
worst-case windows -- concurrency is bounded by *blocks*, not slots.

Layout mirrors the Pallas paged-attention convention
(``k_pages [n_kv_heads, n_pages, page_size, head_dim]``) collapsed to
row-flat head-major arrays ``[n_layers, n_kv_heads, n_rows, head_dim]``
(``n_rows = (n_blocks + 1) * block_len``) so a block is simply a
contiguous row span and gathers/scatters are plain row indexing --
the same head-major streaming layout the dense cache and decode
kernels already use. **Block 0 is reserved** as a write-off scratch
block: unset block-table entries and masked scatter lanes all route
to its rows, so duplicate clamped indices can never corrupt live data
(the duplicate-scatter ordering lesson of the spec-decode path).

Because every sequence fills its window compacted from row 0, token
position ``p`` always lives at offset ``p % block_len`` of its
covering block, for every sequence. Any shared token *prefix*
therefore has an identical block-internal layout in every sequence
that carries it -- the invariant that lets the radix prefix cache
alias whole blocks into a new sequence's table (zero KV copy) instead
of keeping private host copies.

Quantization (``dtype="int8"``): values are stored as int8 with a
float32 scale per (layer, kv-head, row) -- i.e. per token row, the
append-friendly refinement of the per-page scales quantized paged
attention uses. A whole-block scale would have to be frozen at the
block's first write, long before its later rows exist; per-row amax
scales keep the round-trip error bound local (|x - dq(q(x))| <=
amax/254 per row) at a 4/head_dim relative byte overhead.
Quantize-on-write / dequantize-on-read both live inside the jitted
gather/scatter helpers, so the compute path never sees int8.

Host-side accounting (``alloc``/``free``/``incref``) is plain Python
on purpose: it runs between device calls, never inside traced code.
:meth:`KVPool.host_only` builds a pool with no device arrays at all --
the same allocator arithmetic for scheduler/chaos tests and fakes.
"""

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from realhf_tpu.base import logging

logger = logging.getLogger("engine.kv_pool")

#: accepted ``dtype`` spellings -> storage description
KV_CACHE_DTYPES = ("fp32", "bf16", "int8")


class KVPoolOOM(RuntimeError):
    """Raised when an allocation cannot be satisfied. Carries the
    shortfall so the scheduler can relieve exactly that much pressure
    (prefix-cache eviction first, sequence eviction as last resort)."""

    def __init__(self, requested: int, free: int):
        super().__init__(
            f"KV pool exhausted: requested {requested} block(s), "
            f"{free} free")
        self.requested = requested
        self.free = free

    @property
    def shortfall(self) -> int:
        return self.requested - self.free


@dataclasses.dataclass(frozen=True)
class PoolMeta:
    """Static (hashable) pool description closed over by the jitted
    gather/scatter helpers -- dynamic arrays travel separately."""
    block_len: int
    quant: bool              # int8 storage + per-row scales
    store_dtype: str         # "float32" | "bfloat16" | "int8"


class KVPool:
    """Device-resident block arena + host-side block allocator."""

    def __init__(self, cfg, n_blocks: int, block_len: int,
                 dtype: str = "fp32", compute_dtype=None):
        if dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"kv_cache_dtype must be one of {KV_CACHE_DTYPES}, "
                f"got {dtype!r}")
        if n_blocks < 1 or block_len < 1:
            raise ValueError("n_blocks and block_len must be >= 1")
        self.cfg = cfg
        self.n_blocks = int(n_blocks)
        self.block_len = int(block_len)
        self.dtype = dtype
        self.meta = PoolMeta(
            block_len=self.block_len, quant=(dtype == "int8"),
            store_dtype={"fp32": "float32", "bf16": "bfloat16",
                         "int8": "int8"}[dtype])
        # host allocator state: ids 1..n_blocks; 0 reserved (scratch)
        self._free: List[int] = list(range(self.n_blocks, 0, -1))
        self._ref = np.zeros(self.n_blocks + 1, np.int32)
        self._ref[0] = 1  # the scratch block is never allocatable
        self.stats_counters = dict(allocs=0, frees=0, oom=0)

        self._arrays: Optional[Dict] = None
        if cfg is not None:
            import jax.numpy as jnp
            nl, nkv, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
            rows = (self.n_blocks + 1) * self.block_len
            sdt = jnp.dtype(self.meta.store_dtype)
            self._arrays = dict(
                k=jnp.zeros((nl, nkv, rows, hd), sdt),
                v=jnp.zeros((nl, nkv, rows, hd), sdt))
            if self.meta.quant:
                self._arrays["k_scale"] = jnp.zeros((nl, nkv, rows),
                                                    jnp.float32)
                self._arrays["v_scale"] = jnp.zeros((nl, nkv, rows),
                                                    jnp.float32)
            self._bytes_per_row = 2 * nl * nkv * (
                hd * sdt.itemsize + (4 if self.meta.quant else 0))
        else:
            self._bytes_per_row = 0

    @classmethod
    def host_only(cls, n_blocks: int, block_len: int,
                  bytes_per_row: int = 0) -> "KVPool":
        """Allocator arithmetic without device arrays -- for test
        fakes and scheduler/chaos suites (base/testing.py)."""
        pool = cls(None, n_blocks, block_len, dtype="fp32")
        pool._bytes_per_row = int(bytes_per_row)
        return pool

    # -- device arrays (functional style: jitted callers take the
    # dict, return an updated one, and hand it back via update) ------
    def arrays(self) -> Dict:
        if self._arrays is None:
            raise RuntimeError("host_only pool has no device arrays")
        return self._arrays

    def update(self, arrays: Dict):
        self._arrays = arrays

    # -- allocator ----------------------------------------------------
    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_in_use(self) -> int:
        return self.n_blocks - len(self._free)

    @property
    def block_bytes(self) -> int:
        return self._bytes_per_row * self.block_len

    @property
    def bytes_per_row(self) -> int:
        return self._bytes_per_row

    def blocks_for_rows(self, rows: int) -> int:
        """Blocks covering ``rows`` token rows."""
        return -(-max(0, int(rows)) // self.block_len)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks (each at refcount 1). All-or-nothing:
        raises :class:`KVPoolOOM` without side effects when fewer
        than ``n`` are free."""
        n = int(n)
        if n <= 0:
            return []
        if len(self._free) < n:
            self.stats_counters["oom"] += 1
            raise KVPoolOOM(n, len(self._free))
        out = [self._free.pop() for _ in range(n)]
        self._ref[out] = 1
        self.stats_counters["allocs"] += n
        return out

    def incref(self, blocks: Iterable[int]):
        for b in blocks:
            if self._ref[b] <= 0 or b == 0:
                raise ValueError(f"incref on unallocated block {b}")
            self._ref[b] += 1

    def free(self, blocks: Iterable[int]):
        """Drop one reference per listed block; blocks reaching zero
        return to the free list."""
        for b in blocks:
            if b == 0:
                continue
            if self._ref[b] <= 0:
                raise ValueError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(int(b))
                self.stats_counters["frees"] += 1

    def ref(self, block: int) -> int:
        return int(self._ref[block])

    def stats(self) -> Dict:
        in_use = self.n_in_use
        return dict(
            blocks_total=self.n_blocks, blocks_free=self.n_free,
            blocks_in_use=in_use, block_len=self.block_len,
            block_bytes=self.block_bytes,
            bytes_per_row=self._bytes_per_row,
            bytes_in_use=in_use * self.block_bytes,
            bytes_total=self.n_blocks * self.block_bytes,
            dtype=self.dtype, **self.stats_counters)


# ----------------------------------------------------------------------
# jit-safe gather/scatter (pure functions over the arrays dict)
# ----------------------------------------------------------------------
def window_rows(bt, warange, block_len: int):
    """Flat pool rows for window positions ``warange`` (``[S]``) of
    each sequence in block table ``bt`` (``[B, max_blocks]``): row j
    of sequence b lives at ``bt[b, j // blen] * blen + j % blen``.
    Unset table entries (0) resolve into the reserved scratch block,
    whose rows are only ever read masked."""
    cols = warange // block_len                       # [S]
    return bt[:, cols] * block_len + (warange % block_len)[None, :]


def pool_gather(meta: PoolMeta, arrays, rows, compute_dtype):
    """Dequantized ``(k, v)`` -- each ``[nl, B, nkv, S, hd]`` in the
    compute dtype -- for flat pool rows ``rows`` (``[B, S]``)."""
    import jax.numpy as jnp
    k = arrays["k"][:, :, rows]          # [nl, nkv, B, S, hd]
    v = arrays["v"][:, :, rows]
    if meta.quant:
        k = k.astype(jnp.float32) * arrays["k_scale"][:, :, rows][..., None]
        v = v.astype(jnp.float32) * arrays["v_scale"][:, :, rows][..., None]
    cdt = jnp.dtype(compute_dtype)
    return (k.transpose(0, 2, 1, 3, 4).astype(cdt),
            v.transpose(0, 2, 1, 3, 4).astype(cdt))


def _quantize_rows(x):
    """Per-row symmetric int8: ``x`` [..., hd] -> (int8 values,
    float32 scales [...])."""
    import jax.numpy as jnp
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = (amax / 127.0).astype(jnp.float32)
    q = jnp.where(scale[..., None] > 0,
                  x.astype(jnp.float32) / jnp.maximum(scale[..., None],
                                                      1e-30), 0.0)
    q = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)
    return q, scale


def pool_scatter(meta: PoolMeta, arrays, rows, k_new, v_new, mask):
    """Write ``k_new``/``v_new`` (``[nl, B, nkv, m, hd]``) at flat
    pool rows ``rows`` (``[B, m]``). Masked-off lanes are routed into
    the reserved scratch block (row span of block 0), so clamped
    duplicate indices never land on live rows -- scatter write order
    for duplicates is unspecified and has bitten this codebase before
    (see ``_verify_chunk``). Returns the updated arrays dict."""
    import jax.numpy as jnp
    safe = jnp.where(mask, rows, 0)      # 0 = scratch block, row 0
    out = dict(arrays)
    if meta.quant:
        kq, ks = _quantize_rows(k_new)
        vq, vs = _quantize_rows(v_new)
        out["k"] = arrays["k"].at[:, :, safe].set(
            kq.transpose(0, 2, 1, 3, 4))
        out["v"] = arrays["v"].at[:, :, safe].set(
            vq.transpose(0, 2, 1, 3, 4))
        out["k_scale"] = arrays["k_scale"].at[:, :, safe].set(
            ks.transpose(0, 2, 1, 3))
        out["v_scale"] = arrays["v_scale"].at[:, :, safe].set(
            vs.transpose(0, 2, 1, 3))
    else:
        sdt = arrays["k"].dtype
        out["k"] = arrays["k"].at[:, :, safe].set(
            k_new.transpose(0, 2, 1, 3, 4).astype(sdt))
        out["v"] = arrays["v"].at[:, :, safe].set(
            v_new.transpose(0, 2, 1, 3, 4).astype(sdt))
    return out


def int8_roundtrip_error_bound(x: np.ndarray) -> float:
    """The per-row bound the int8 path guarantees: half a quantization
    step, ``amax / 254`` per row (tests assert against this)."""
    amax = np.max(np.abs(x), axis=-1, keepdims=True)
    return float(np.max(amax) / 254.0 + 1e-12)
