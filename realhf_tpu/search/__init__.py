from realhf_tpu.search.engine import (  # noqa: F401
    MFCWorkload,
    SearchResult,
    apply_searched_allocations,
    search_rpc_allocations,
    suggest_worker_assignment,
)
