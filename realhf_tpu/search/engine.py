"""Allocation search: C++ MCMC over per-MFC placements.

TPU-native counterpart of the reference search engine
(``realhf/search_engine/search.py:25`` driving the C++
``mdm_search.multi_mcmc_search``, csrc/search/search.cpp): Python
enumerates candidate placements per MFC -- a contiguous chip slice and
a (dp, tp) layout that fits HBM -- and prices each with an analytic
TPU cost model (MXU flops at an efficiency factor for compute-bound
phases, HBM bandwidth for decode, ICI bandwidth for parameter
reallocation between layouts). The native module
(``csrc/mcmc_search.cpp``) then runs simulated annealing, scoring
assignments by simulating the dataflow graph (dependency + device
contention scheduling, same-role realloc charges), and returns the
best assignment.

The .so is compiled on first use with g++ (no pybind11 in the image;
plain C ABI + ctypes).
"""

import ctypes
import dataclasses
import os
import subprocess
from typing import Dict, List, Optional, Tuple

import numpy as np

from realhf_tpu.api.config import ModelInterfaceType
from realhf_tpu.base import logging
from realhf_tpu.parallel.mesh import ParallelismConfig

logger = logging.getLogger("search", "benchmark")

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc",
    "mcmc_search.cpp")


# ---------------------------------------------------------------------
# Native module loading (compile on demand)
# ---------------------------------------------------------------------
_lib = None


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(_CSRC), "build")
    os.makedirs(d, exist_ok=True)
    return d


def load_native():
    """Compile (if stale) and load the MCMC search shared object."""
    global _lib
    if _lib is not None:
        return _lib
    # cache key = source content hash (mtime is meaningless after a
    # fresh clone, and the .so is never committed -- platform-specific)
    import hashlib
    with open(_CSRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_build_dir(), f"libmcmc_search-{digest}.so")
    if not os.path.exists(so):
        import glob
        for stale in glob.glob(
                os.path.join(_build_dir(), "libmcmc_search*.so")):
            try:
                os.remove(stale)
            except OSError:
                pass
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               _CSRC, "-o", so]
        logger.info("Building native search module: %s", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           text=True)
        except subprocess.CalledProcessError as e:
            logger.error("Native search build failed:\n%s", e.stderr)
            raise
    lib = ctypes.CDLL(so)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i8p = ctypes.POINTER(ctypes.c_int8)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.mcmc_search.restype = ctypes.c_double
    lib.mcmc_search.argtypes = [
        ctypes.c_int, ctypes.c_int, i64p, i32p, i32p, f64p, i32p, i32p,
        i8p, f64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_double,
        ctypes.c_double, ctypes.c_uint64, i64p]
    lib.simulate_assignment.restype = ctypes.c_double
    lib.simulate_assignment.argtypes = [
        ctypes.c_int, ctypes.c_int, i64p, i32p, i32p, f64p, i32p, i32p,
        i8p, f64p, ctypes.c_int64, i64p]
    _lib = lib
    return lib


# ---------------------------------------------------------------------
# Cost model (v5e defaults; overridable)
# ---------------------------------------------------------------------
@dataclasses.dataclass
class TPUCostModel:
    peak_flops: float = 197e12        # bf16 per chip
    mxu_efficiency: float = 0.4       # achieved fraction on train/prefill
    hbm_bandwidth: float = 819e9      # bytes/s per chip
    ici_bandwidth: float = 186e9      # bytes/s per chip (all links)
    hbm_budget: float = 16e9 * 0.6


#: calibration artifact the measured-fit entry (scripts/calibrate.py)
#: persists and default_cost_model() picks up
CALIBRATION_ENV = "REALHF_TPU_CALIBRATION"
CALIBRATION_FILE = "calibration_tpu.json"
_calib_logged: set = set()


def load_cost_model(path: str) -> Optional[TPUCostModel]:
    """Parse a calibration artifact into a TPUCostModel, tolerating
    both the full artifact layout ({"calibrated": {...}}) and a flat
    field dict; unknown keys are ignored, absent ones keep defaults.
    Returns None (never raises) on a missing/corrupt file -- an
    unreadable calibration must degrade to the analytic defaults, not
    kill an allocation search."""
    import json
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(raw, dict) and isinstance(raw.get("calibrated"), dict):
        raw = raw["calibrated"]
    if not isinstance(raw, dict):
        return None
    fields = {f.name for f in dataclasses.fields(TPUCostModel)}
    kept = {k: float(v) for k, v in raw.items()
            if k in fields and isinstance(v, (int, float))}
    if not kept:
        return None
    return TPUCostModel(**kept)


def default_cost_model() -> TPUCostModel:
    """The cost model searches use when the caller passes none: a
    persisted on-chip calibration when present (``$REALHF_TPU_CALIBRATION``
    or ``./calibration_tpu.json``, written by ``scripts/calibrate.py``),
    else the analytic v5e defaults -- allocations stop being educated
    guesses as soon as one chip window has run the microbenchmark."""
    path = os.environ.get(CALIBRATION_ENV) or CALIBRATION_FILE
    cm = load_cost_model(path)
    if cm is None:
        return TPUCostModel()
    if path not in _calib_logged:
        _calib_logged.add(path)
        logger.info(
            "Cost model loaded from calibration %s: "
            "mxu_efficiency=%.3f, hbm_bw=%.0f GB/s", path,
            cm.mxu_efficiency, cm.hbm_bandwidth / 1e9)
    return cm


@dataclasses.dataclass
class MFCWorkload:
    """What one MFC costs, independent of layout."""
    name: str
    role: str
    interface_type: ModelInterfaceType
    fwd_flops: float                  # one forward over the batch
    param_bytes: float                # bf16 weight bytes
    train_state_bytes: float = 0.0    # weights+master+adam when training
    gen_tokens: int = 0               # decode steps (generate MFCs)
    n_layers: int = 0                 # for pipeline-stage divisibility
                                      # (0 = unknown: no pp candidates)

    @property
    def trainable(self) -> bool:
        return self.interface_type == ModelInterfaceType.TRAIN_STEP


@dataclasses.dataclass
class Candidate:
    parallel: ParallelismConfig
    dev_lo: int
    dev_hi: int
    time: float


@dataclasses.dataclass
class SearchResult:
    time: float                       # simulated step seconds
    assignment: Dict[str, Candidate]  # mfc name -> placement
    # roles whose searched slices are disjoint grouped onto different
    # model workers (filled by apply_searched_allocations)
    worker_assignment: Dict[str, int] = dataclasses.field(
        default_factory=dict)


def suggest_worker_assignment(workloads: List[MFCWorkload],
                              assignment: Dict[str, Candidate]
                              ) -> Dict[str, int]:
    """Role -> model-worker index realizing the simulator's slice
    concurrency: the runtime overlaps MFCs only across worker
    processes (each owning its devices), so roles whose searched
    device slices are disjoint go to different workers; overlapping
    slices share one."""
    spans: Dict[str, Tuple[int, int]] = {}
    for w in workloads:
        c = assignment[w.name]
        lo, hi = spans.get(w.role, (c.dev_lo, c.dev_hi))
        spans[w.role] = (min(lo, c.dev_lo), max(hi, c.dev_hi))
    # interval-merge sweep over role spans sorted by lo: overlapping
    # spans share one worker, disjoint spans get their own
    ordered = sorted(spans.items(), key=lambda kv: kv[1])
    out: Dict[str, int] = {}
    idx = -1
    cur_hi = -1
    for role, (lo, hi) in ordered:
        if lo >= cur_hi:  # disjoint from the running group
            idx += 1
            cur_hi = hi
        else:
            cur_hi = max(cur_hi, hi)
        out[role] = idx
    return out


def _pow2s(n: int) -> List[int]:
    out, p = [], 1
    while p <= n:
        out.append(p)
        p *= 2
    return out


def exec_time(w: MFCWorkload, tp: int, dp: int,
              cm: TPUCostModel, pp: int = 1) -> float:
    """Seconds for one execution of the MFC on dp*tp*pp chips.

    Pipeline stages add the schedule's bubble: (M + pp - 1) / M over
    perfect scaling at the engine's default microbatch count for the
    default 1F1B schedule (M = 4*pp -- its bounded residual memory
    affords twice GPipe's M, halving the (pp-1)/M overhead; see
    parallel/schedule.train_bubble_factor). pp candidates therefore
    price cheaper than under the old GPipe term and re-rank.
    """
    chips = tp * dp * pp
    if pp > 1:
        from realhf_tpu.parallel.schedule import train_bubble_factor
        bubble = train_bubble_factor(pp)
    else:
        bubble = 1.0
    if w.interface_type == ModelInterfaceType.TRAIN_STEP:
        flops = 3.0 * w.fwd_flops          # fwd + bwd (2x)
        return bubble * flops / (chips * cm.peak_flops
                                 * cm.mxu_efficiency)
    if w.interface_type == ModelInterfaceType.GENERATE:
        prefill = w.fwd_flops / (chips * cm.peak_flops
                                 * cm.mxu_efficiency)
        # decode is weight-bandwidth bound: every step re-reads this
        # chip's weight shard from HBM
        decode = w.gen_tokens * (w.param_bytes / tp) / cm.hbm_bandwidth
        if pp > 1:
            # pp-mesh generation runs on the collapsed dp x tp decode
            # view (engine.decode_engine): same per-chip decode traffic
            # at the view's tp (= train tp by default), plus one
            # weights reshard onto the view per weight version
            return (prefill + decode
                    + (w.param_bytes / chips) / cm.ici_bandwidth)
        return prefill + decode
    return bubble * w.fwd_flops / (chips * cm.peak_flops
                                   * cm.mxu_efficiency)


def enumerate_candidates(w: MFCWorkload, n_devices: int,
                         cm: TPUCostModel) -> List[Candidate]:
    """(slice, layout) placements whose per-chip memory fits."""
    need = w.train_state_bytes if w.trainable else w.param_bytes * 1.25
    out: List[Candidate] = []
    # GENERATE candidates stay pp=1 on purpose: a same-slice pp=1
    # candidate already models the colocated-rollout configuration
    # (overlapping slices serialize in the simulator, and the runtime
    # realizes it as either a realloc replica or the engine's decode
    # view -- both one extra gen-layout weight copy); a distinct pp>1
    # generate candidate would be redundant search space. exec_time
    # still prices pp>1 correctly for direct/profile callers.
    if w.interface_type == ModelInterfaceType.GENERATE or not w.n_layers:
        pps = [1]
    else:
        pps = [pp for pp in _pow2s(n_devices)
               if w.n_layers % pp == 0]
    for pp in pps:
        for tp in _pow2s(n_devices // pp):
            if need / (tp * pp) > cm.hbm_budget:
                continue
            for dp in _pow2s(n_devices // (tp * pp)):
                size = tp * dp * pp
                t = exec_time(w, tp, dp, cm, pp)
                for lo in range(0, n_devices - size + 1, size):
                    out.append(Candidate(
                        ParallelismConfig(data_parallel_size=dp,
                                          tensor_parallel_size=tp,
                                          pipeline_parallel_size=pp,
                                          sequence_parallel=(
                                              tp > 1 and w.trainable)),
                        lo, lo + size, t))
    if not out:  # nothing fits even at full TP: loud fallback
        logger.warning(
            "MFC %s does not fit the HBM budget at any layout on %d "
            "devices (%.1f GB/chip needed at full TP, budget %.1f GB);"
            " using full TP anyway -- expect OOM without remat/offload"
            " headroom.", w.name, n_devices,
            need / n_devices / 1e9, cm.hbm_budget / 1e9)
        out.append(Candidate(
            ParallelismConfig(data_parallel_size=1,
                              tensor_parallel_size=n_devices,
                              sequence_parallel=w.trainable),
            0, n_devices, exec_time(w, n_devices, 1, cm)))
    return out


# ---------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------
@dataclasses.dataclass
class _FlatProblem:
    workloads: List[MFCWorkload]
    n_devices: int
    cands: List[List[Candidate]]
    flat: List[Candidate]
    offsets: np.ndarray
    dev_lo: np.ndarray
    dev_hi: np.ndarray
    times: np.ndarray
    roles: np.ndarray
    trainable: np.ndarray
    dep_m: np.ndarray
    realloc: np.ndarray

    @property
    def n(self):
        return len(self.workloads)

    @property
    def m(self):
        return int(self.offsets[-1])

    def args(self):
        def ptr(arr, ct):
            return arr.ctypes.data_as(ctypes.POINTER(ct))
        return (self.n, self.n_devices,
                ptr(self.offsets, ctypes.c_int64),
                ptr(self.dev_lo, ctypes.c_int32),
                ptr(self.dev_hi, ctypes.c_int32),
                ptr(self.times, ctypes.c_double),
                ptr(self.roles, ctypes.c_int32),
                ptr(self.trainable, ctypes.c_int32),
                ptr(self.dep_m, ctypes.c_int8),
                ptr(self.realloc, ctypes.c_double),
                self.m)


def _flatten(workloads: List[MFCWorkload], deps: Dict[str, List[str]],
             n_devices: int, cm: TPUCostModel) -> _FlatProblem:
    n = len(workloads)
    cands = [enumerate_candidates(w, n_devices, cm) for w in workloads]
    offsets = np.zeros(n + 1, np.int64)
    for i, cl in enumerate(cands):
        offsets[i + 1] = offsets[i] + len(cl)
    m = int(offsets[-1])
    flat = [c for cl in cands for c in cl]

    name_idx = {w.name: i for i, w in enumerate(workloads)}
    dep_m = np.zeros((n, n), np.int8)
    for name, parents in deps.items():
        for p in parents:
            dep_m[name_idx[name], name_idx[p]] = 1

    role_ids: Dict[str, int] = {}
    cand_owner = np.concatenate(
        [np.full(len(cl), i) for i, cl in enumerate(cands)])
    # vectorized pairwise realloc cost: moving a role's weights
    # between two placements is bounded by the smaller slice's
    # aggregate ICI bandwidth; identical (layout, slice) pairs are
    # free. (The C++ simulator reads only same-role home->candidate
    # rows, but the dense numpy build is cheap.)
    lo = np.asarray([c.dev_lo for c in flat])
    hi = np.asarray([c.dev_hi for c in flat])
    sizes = hi - lo
    pbytes = np.asarray([workloads[int(o)].param_bytes
                         for o in cand_owner])
    chips = np.minimum(sizes[:, None], sizes[None, :])
    realloc = pbytes[:, None] / (chips * cm.ici_bandwidth)
    layout_key = np.asarray(
        [hash((c.parallel.data_parallel_size,
               c.parallel.tensor_parallel_size,
               c.parallel.pipeline_parallel_size,
               c.parallel.context_parallel_size,
               c.dev_lo, c.dev_hi)) for c in flat])
    realloc[layout_key[:, None] == layout_key[None, :]] = 0.0

    return _FlatProblem(
        workloads=workloads, n_devices=n_devices, cands=cands,
        flat=flat, offsets=offsets,
        dev_lo=np.asarray([c.dev_lo for c in flat], np.int32),
        dev_hi=np.asarray([c.dev_hi for c in flat], np.int32),
        times=np.asarray([c.time for c in flat], np.float64),
        roles=np.asarray([role_ids.setdefault(w.role, len(role_ids))
                          for w in workloads], np.int32),
        trainable=np.asarray([int(w.trainable) for w in workloads],
                             np.int32),
        dep_m=np.ascontiguousarray(dep_m.reshape(-1)),
        realloc=np.ascontiguousarray(realloc.reshape(-1)))


def search_rpc_allocations(
    workloads: List[MFCWorkload],
    deps: Dict[str, List[str]],
    n_devices: int,
    cost_model: Optional[TPUCostModel] = None,
    n_steps: int = 20000,
    seed: int = 1,
) -> SearchResult:
    """MCMC-search placements for the given MFC workloads.

    ``deps[name]`` lists MFCs that must finish before ``name`` starts
    (the DFG edges).
    """
    cm = cost_model or default_cost_model()
    lib = load_native()
    p = _flatten(workloads, deps, n_devices, cm)

    out_pick = np.zeros(p.n, np.int64)
    best = lib.mcmc_search(
        *p.args(), n_steps, 1.0, 1e4, seed,
        out_pick.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))

    assignment = {w.name: p.flat[int(out_pick[i])]
                  for i, w in enumerate(workloads)}
    logger.info("MCMC search: %d MFCs, %d candidates, best simulated "
                "step %.3fs", p.n, p.m, best)
    return SearchResult(time=float(best), assignment=assignment)


def simulate_named_assignment(
    workloads: List[MFCWorkload],
    deps: Dict[str, List[str]],
    n_devices: int,
    picks: Dict[str, Candidate],
    cost_model: Optional[TPUCostModel] = None,
) -> float:
    """Simulated step seconds for an explicit assignment (the same
    native simulator the search uses -- dependency + device-contention
    scheduling with realloc charges)."""
    cm = cost_model or default_cost_model()
    lib = load_native()
    p = _flatten(workloads, deps, n_devices, cm)

    def locate(i, c: Candidate) -> int:
        lo, hi = int(p.offsets[i]), int(p.offsets[i + 1])
        for j in range(lo, hi):
            f = p.flat[j]
            if (f.parallel.same_layout(c.parallel)
                    and (f.dev_lo, f.dev_hi) == (c.dev_lo, c.dev_hi)):
                return j
        raise ValueError(
            f"{workloads[i].name}: candidate {c} not enumerable")

    pick = np.asarray(
        [locate(i, picks[w.name]) for i, w in enumerate(workloads)],
        np.int64)
    return float(lib.simulate_assignment(
        *p.args(),
        pick.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))))


def calibrate_cost_model(
    spec,
    base: Optional[TPUCostModel] = None,
    probe_seqs: int = 4,
    probe_len: int = 512,
    probe_gen_tokens: int = 32,
    probe_layers: int = 4,
) -> TPUCostModel:
    """Measure-and-fit the cost model on the CURRENT backend
    (reference profiler-driven cost model,
    realhf/search_engine/estimate.py:323 + layers.py:56: per-layer
    fwd/bwd/opt timings feed the estimator; analytic rooflines rank
    candidates fine but mis-price realloc-vs-colocate trade-offs).

    For each distinct role architecture, a depth-truncated probe model
    (same hidden/ffn/vocab shapes, ``probe_layers`` layers -- per-layer
    cost is depth-linear, so achieved efficiency transfers) runs one
    timed train step and one timed decode on a single device. The
    returned model replaces ``mxu_efficiency`` with the measured
    train-step MFU and scales ``hbm_bandwidth`` by the measured decode
    bandwidth fraction."""
    import time

    import jax

    from realhf_tpu.api.config import ModelName
    from realhf_tpu.base import monitor
    from realhf_tpu.engine.engine import Engine
    from realhf_tpu.engine.optim import OptimizerConfig
    from realhf_tpu.experiments.heuristic import _model_config_of
    from realhf_tpu.models import transformer as T
    from realhf_tpu.ops import functional as F
    from realhf_tpu.parallel.mesh import MeshContext, make_mesh

    cm = dataclasses.replace(base or TPUCostModel())
    mfus: List[float] = []
    bw_fracs: List[float] = []
    seen = set()
    for role, mspec in spec.models.items():
        cfg = _model_config_of(mspec)
        key = (cfg.hidden_dim, cfg.intermediate_dim, cfg.n_q_heads,
               cfg.n_kv_heads, cfg.vocab_size, cfg.mlp_type)
        if key in seen:
            continue
        seen.add(key)
        probe = dataclasses.replace(
            cfg, n_layers=min(probe_layers, cfg.n_layers),
            is_critic=False, gradient_checkpointing=True)
        parallel = ParallelismConfig()
        mesh = make_mesh(parallel, devices=jax.devices()[:1])
        ctx = MeshContext(ModelName(f"probe_{role}", 0), mesh, parallel)
        params = T.init_params(probe, jax.random.PRNGKey(0))
        engine = Engine(probe, ctx, params,
                        optimizer=OptimizerConfig(
                            lr=1e-5, warmup_steps_proportion=0.0,
                            lr_scheduler_type="constant"),
                        total_train_steps=100)
        rng = np.random.default_rng(0)
        ids = rng.integers(2, probe.vocab_size,
                           size=(probe_seqs, probe_len)).astype(np.int32)
        seg = np.ones_like(ids)
        mb = dict(input_ids=ids, seg_ids=seg)

        def loss_fn(p, mb):
            h, _ = T.forward(probe, p, mb["input_ids"], mb["seg_ids"])
            lp = F.shifted_logprobs_from_hidden(
                probe, p, h, mb["input_ids"], mb["seg_ids"])
            return -lp.mean(), {}

        engine.train_batch([mb], loss_fn, loss_fn_key="calib")  # compile
        t0 = time.monotonic()
        engine.train_batch([mb], loss_fn, loss_fn_key="calib")
        train_s = time.monotonic() - t0
        flops = 4 * monitor.transformer_forward_flops(  # remat: 4x fwd
            n_layers=probe.n_layers, hidden_dim=probe.hidden_dim,
            n_q_heads=probe.n_q_heads, n_kv_heads=probe.n_kv_heads,
            head_dim=probe.head_dim,
            intermediate_dim=probe.intermediate_dim,
            vocab_size=probe.vocab_size,
            seqlens=[probe_len] * probe_seqs)
        mfus.append(flops / train_s / cm.peak_flops)

        from realhf_tpu.ops.sampling import GenerationHyperparameters
        from realhf_tpu.engine import packing
        prompts = [ids[i, :64] for i in range(probe_seqs)]
        pids, pseg, ppos = packing.left_padded_prompts(prompts, pad_id=0)

        def timed_gen(gn):
            g = GenerationHyperparameters(
                max_new_tokens=gn, min_new_tokens=gn, greedy=True,
                force_no_logits_mask=True)
            out = engine.generate(pids, pseg, ppos,
                                  jax.random.PRNGKey(0), g,
                                  eos_token_id=None, pad_token_id=0)
            jax.block_until_ready(out.tokens)  # compile
            t0 = time.monotonic()
            out = engine.generate(pids, pseg, ppos,
                                  jax.random.PRNGKey(1), g,
                                  eos_token_id=None, pad_token_id=0)
            jax.block_until_ready(out.tokens)
            return time.monotonic() - t0

        # Decode bandwidth from a TWO-POINT fit: one short and one long
        # generation share the prefill + sampling + dispatch overheads,
        # so the difference isolates pure per-token decode time (the
        # single-call version divided decode bytes by a wall that
        # included prefill, deflating the bandwidth estimate -- same
        # conflation the r3 advisor flagged in bench.py).
        gn_lo = max(2, probe_gen_tokens // 4)
        t_lo = timed_gen(gn_lo)
        t_hi = timed_gen(probe_gen_tokens)
        decode_s = max(t_hi - t_lo, 1e-6)
        pbytes = probe.n_params() * jnp_dtype_size(probe.param_dtype)
        decode_bytes = (probe_gen_tokens - gn_lo) * pbytes
        bw_fracs.append(decode_bytes / decode_s / cm.hbm_bandwidth)

    if mfus:
        cm.mxu_efficiency = float(np.clip(np.median(mfus), 0.01, 1.0))
    if bw_fracs:
        cm.hbm_bandwidth *= float(np.clip(np.median(bw_fracs), 0.01, 1.0))
    logger.info(
        "Calibrated cost model: mxu_efficiency=%.3f (measured MFUs %s), "
        "effective HBM bw %.0f GB/s (fracs %s)", cm.mxu_efficiency,
        [round(m, 3) for m in mfus], cm.hbm_bandwidth / 1e9,
        [round(b, 3) for b in bw_fracs])
    return cm


def jnp_dtype_size(dtype_name: str) -> int:
    import jax.numpy as jnp
    return jnp.dtype(dtype_name).itemsize


def workloads_from_spec(spec, gen_tokens: int = 256,
                        avg_seqlen: int = 512) -> Tuple[
                            List[MFCWorkload], Dict[str, List[str]]]:
    """Derive workloads + dependency lists from an ExperimentSpec."""
    from realhf_tpu.api.dfg import DFG
    from realhf_tpu.base import monitor
    from realhf_tpu.experiments.heuristic import _model_config_of

    dfg = DFG(spec.mfcs)
    out = []
    for node in dfg.nodes:
        cfg = _model_config_of(spec.models[node.role])
        seqlens = [avg_seqlen] * node.n_seqs
        fwd = monitor.transformer_forward_flops(
            n_layers=cfg.n_layers, hidden_dim=cfg.hidden_dim,
            n_q_heads=cfg.n_q_heads, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim,
            intermediate_dim=cfg.intermediate_dim,
            vocab_size=cfg.vocab_size, seqlens=seqlens)
        pbytes = cfg.n_params() * 2.0
        out.append(MFCWorkload(
            name=node.name, role=node.role,
            interface_type=node.interface_type,
            fwd_flops=float(fwd), param_bytes=pbytes,
            train_state_bytes=cfg.n_params() * 18.0,
            n_layers=cfg.n_layers,
            gen_tokens=(gen_tokens if node.interface_type
                        == ModelInterfaceType.GENERATE else 0)))
    deps = {n.name: [p.name for p in n.parents] for n in dfg.nodes}
    return out, deps


def apply_searched_allocations(spec, n_devices: int,
                               cost_model: Optional[TPUCostModel] = None,
                               n_steps: int = 20000,
                               gen_tokens: int = 256,
                               avg_seqlen: int = 512) -> SearchResult:
    """allocation_mode=search: run the MCMC search and write the
    resulting layouts into the spec (role primaries from train MFCs,
    per-MFC overrides elsewhere), like apply_heuristic_allocations.

    The simulator's slice-level CONCURRENCY is realized by the runtime
    only across model-worker processes (each owning its own devices):
    the result carries ``worker_assignment`` for that; in inline mode
    (one process, serial MFCs) only the layouts apply and the
    simulated time is optimistic about overlap.
    """
    workloads, deps = workloads_from_spec(spec, gen_tokens, avg_seqlen)
    res = search_rpc_allocations(workloads, deps, n_devices,
                                 cost_model, n_steps)
    res.worker_assignment = suggest_worker_assignment(workloads,
                                                      res.assignment)
    primaries: Dict[str, ParallelismConfig] = {}
    for w in workloads:
        if w.trainable:
            primaries[w.role] = res.assignment[w.name].parallel
    for w in workloads:
        primaries.setdefault(w.role, res.assignment[w.name].parallel)
    for role, par in primaries.items():
        spec.models[role] = dataclasses.replace(spec.models[role],
                                                parallel=par)
    spec.allocations = dict(spec.allocations)
    for w in workloads:
        par = res.assignment[w.name].parallel
        if not par.same_layout(primaries[w.role]):
            spec.allocations[w.name] = par
    return res
