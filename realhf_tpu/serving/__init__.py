"""Async rollout & serving subsystem (docs/serving.md).

Turns the engine-level continuous-batching generator into a
long-running generation service: admission-controlled request queue,
iteration-level scheduler with weight hot-swap and bounded staleness,
and a ZMQ streaming server/client pair wired into the worker stack.
"""

from realhf_tpu.serving.fleet import (  # noqa: F401
    FleetRegistry,
    LeaseLostError,
    ReplicaInfo,
)
from realhf_tpu.serving.request_queue import (  # noqa: F401
    AdmissionVerdict,
    GenRequest,
    Priority,
    RequestQueue,
)
from realhf_tpu.serving.ring import Ring, rehomed  # noqa: F401
from realhf_tpu.serving.router import (  # noqa: F401
    BreakerState,
    CircuitBreaker,
    FleetRouter,
)
from realhf_tpu.serving.router_shard import (  # noqa: F401
    ShardedRolloutClient,
    ShardedRouter,
)
from realhf_tpu.serving.scheduler import (  # noqa: F401
    ContinuousScheduler,
    FinishedRollout,
    ServeEvent,
)
from realhf_tpu.serving.server import (  # noqa: F401
    RolloutClient,
    RolloutResult,
    RolloutServer,
    rollout_server_key,
)
from realhf_tpu.serving.weight_dist import (  # noqa: F401
    ChunkedWeightReceiver,
    WeightDistributor,
    relay_tree,
)
from realhf_tpu.serving.weight_sync import WeightSync  # noqa: F401
