"""Iteration-level continuous scheduler over decode slots.

Orca-style scheduling for the rollout service: between decode
iterations the scheduler (1) installs pending weight swaps, (2)
evicts sequences that can no longer produce a useful result (deadline
passed, or doomed to exceed the staleness bound after a weight jump),
(3) admits queued requests into free slots (prefill interleaved with
decoding of the other slots), (4) runs one decode chunk, and (5)
harvests finished sequences, stamping each with the weight versions it
was generated under.

The backend contract (duck-typed; satisfied by
``engine.inflight.InflightBatchingGenerator`` and by test fakes)::

    n_slots: int                   chunk: int (decode steps per chunk)
    free_slots() -> List[int]
    fill_slot(slot, int_id, prompt)
    decode_chunk(key)
    harvest() -> List[FinishedSequence]   # frees slots
    release_slot(slot)                    # abort, frees slot
    swap_params(params)
    snapshot_slot(slot) -> (tokens, logprobs)
    snapshot_slots(slots) -> {slot: (tokens, logprobs)}  # optional:
        one bundled device fetch for streaming (falls back to
        per-slot snapshot_slot when absent)

Counters make the continuous-batching win measurable: ``decode_steps``
(an upper bound -- the backend's chunk loop may early-exit) versus
``tokens_out``, which is exactly the number of decode passes a
sequential (one-request-at-a-time) server would have paid.
"""

import dataclasses
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.obs import metrics as obs_metrics
from realhf_tpu.obs import tracing
from realhf_tpu.serving import protocol
from realhf_tpu.serving.request_queue import (
    GenRequest,
    RequestQueue,
    count_expired,
)
from realhf_tpu.serving.weight_sync import WeightSync

logger = logging.getLogger("serving.scheduler")


@dataclasses.dataclass
class ServeEvent:
    """One scheduler-step outcome, routed to clients by the server.

    kinds: ``started`` (entered a slot), ``tokens`` (incremental
    delta), ``done`` (finished, data carries the FinishedRollout),
    ``stale`` (finished/evicted beyond the staleness bound),
    ``expired`` (deadline passed while decoding), ``cancelled``,
    ``rejected`` (the backend refused the prompt at prefill time --
    admission normally catches this first via ``max_prompt_len``).
    """
    kind: str
    rid: str
    data: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FinishedRollout:
    rid: str
    tokens: np.ndarray
    logprobs: np.ndarray
    no_eos: bool
    #: weight version installed when the sequence entered its slot --
    #: the behavior-policy version async RLHF consumers key on.
    weight_version: int
    #: version installed when it finished (== weight_version unless a
    #: hot-swap happened mid-stream).
    weight_version_final: int
    queued_secs: float = 0.0
    serve_secs: float = 0.0
    #: speculative-decoding accounting for this request (0/0 when the
    #: drafter is off); accept rate = spec_accepted / spec_proposed
    spec_proposed: int = 0
    spec_accepted: int = 0


@dataclasses.dataclass
class _ActiveSeq:
    int_id: int
    slot: int
    req: GenRequest
    version_start: int
    streamed: int = 0  # tokens already reported via `tokens` events


class ContinuousScheduler:
    """Admission/eviction + decode driving over a slot backend."""

    def __init__(self, backend, queue: RequestQueue,
                 weight_sync: Optional[WeightSync] = None,
                 max_staleness: Optional[int] = None,
                 stream_tokens: bool = True,
                 prefix_cache=None,
                 clock: Callable[[], float] = time.monotonic):
        self.backend = backend
        self.queue = queue
        self.weight_sync = weight_sync or WeightSync()
        self.max_staleness = max_staleness
        self.stream_tokens = stream_tokens
        # radix prefix/KV reuse (serving/prefix_cache.py). Two
        # substrate pairings are engaged, the rest degrade with a
        # warning:
        # - POOLED cache + paged backend (shared engine/kv_pool.py
        #   pool): prefix hits alias blocks, publication is refcount
        #   bookkeeping, eviction relieves decode OOM pressure;
        # - host-copy cache + dense backend implementing the prefix
        #   fill + KV export extensions (the pre-pool flow).
        self.prefix_cache = prefix_cache
        self._pooled = bool(getattr(prefix_cache, "is_pooled", False))
        backend_pool = getattr(backend, "kv_pool", None)
        if prefix_cache is None:
            self._prefix_capable = False
        elif self._pooled:
            self._prefix_capable = backend_pool is not None
            if not self._prefix_capable:
                logger.warning(
                    "pooled prefix cache configured but backend %s "
                    "has no kv_pool; running without reuse.",
                    type(backend).__name__)
            elif prefix_cache.pool is not backend_pool:
                raise ValueError(
                    "prefix cache and backend must share ONE KVPool "
                    "-- that sharing is the point of the pool")
        else:
            self._prefix_capable = (
                getattr(backend, "supports_prefix_fill", False)
                and backend_pool is None)
            if not self._prefix_capable:
                logger.warning(
                    "prefix cache configured but backend %s %s; "
                    "running without reuse.", type(backend).__name__,
                    "is paged (use PooledPrefixCache)"
                    if backend_pool is not None
                    else "lacks supports_prefix_fill")
        self._clock = clock
        self._active: Dict[int, _ActiveSeq] = {}  # int_id -> seq
        self._by_slot: Dict[int, int] = {}        # slot -> int_id
        self._next_id = 0
        #: one-deep holding slot for a request popped from the queue
        #: that the KV pool cannot admit yet (admission is gated on
        #: free blocks, not slots): retried first next step, so pool
        #: backpressure defers work instead of dropping it
        self._parked = None
        self.last_pool_stats: Optional[Dict] = None
        self.stats = dict(prefills=0, decode_chunks=0, decode_steps=0,
                          tokens_out=0, finished=0, expired=0, stale=0,
                          cancelled=0, swaps=0, fill_failed=0,
                          sequential_equiv_steps=0,
                          prefix_hits=0, prefix_misses=0,
                          prefix_evictions=0, prefix_tokens_saved=0,
                          spec_proposed=0, spec_accepted=0,
                          kv_oom_evictions=0, kv_relief_blocks=0,
                          kv_parked=0)

    def _count(self, key: str, n: int = 1):
        """Bump a scheduler counter AND its mirror in the process
        metrics registry, so the worker health surface's Prometheus
        export (``serving_<key>_total``) tracks the same numbers the
        ``stats`` command reports."""
        self.stats[key] += n
        obs_metrics.inc(f"serving_{key}_total", n)

    def _count_expired(self, req: GenRequest):
        """Deadline expiry keeps the ``stats`` mirror but carries the
        admission class on the metric
        (``serving_expired_total{class}``), matching the queue-side
        shunt in ``request_queue.pop``."""
        self.stats["expired"] += 1
        count_expired(req)

    # ------------------------------------------------------------------
    @property
    def n_live(self) -> int:
        return len(self._active)

    def idle(self) -> bool:
        return (not self._active and len(self.queue) == 0
                and self._parked is None)

    def active_rids(self) -> List[str]:
        return [s.req.rid for s in self._active.values()]

    def take_parked(self):
        """Hand back the pool-backpressure holding slot (the server's
        drain bounces it alongside the queued requests)."""
        req, self._parked = self._parked, None
        return [req] if req is not None else []

    # ------------------------------------------------------------------
    def cancel(self, rid: str) -> bool:
        """Abort an ACTIVE sequence (queued ones are cancelled at the
        queue; a pool-parked one counts too). Frees the slot
        immediately."""
        if self._parked is not None and self._parked.rid == rid:
            self._parked = None
            self._count("cancelled")
            return True
        for int_id, seq in list(self._active.items()):
            if seq.req.rid == rid:
                self._evict(int_id)
                self._count("cancelled")
                return True
        return False

    def _evict(self, int_id: int):
        seq = self._active.pop(int_id)
        self._by_slot.pop(seq.slot, None)
        self.backend.release_slot(seq.slot)

    # ------------------------------------------------------------------
    def poll_weights(self) -> Optional[int]:
        """Install pending weights, if any. Safe whenever no decode
        chunk is in flight -- ``step`` calls it between iterations, and
        the server calls it directly while idle so a pushed version
        becomes visible to admission without waiting for traffic.
        Returns the newly installed version or None."""
        swapped = self.weight_sync.poll(self.backend.swap_params)
        if swapped is not None:
            self._count("swaps")
            if self.prefix_cache is not None:
                # cached KV is a function of (tokens, WEIGHTS): donor
                # rows computed under the old version must never seed
                # a sequence under the new one
                dropped = self.prefix_cache.clear()
                if dropped:
                    self._count("prefix_evictions", dropped)
                logger.info("Weight swap to v%d flushed %d prefix-"
                            "cache block(s).", swapped, dropped)
        return swapped

    # ------------------------------------------------------------------
    def step(self, key, admit: bool = True) -> List[ServeEvent]:
        """One serve iteration; returns the events it produced."""
        events: List[ServeEvent] = []
        now = self._clock()

        # 1. weight swap between iterations
        self.poll_weights()
        version = self.weight_sync.version

        # 2. evictions: deadline / doomed-stale sequences stop burning
        #    decode steps right away
        for int_id, seq in list(self._active.items()):
            if (seq.req.deadline is not None
                    and seq.req.deadline <= now):
                self._evict(int_id)
                self._count_expired(seq.req)
                events.append(ServeEvent(protocol.EXPIRED, seq.req.rid))
            elif self._is_stale(seq, version):
                self._evict(int_id)
                self._count("stale")
                events.append(ServeEvent(protocol.STALE, seq.req.rid,
                                         self._stale_info(seq, version)))

        # 3. admission: prefill queued requests into free slots.
        #    Paged backends gate on POOL FREE BLOCKS, not just slots:
        #    a request the pool cannot take is parked (backpressure,
        #    retried next step after evict-to-pool relief) instead of
        #    consuming a slot it cannot fill.
        if admit:
            for slot in self.backend.free_slots():
                req, self._parked = self._parked, None
                if req is None:
                    req = self.queue.pop()
                if req is None:
                    break
                if req.deadline is not None and req.deadline <= now:
                    # expired while parked (queue.pop filters its own)
                    self._count_expired(req)
                    events.append(ServeEvent(protocol.EXPIRED, req.rid))
                    continue
                if not self._pool_admissible(req):
                    self._parked = req
                    self._count("kv_parked")
                    break
                req.started_at = now
                int_id = self._next_id
                self._next_id += 1
                try:
                    self._fill_slot(slot, int_id, req)
                except Exception as e:  # noqa: BLE001 - one bad
                    # request must not crash the serve loop and drop
                    # every other in-flight sequence
                    logger.error("fill_slot failed for %s: %r",
                                 req.rid, e)
                    self.backend.release_slot(slot)
                    self._count("fill_failed")
                    events.append(ServeEvent(
                        protocol.REJECTED, req.rid,
                        dict(reason=protocol.REASON_FILL_FAILED,
                             error=str(e), retry_after=None)))
                    continue
                self._active[int_id] = _ActiveSeq(
                    int_id, slot, req, version_start=version)
                self._by_slot[slot] = int_id
                self._count("prefills")
                events.append(ServeEvent(protocol.STARTED, req.rid,
                                         dict(weight_version=version)))

        # 4. one decode chunk over every live slot
        if self._active:
            # the decode-chunk span is what makes continuous batching
            # legible in the merged timeline: one span covers ALL live
            # sequences, so a Perfetto lane shows chunk-interleaved
            # serving instead of per-request decode walls
            with tracing.span("serve:decode_chunk",
                              n_live=len(self._active),
                              weight_version=version):
                self._decode_with_relief(key, events)
            self._count("decode_chunks")
            self._count("decode_steps", self.backend.chunk)

        # 5. harvest + streaming deltas. Pooled caches take BLOCK IDS
        #    (publication = refcount bookkeeping, zero device
        #    transfer); host caches take the bundled KV download; no
        #    cache, no export.
        if self._prefix_capable and self._pooled:
            harvested = self.backend.harvest(export_blocks=True)
        elif self._prefix_capable:
            harvested = self.backend.harvest(export_kv=True)
        else:
            harvested = self.backend.harvest()
        for fs in harvested:
            seq = self._active.pop(fs.request_id, None)
            if seq is None:
                # evicted this very step; still release the receiver-
                # owned block refs a pooled export attached
                if self._pooled and getattr(fs, "blocks", None):
                    self.backend.kv_pool.free(fs.blocks)
                continue
            self._by_slot.pop(seq.slot, None)
            self._count("tokens_out", len(fs.tokens))
            self._count("sequential_equiv_steps", len(fs.tokens))
            if getattr(fs, "spec_proposed", 0):
                self._count("spec_proposed", fs.spec_proposed)
                self._count("spec_accepted", fs.spec_accepted)
            self._publish_kv(seq, fs, version)
            if self._is_stale(seq, version):
                self._count("stale")
                events.append(ServeEvent(protocol.STALE, seq.req.rid,
                                         self._stale_info(seq, version)))
                continue
            self._count("finished")
            out = FinishedRollout(
                rid=seq.req.rid, tokens=fs.tokens, logprobs=fs.logprobs,
                no_eos=fs.no_eos, weight_version=seq.version_start,
                weight_version_final=version,
                spec_proposed=getattr(fs, "spec_proposed", 0),
                spec_accepted=getattr(fs, "spec_accepted", 0),
                queued_secs=max(0.0, (seq.req.started_at or now)
                                - seq.req.submitted_at),
                serve_secs=max(0.0, now - (seq.req.started_at or now)))
            self.queue.note_service_time(now - seq.req.submitted_at)
            events.append(ServeEvent(protocol.DONE, seq.req.rid,
                                     dict(result=out)))
        if self.stream_tokens:
            # one bundled device fetch for every live slot -- a
            # per-slot snapshot_slot pays one sync round-trip each
            snaps = self._snapshot_active()
            for seq in self._active.values():
                tokens, logprobs = snaps[seq.slot]
                if len(tokens) > seq.streamed:
                    events.append(ServeEvent(
                        protocol.TOKENS, seq.req.rid,
                        dict(tokens=tokens[seq.streamed:],
                             logprobs=logprobs[seq.streamed:],
                             offset=seq.streamed)))
                    seq.streamed = len(tokens)
        self._update_pool_gauges()
        return events

    # ------------------------------------------------------------------
    # KV-pool pressure management (docs/serving.md "Admission &
    # KV-pool backpressure")
    # ------------------------------------------------------------------
    def _pool_admissible(self, req: GenRequest) -> bool:
        """Admit while blocks remain: a paged backend names the
        free-list blocks a fill of this prompt will take; when the
        pool is short, evict-to-pool (unpinned prefix-cache blocks)
        runs BEFORE the request is parked."""
        if getattr(self.backend, "kv_pool", None) is None:
            return True
        need = self.backend.admission_blocks_needed(len(req.prompt))
        pool = self.backend.kv_pool
        if pool.n_free >= need:
            return True
        self._relieve_pool(need - pool.n_free)
        return pool.n_free >= need

    def _relieve_pool(self, shortfall: int) -> int:
        """Return KV blocks to the pool by evicting unpinned prefix-
        cache nodes (LRU): cold cached prefixes are the one reserve
        that costs nothing live to give back."""
        if (shortfall <= 0 or not self._pooled
                or not self._prefix_capable):
            return 0
        freed = self.prefix_cache.evict_blocks(shortfall)
        if freed:
            self._count("kv_relief_blocks", freed)
            self._count("prefix_evictions")
        return freed

    def _decode_with_relief(self, key, events: List[ServeEvent]):
        """Run the decode chunk, relieving KV-pool OOM pressure in
        escalation order: prefix-cache eviction first (evict-to-pool),
        then -- only when the cache has nothing left to give -- evict
        the YOUNGEST live sequence with an explicit ``rejected
        (reason=kv_oom)`` terminal (harvest-reject). Each loop
        iteration frees blocks or removes a sequence, so it
        terminates."""
        from realhf_tpu.engine.kv_pool import KVPoolOOM
        while True:
            try:
                self.backend.decode_chunk(key)
                return
            except KVPoolOOM as e:
                if self._relieve_pool(max(1, e.shortfall)):
                    continue
                if not self._active:
                    return
                int_id = max(self._active)
                seq = self._active[int_id]
                self._evict(int_id)
                self._count("kv_oom_evictions")
                logger.warning(
                    "KV pool exhausted mid-decode and the prefix "
                    "cache is dry; evicted youngest sequence %s.",
                    seq.req.rid)
                events.append(ServeEvent(
                    protocol.REJECTED, seq.req.rid,
                    dict(reason=protocol.REASON_KV_OOM,
                         retry_after=None)))

    def _update_pool_gauges(self):
        """Surface the pool through the PR 13 telemetry plane:
        bytes in use, free blocks, and the internal fragmentation
        ratio (1 - live rows / rows the in-use blocks could hold,
        counting both tenants' rows)."""
        stats_fn = getattr(self.backend, "kv_pool_stats", None)
        if stats_fn is None \
                or getattr(self.backend, "kv_pool", None) is None:
            return
        s = stats_fn()
        rows = s.get("rows_in_use", 0)
        if self._pooled and self._prefix_capable:
            rows += getattr(self.prefix_cache, "rows", 0)
        cap_rows = s["blocks_in_use"] * s["block_len"]
        frag = 1.0 - rows / cap_rows if cap_rows else 0.0
        frag = min(1.0, max(0.0, frag))
        obs_metrics.set_gauge("serving_kv_pool_bytes_in_use",
                              s["bytes_in_use"])
        obs_metrics.set_gauge("serving_kv_pool_blocks_free",
                              s["blocks_free"])
        obs_metrics.set_gauge("serving_kv_pool_frag_ratio", frag)
        self.last_pool_stats = dict(s, frag_ratio=round(frag, 4))

    # ------------------------------------------------------------------
    def _fill_slot(self, slot: int, int_id: int, req: GenRequest):
        """Prefill a request into a slot, consulting the radix prefix
        cache first: on a hit, the donor seeds the slot (pooled: the
        cached blocks are ALIASED into the slot's block table; host:
        the donor KV is copied in) and only the uncached suffix runs
        the forward. The donor pin lives for exactly the match->fill
        window."""
        if not self._prefix_capable:
            self.backend.fill_slot(slot, int_id, req.prompt)
            return
        # the model still needs >= 1 real token to produce the hidden
        # state feeding the first decode step
        m = self.prefix_cache.match(req.prompt,
                                    max_len=len(req.prompt) - 1)
        try:
            if m.cached_len > 0:
                self._count("prefix_hits")
                self._count("prefix_tokens_saved", m.cached_len)
                if self._pooled:
                    self.backend.fill_slot(
                        slot, int_id, req.prompt,
                        cached_len=m.cached_len,
                        cached_blocks=list(m.blocks))
                else:
                    self.backend.fill_slot(slot, int_id, req.prompt,
                                           cached_len=m.cached_len,
                                           prefix_kv=(m.k, m.v))
            else:
                self._count("prefix_misses")
                self.backend.fill_slot(slot, int_id, req.prompt)
        finally:
            self.prefix_cache.release(m.handle)

    def _publish_kv(self, seq: _ActiveSeq, fs, version: int):
        """Credit a finished sequence's KV back to the prefix cache.
        Skipped when the sequence lived through a weight swap: its
        rows mix weight versions and must not seed future requests.
        Pooled flow: ``fs.blocks`` carry receiver-owned pool refs --
        the cache increfs what it keeps, then the refs are ALWAYS
        freed here, publication or not."""
        if self._pooled:
            blocks = getattr(fs, "blocks", None)
            if not self._prefix_capable or blocks is None:
                return
            try:
                if seq.version_start == version:
                    ev0 = self.prefix_cache.stats["evictions"]
                    self.prefix_cache.insert(
                        np.concatenate(
                            [np.asarray(seq.req.prompt, np.int64),
                             np.asarray(fs.tokens, np.int64)]),
                        blocks=blocks)
                    ev = self.prefix_cache.stats["evictions"] - ev0
                    if ev:
                        self._count("prefix_evictions", ev)
            finally:
                self.backend.kv_pool.free(blocks)
            obs_metrics.set_gauge("serving_prefix_bytes",
                                  self.prefix_cache.bytes_used)
            return
        if (not self._prefix_capable or getattr(fs, "kv", None) is None
                or seq.version_start != version):
            return
        ev0 = self.prefix_cache.stats["evictions"]
        self.prefix_cache.insert(
            np.concatenate([np.asarray(seq.req.prompt, np.int64),
                            np.asarray(fs.tokens, np.int64)]),
            fs.kv[0], fs.kv[1])
        ev = self.prefix_cache.stats["evictions"] - ev0
        if ev:
            self._count("prefix_evictions", ev)
        obs_metrics.set_gauge("serving_prefix_bytes",
                              self.prefix_cache.bytes_used)

    # ------------------------------------------------------------------
    def _snapshot_active(self) -> Dict[int, tuple]:
        """slot -> (tokens, logprobs) for every live slot; one bundled
        transfer via the backend's ``snapshot_slots`` when it has one
        (test fakes may only provide the per-slot form)."""
        slots = [seq.slot for seq in self._active.values()]
        batched = getattr(self.backend, "snapshot_slots", None)
        if batched is not None:
            return batched(slots)
        return {s: self.backend.snapshot_slot(s) for s in slots}

    def _is_stale(self, seq: _ActiveSeq, version: int) -> bool:
        return (self.max_staleness is not None
                and version - seq.version_start > self.max_staleness)

    def _stale_info(self, seq: _ActiveSeq, version: int) -> dict:
        return dict(weight_version=seq.version_start,
                    current_version=version,
                    max_staleness=self.max_staleness)
