"""GenServerWorker: a rollout server in the worker/scheduler stack.

The serving subsystem's process shell: a :class:`Worker` whose poll
loop IS the serve loop. It inherits the full PR-1 fault-tolerance
plumbing for free -- heartbeat beacon, status publication, watchdog
attribution, scheduler supervision (``apps.main.run_serve``) -- so a
hung generation server is detected and named like any other worker.

Extra worker commands beyond the base set:

- ``stats``: the server's scheduler/queue counters.
- ``update_weights {version, path?}``: hot-swap. With ``path``, loads
  an HF-format checkpoint and pushes it through WeightSync; without,
  re-pushes the current weights under the new version (a pure version
  bump -- the trainer advanced but this role's weights are refreshed
  out-of-band, or a staleness drill).
- ``drain``: early graceful drain without exiting.
"""

import os
import pickle
from typing import Any, Dict

from realhf_tpu.base import constants, logging, seeding
from realhf_tpu.system import worker_base

logger = logging.getLogger("gen_server_worker", "system")


class GenServerWorker(worker_base.Worker):
    """One RolloutServer over one model role (see docs/serving.md)."""

    def _configure(self, config: Dict):
        from realhf_tpu.api.experiment import ExperimentSpec
        from realhf_tpu.engine.inflight import InflightBatchingGenerator
        from realhf_tpu.ops.sampling import GenerationHyperparameters
        from realhf_tpu.serving.fleet import FleetRegistry
        from realhf_tpu.serving.prefix_cache import RadixPrefixCache
        from realhf_tpu.serving.request_queue import RequestQueue
        from realhf_tpu.serving.server import RolloutServer
        from realhf_tpu.system.model_host import build_model

        with open(config["spec_path"], "rb") as f:
            spec: ExperimentSpec = pickle.load(f)
        self.spec = spec
        self.server_index = int(config.get("server_index", 0))
        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)
        seeding.set_random_seed(spec.seed + 1000 + self.server_index)

        sv = spec.serving
        if sv is None:
            raise ValueError(
                "GenServerWorker needs ExperimentSpec.serving (see "
                "experiments/serve_exp.py).")
        mspec = spec.models[sv.model_role]
        self.model = build_model(sv.model_role, mspec, tokenizer=None,
                                 total_steps=1, init_seed=spec.seed)
        gconfig = GenerationHyperparameters(
            **dict(sv.gconfig, force_no_logits_mask=True))
        # hot-path knobs (docs/serving.md "Prefix cache & speculative
        # decoding"): REALHF_TPU_SPEC_K overrides the spec for drills
        spec_k = int(os.environ.get("REALHF_TPU_SPEC_K",
                                    sv.spec_decode_k))
        # paged KV pool (docs/perf.md "Paged KV & quantization"):
        # int8 implies the pool -- dequant-on-read lives in its
        # gather path
        kv_pool = None
        paged = sv.paged_kv or sv.kv_cache_dtype == "int8"
        if paged:
            from realhf_tpu.engine.kv_pool import KVPool
            from realhf_tpu.models import transformer as T
            cache_len = T.round_cache_len(
                sv.max_prompt_len + gconfig.max_new_tokens)
            n_blocks = sv.kv_pool_blocks or sv.n_slots * (
                -(-cache_len // sv.kv_block_len))
            kv_pool = KVPool(self.model.config, n_blocks,
                             sv.kv_block_len,
                             dtype=sv.kv_cache_dtype or "fp32")
            logger.info(
                "KV pool: %d blocks x %d tokens (%d bytes, dtype=%s) "
                "for %d slots.", n_blocks, sv.kv_block_len,
                n_blocks * kv_pool.block_bytes, kv_pool.dtype,
                sv.n_slots)
        backend = InflightBatchingGenerator(
            self.model.config, self.model.engine.params, gconfig,
            n_slots=sv.n_slots, max_prompt_len=sv.max_prompt_len,
            eos_token_id=sv.eos_token_id, pad_token_id=sv.pad_token_id,
            chunk_size=sv.chunk_size, spec_decode_k=spec_k,
            kv_pool=kv_pool,
            kv_cache_dtype=None if paged else sv.kv_cache_dtype)
        if sv.prefix_cache_bytes <= 0:
            prefix_cache = None
        elif kv_pool is not None:
            # the pool is the one KV allocator BOTH tenants share:
            # cached prefixes are pool blocks, hits alias them into
            # slot tables, eviction relieves decode OOM pressure
            from realhf_tpu.serving.prefix_cache import (
                PooledPrefixCache,
            )
            prefix_cache = PooledPrefixCache(kv_pool,
                                             sv.prefix_cache_bytes)
        else:
            prefix_cache = RadixPrefixCache(sv.prefix_cache_bytes)
        # fleet mode: register this replica under a keepalive lease so
        # the FleetRouter discovers it (and fails its work over the
        # moment the lease lapses)
        fleet = FleetRegistry(
            spec.experiment_name, spec.trial_name,
            lease_ttl=sv.lease_ttl_secs) if sv.fleet_router else None
        grow_advisor = None
        if getattr(sv, "autoscale_queue_threshold", 0) > 0:
            from realhf_tpu.system.elastic import GrowAdvisor
            grow_advisor = GrowAdvisor(sv.autoscale_queue_threshold)
        self.rollout_server = RolloutServer(
            backend,
            experiment_name=spec.experiment_name,
            trial_name=spec.trial_name,
            server_name=self.worker_name,
            queue=RequestQueue(max_depth=sv.max_queue_depth,
                               n_slots=sv.n_slots),
            max_staleness=sv.max_staleness,
            stream_tokens=sv.stream_tokens,
            prefix_cache=prefix_cache,
            fleet=fleet,
            grow_advisor=grow_advisor,
            drain_deadline_secs=sv.drain_deadline_secs,
            seed=spec.seed + self.server_index)
        self._drain_timeout = sv.drain_timeout_secs
        if fleet is not None:
            # ride the heartbeat beacon: the fleet lease must keep
            # beating while the serve loop sits in a long jit compile,
            # exactly like the PR-1 worker heartbeat itself
            self.server.add_beat_hook(self.rollout_server.lease_beat)
        logger.info("Gen server %s configured: role=%s slots=%d "
                    "staleness=%s fleet=%s prefix_cache=%dB "
                    "spec_k=%d.", self.worker_name, sv.model_role,
                    sv.n_slots, sv.max_staleness, sv.fleet_router,
                    sv.prefix_cache_bytes, spec_k)
        return dict(address=self.rollout_server.address)

    # ------------------------------------------------------------------
    def _poll(self) -> worker_base.PollResult:
        n = self.rollout_server.serve_step(poll_timeout=0.02)
        return worker_base.PollResult(sample_count=n,
                                      batch_count=1 if n else 0)

    def _handle_command(self, cmd: str, kwargs: Dict) -> Any:
        if cmd == "stats":
            return self.rollout_server.stats()
        if cmd == "update_weights":
            return self._update_weights(**(kwargs or {}))
        if cmd == "update_weights_chunks":
            return self._update_weights_chunks(**(kwargs or {}))
        if cmd == "drain":
            self.rollout_server.drain(timeout=self._drain_timeout)
            return self.rollout_server.stats()
        return super()._handle_command(cmd, kwargs)

    def _health_extra(self) -> Dict:
        """Serving fields for /healthz (obs/http.py): drain state
        (flips the endpoint to 503/DRAINING the moment a drain
        starts), the fleet lease's fencing epoch, weight version, and
        load figures."""
        rs = getattr(self, "rollout_server", None)
        if rs is None:
            return {}
        return dict(draining=bool(rs._draining),
                    fencing_epoch=rs.fencing_epoch,
                    weight_version=rs.weight_sync.version,
                    queue_depth=len(rs.queue),
                    live_slots=rs.scheduler.n_live)

    def _preempt_hook(self, grace: float):
        """Drain-on-preempt (docs/serving.md "Shutdown"): on a
        preemption notice the server stops admitting, bounces queued
        requests with ``protocol.DRAINING`` (the wire kinds and
        reasons are declared in serving/protocol.py, which is
        normative), and finishes (or cancels) in-flight
        sequences inside the grace window -- clients see terminal
        events, never a socket that silently vanished. The remaining
        grace after the drain lets late fetches of the final events
        complete before the PREEMPTED exit."""
        budget = max(0.0, min(self._drain_timeout, grace * 0.8))
        logger.warning("Gen server %s preempted: draining within "
                       "%.1fs.", self.worker_name, budget)
        self.rollout_server.drain(timeout=budget)

    def _update_weights(self, version: int, path: str = None) -> Dict:
        if path is not None:
            from realhf_tpu.models.hf import load_hf_checkpoint
            _, params = load_hf_checkpoint(
                path, self.spec.models[self.spec.serving.model_role]
                .hf_family)
        else:
            params = self.rollout_server.scheduler.backend.params
        self.rollout_server.weight_sync.push(params, version)
        return dict(pending_version=version,
                    installed_version=self.rollout_server.weight_sync.version)

    def _update_weights_chunks(self, message: Dict) -> Dict:
        """Chunked weight push (docs/serving.md "Chunked weight
        distribution"): apply one ``WeightDistributor`` payload. The
        receiver keeps leaf state between pushes, so a dedup'd push
        still installs a full tree; a missing-base reply makes the
        distributor resync this replica with a direct full push."""
        if getattr(self, "_chunk_receiver", None) is None:
            from realhf_tpu.serving.weight_dist import (
                ChunkedWeightReceiver,
            )
            self._chunk_receiver = ChunkedWeightReceiver(
                self.rollout_server.weight_sync)
        return self._chunk_receiver.apply(message)

    def _exit_hook(self):
        if getattr(self, "rollout_server", None) is not None:
            self.rollout_server.drain(timeout=self._drain_timeout)
            self.rollout_server.close()


class RouterWorker(worker_base.Worker):
    """The serving fleet's front door: one FleetRouter in the worker
    stack (docs/serving.md "Fleet, failover & circuit breakers").

    Same PR-1 plumbing as every worker (heartbeats, watchdog
    attribution, preemption notices); the poll loop IS the routing
    loop. Clients rendezvous exactly like against a single server::

        RolloutClient(experiment_name=..., trial_name=...,
                      server_name="router")

    Extra commands: ``stats`` (router + per-replica breaker view),
    ``drain`` (stop admission, flush in-flight), ``probe {name}``
    (hedged blocking health check of one replica).
    """

    def _configure(self, config: Dict):
        from realhf_tpu.api.experiment import ExperimentSpec
        from realhf_tpu.serving.fleet import FleetRegistry
        from realhf_tpu.serving.router import FleetRouter

        with open(config["spec_path"], "rb") as f:
            spec: ExperimentSpec = pickle.load(f)
        self.spec = spec
        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)
        sv = spec.serving
        if sv is None:
            raise ValueError(
                "RouterWorker needs ExperimentSpec.serving (see "
                "experiments/serve_exp.py).")
        registry = FleetRegistry(spec.experiment_name, spec.trial_name,
                                 lease_ttl=sv.lease_ttl_secs)
        router_kw = dict(
            router_name=self.worker_name,
            experiment_name=spec.experiment_name,
            trial_name=spec.trial_name,
            max_pending=sv.router_max_pending,
            dispatch_timeout=sv.router_dispatch_timeout_secs,
            response_timeout=sv.router_response_timeout_secs,
            hedge_delay=sv.router_hedge_delay_secs,
            max_hedges=sv.router_max_hedges,
            breaker_failures=sv.router_breaker_failures,
            breaker_cooldown=sv.router_breaker_cooldown_secs,
            affinity_prefix_len=sv.router_affinity_prefix_len,
            fleet_poll_interval=min(0.5, sv.lease_ttl_secs / 4.0))
        if getattr(sv, "n_routers", 1) > 1:
            # sharded router plane (docs/serving.md "Sharded router
            # plane"): this shard registers its own lease/epoch in the
            # registry and owns a consistent-hash slice of rid space;
            # clients discover the ring through the registry
            # (ShardedRolloutClient), so no singleton rendezvous key
            from realhf_tpu.serving.router_shard import ShardedRouter
            self.router = ShardedRouter(registry, **router_kw)
        else:
            self.router = FleetRouter(registry, **router_kw)
        self._drain_timeout = sv.drain_timeout_secs
        logger.info("Router %s configured: lease_ttl=%.1fs hedge=%s "
                    "breaker=%d/%.1fs.", self.worker_name,
                    sv.lease_ttl_secs, sv.router_hedge_delay_secs,
                    sv.router_breaker_failures,
                    sv.router_breaker_cooldown_secs)
        return dict(address=self.router.address)

    def _poll(self) -> worker_base.PollResult:
        n = self.router.route_step(poll_timeout=0.02)
        return worker_base.PollResult(sample_count=n,
                                      batch_count=1 if n else 0)

    def _handle_command(self, cmd: str, kwargs: Dict) -> Any:
        if cmd == "stats":
            return self.router.stats()
        if cmd == "drain":
            self.router.drain(timeout=self._drain_timeout)
            return self.router.stats()
        if cmd == "probe":
            return dict(alive=self.router.probe(**(kwargs or {})))
        return super()._handle_command(cmd, kwargs)

    def _health_extra(self) -> Dict:
        router = getattr(self, "router", None)
        if router is None:
            return {}
        replicas = router._replicas
        return dict(draining=bool(router._draining),
                    pending=len(router._pending),
                    inflight=len(router._requests),
                    replicas_live=sum(1 for r in replicas.values()
                                      if not r.lost),
                    replicas_healthy=sum(
                        1 for r in replicas.values()
                        if not r.lost and not r.retiring
                        and r.breaker.allow()))

    def _preempt_hook(self, grace: float):
        budget = max(0.0, min(self._drain_timeout, grace * 0.8))
        logger.warning("Router %s preempted: draining within %.1fs.",
                       self.worker_name, budget)
        self.router.drain(timeout=budget)

    def _exit_hook(self):
        if getattr(self, "router", None) is not None:
            self.router.drain(timeout=self._drain_timeout)
            self.router.close()


class GatewayWorker(worker_base.Worker):
    """The HTTP front door in the worker stack (docs/serving.md
    "Front door"): one :class:`~realhf_tpu.serving.gateway.
    GatewayServer` exposing OpenAI-compatible streaming
    ``/v1/completions`` over SSE, fronting the router plane with
    per-tenant quotas, SLO classes, and deadline-aware shedding.

    The HTTP server runs on its own daemon threads; the worker's poll
    loop only keeps the heartbeat/watchdog plumbing fed and reports
    request throughput. Extra commands: ``stats`` (gateway + policy +
    brownout view), ``drain`` (refuse new admissions with 503).
    """

    def _configure(self, config: Dict):
        from realhf_tpu.api.experiment import ExperimentSpec
        from realhf_tpu.base import name_resolve
        from realhf_tpu.serving.gateway import (
            BrownoutLadder,
            GatewayPolicy,
            GatewayServer,
            RouterLoadProbe,
            gateway_http_key,
            telemetry_metrics_fetch,
        )

        with open(config["spec_path"], "rb") as f:
            spec: ExperimentSpec = pickle.load(f)
        self.spec = spec
        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)
        sv = spec.serving
        if sv is None:
            raise ValueError(
                "GatewayWorker needs ExperimentSpec.serving (see "
                "experiments/serve_exp.py).")

        # one RolloutClient-shaped backend per pooled connection:
        # sharded plane -> ShardedRolloutClient (ring discovery +
        # failover), fleet -> the router, single server -> direct
        fleet = bool(sv.fleet_router)
        sharded = fleet and getattr(sv, "n_routers", 1) > 1
        if sharded:
            from realhf_tpu.serving.fleet import FleetRegistry
            from realhf_tpu.serving.router_shard import (
                ShardedRolloutClient,
            )

            def client_factory():
                return ShardedRolloutClient(FleetRegistry(
                    spec.experiment_name, spec.trial_name,
                    lease_ttl=sv.lease_ttl_secs))
        else:
            from realhf_tpu.serving.server import RolloutClient
            upstream = "router/0" if fleet else "rollout/0"

            def client_factory():
                return RolloutClient(
                    experiment_name=spec.experiment_name,
                    trial_name=spec.trial_name,
                    server_name=upstream)

        # the shed decision reads the router plane's own telemetry
        # (queue depth gauges + latency p95) -- no new signal path
        load_probe = None
        if fleet:
            load_probe = RouterLoadProbe(
                telemetry_metrics_fetch(spec.experiment_name,
                                        spec.trial_name, "router/0"),
                n_slots=sv.n_servers * sv.n_slots)
        policy = GatewayPolicy(
            tenants=dict(sv.gateway_tenants),
            default_rate=sv.gateway_tenant_rate,
            default_burst=sv.gateway_tenant_burst,
            interactive_slo_secs=sv.gateway_interactive_slo_secs,
            batch_slo_secs=sv.gateway_batch_slo_secs,
            trim_max_new_tokens=sv.gateway_trim_max_new_tokens,
            load_probe=load_probe,
            brownout=BrownoutLadder())
        self.gateway = GatewayServer(
            client_factory, policy=policy,
            port=sv.gateway_port, process_name=self.worker_name,
            stream_timeout=sv.gateway_stream_timeout_secs).start()
        name_resolve.add(
            gateway_http_key(spec.experiment_name, spec.trial_name,
                             self.worker_name),
            self.gateway.address, replace=True)
        self._drain_timeout = sv.drain_timeout_secs
        self._last_requests = 0
        logger.info("Gateway %s serving on %s (fleet=%s sharded=%s).",
                    self.worker_name, self.gateway.address, fleet,
                    sharded)
        return dict(address=self.gateway.address)

    def _poll(self) -> worker_base.PollResult:
        n = self.gateway.stats["http_requests"] - self._last_requests
        self._last_requests += n
        return worker_base.PollResult(sample_count=n,
                                      batch_count=1 if n else 0)

    def _handle_command(self, cmd: str, kwargs: Dict) -> Any:
        if cmd == "stats":
            return dict(gateway=dict(self.gateway.stats),
                        policy=dict(self.gateway.policy.stats),
                        brownout_level=self.gateway.policy.brownout
                        .level)
        if cmd == "drain":
            self.gateway.start_drain()
            return dict(self.gateway.stats)
        return super()._handle_command(cmd, kwargs)

    def _health_extra(self) -> Dict:
        gw = getattr(self, "gateway", None)
        if gw is None:
            return {}
        return dict(draining=bool(gw._draining),
                    http_requests=gw.stats["http_requests"],
                    streams=gw.stats["streams"],
                    brownout_level=gw.policy.brownout.level)

    def _preempt_hook(self, grace: float):
        logger.warning("Gateway %s preempted: refusing new "
                       "admissions.", self.worker_name)
        self.gateway.start_drain()

    def _exit_hook(self):
        if getattr(self, "gateway", None) is not None:
            self.gateway.start_drain()
            self.gateway.stop()
