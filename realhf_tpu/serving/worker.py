"""GenServerWorker: a rollout server in the worker/scheduler stack.

The serving subsystem's process shell: a :class:`Worker` whose poll
loop IS the serve loop. It inherits the full PR-1 fault-tolerance
plumbing for free -- heartbeat beacon, status publication, watchdog
attribution, scheduler supervision (``apps.main.run_serve``) -- so a
hung generation server is detected and named like any other worker.

Extra worker commands beyond the base set:

- ``stats``: the server's scheduler/queue counters.
- ``update_weights {version, path?}``: hot-swap. With ``path``, loads
  an HF-format checkpoint and pushes it through WeightSync; without,
  re-pushes the current weights under the new version (a pure version
  bump -- the trainer advanced but this role's weights are refreshed
  out-of-band, or a staleness drill).
- ``drain``: early graceful drain without exiting.
"""

import pickle
from typing import Any, Dict

from realhf_tpu.base import constants, logging, seeding
from realhf_tpu.system import worker_base

logger = logging.getLogger("gen_server_worker", "system")


class GenServerWorker(worker_base.Worker):
    """One RolloutServer over one model role (see docs/serving.md)."""

    def _configure(self, config: Dict):
        from realhf_tpu.api.experiment import ExperimentSpec
        from realhf_tpu.engine.inflight import InflightBatchingGenerator
        from realhf_tpu.ops.sampling import GenerationHyperparameters
        from realhf_tpu.serving.request_queue import RequestQueue
        from realhf_tpu.serving.server import RolloutServer
        from realhf_tpu.system.model_host import build_model

        with open(config["spec_path"], "rb") as f:
            spec: ExperimentSpec = pickle.load(f)
        self.spec = spec
        self.server_index = int(config.get("server_index", 0))
        constants.set_experiment_trial_names(spec.experiment_name,
                                             spec.trial_name)
        seeding.set_random_seed(spec.seed + 1000 + self.server_index)

        sv = spec.serving
        if sv is None:
            raise ValueError(
                "GenServerWorker needs ExperimentSpec.serving (see "
                "experiments/serve_exp.py).")
        mspec = spec.models[sv.model_role]
        self.model = build_model(sv.model_role, mspec, tokenizer=None,
                                 total_steps=1, init_seed=spec.seed)
        gconfig = GenerationHyperparameters(
            **dict(sv.gconfig, force_no_logits_mask=True))
        backend = InflightBatchingGenerator(
            self.model.config, self.model.engine.params, gconfig,
            n_slots=sv.n_slots, max_prompt_len=sv.max_prompt_len,
            eos_token_id=sv.eos_token_id, pad_token_id=sv.pad_token_id,
            chunk_size=sv.chunk_size)
        self.rollout_server = RolloutServer(
            backend,
            experiment_name=spec.experiment_name,
            trial_name=spec.trial_name,
            server_name=self.worker_name,
            queue=RequestQueue(max_depth=sv.max_queue_depth,
                               n_slots=sv.n_slots),
            max_staleness=sv.max_staleness,
            stream_tokens=sv.stream_tokens,
            seed=spec.seed + self.server_index)
        self._drain_timeout = sv.drain_timeout_secs
        logger.info("Gen server %s configured: role=%s slots=%d "
                    "staleness=%s.", self.worker_name, sv.model_role,
                    sv.n_slots, sv.max_staleness)
        return dict(address=self.rollout_server.address)

    # ------------------------------------------------------------------
    def _poll(self) -> worker_base.PollResult:
        n = self.rollout_server.serve_step(poll_timeout=0.02)
        return worker_base.PollResult(sample_count=n,
                                      batch_count=1 if n else 0)

    def _handle_command(self, cmd: str, kwargs: Dict) -> Any:
        if cmd == "stats":
            return self.rollout_server.stats()
        if cmd == "update_weights":
            return self._update_weights(**(kwargs or {}))
        if cmd == "drain":
            self.rollout_server.drain(timeout=self._drain_timeout)
            return self.rollout_server.stats()
        return super()._handle_command(cmd, kwargs)

    def _preempt_hook(self, grace: float):
        """Drain-on-preempt (docs/serving.md "Shutdown"): on a
        preemption notice the server stops admitting, bounces queued
        requests with "draining", and finishes (or cancels) in-flight
        sequences inside the grace window -- clients see terminal
        events, never a socket that silently vanished. The remaining
        grace after the drain lets late fetches of the final events
        complete before the PREEMPTED exit."""
        budget = max(0.0, min(self._drain_timeout, grace * 0.8))
        logger.warning("Gen server %s preempted: draining within "
                       "%.1fs.", self.worker_name, budget)
        self.rollout_server.drain(timeout=budget)

    def _update_weights(self, version: int, path: str = None) -> Dict:
        if path is not None:
            from realhf_tpu.models.hf import load_hf_checkpoint
            _, params = load_hf_checkpoint(
                path, self.spec.models[self.spec.serving.model_role]
                .hf_family)
        else:
            params = self.rollout_server.scheduler.backend.params
        self.rollout_server.weight_sync.push(params, version)
        return dict(pending_version=version,
                    installed_version=self.rollout_server.weight_sync.version)

    def _exit_hook(self):
        if getattr(self, "rollout_server", None) is not None:
            self.rollout_server.drain(timeout=self._drain_timeout)
            self.rollout_server.close()
