"""Million-user HTTP front door: ``/v1/completions`` over SSE.

Real user traffic at the ROADMAP scale arrives as HTTP, not as the
custom ZMQ wire ``serving/server.py`` speaks. :class:`GatewayServer`
is the production ingress: an OpenAI-compatible streaming completions
endpoint on the same stdlib ``ThreadingHTTPServer`` plane as the
telemetry endpoints (``obs/http.py``), fronting the FleetRouter /
sharded router plane through ordinary :class:`RolloutClient`\\ s.

The robustness machinery is the point, not the plumbing
(docs/serving.md "Front door"):

- **Per-tenant token buckets** (:class:`TokenBucket`, injectable
  clock, no sleeps): a flooding tenant exhausts its own
  ``rejected(reason=quota)`` budget, never another tenant's latency.
- **SLO classes**: the request's ``slo`` field
  (``interactive``/``batch``, declared in
  ``protocol.GATEWAY_SLO_CLASSES``) maps onto the PR 2 admission
  queue's priority classes, so latency-bound traffic overtakes
  throughput-bound traffic end to end.
- **Deadline-aware shedding BEFORE dispatch**: a request that cannot
  meet its deadline given the current queue depth and the latency p95
  from the PR 13 histograms is rejected ``429 Retry-After``
  (``reason=deadline_unmeetable``) instead of burning a decode slot
  producing an answer nobody will wait for.
- **Brownout ladder** (:class:`BrownoutLadder`) under sustained
  overload: shed batch first, then trim ``max_tokens``, interactive
  last -- graceful degradation instead of collapse.

Exactly-once terminal on the HTTP surface: a shed request's 4xx/5xx
reply IS its terminal (the router never sees the rid); an admitted
request relays exactly the wire terminal the client-request state
machine guarantees (``protocol.GATEWAY_REQUEST``). Status mapping is
declared in ``protocol.GATEWAY_HTTP_STATUS`` /
``GATEWAY_REJECT_STATUS``; the graft-lint wire checker covers the SSE
emit sites (``_sse_event``) like any other send path.

Every decision is measured on the telemetry plane:
``serving_gateway_*`` and ``tenant_*`` metrics (catalog:
docs/observability.md).
"""

import dataclasses
import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.obs import metrics as obs_metrics
from realhf_tpu.obs.http import (
    BoundedRequestHandler,
    parse_prometheus_text,
    prom_histogram_quantile,
    prom_scalar,
)
from realhf_tpu.serving import protocol

logger = logging.getLogger("serving.gateway")

#: completion request bodies are prompts + knobs, not uploads
MAX_BODY_BYTES = 1 << 20

#: service-seconds fallback while the latency histogram is empty
DEFAULT_SERVICE_SECS = 1.0

# Brownout ladder rungs (shed cheapest traffic first, interactive
# absolutely last -- docs/serving.md "Front door").
LEVEL_NORMAL = 0
LEVEL_SHED_BATCH = 1
LEVEL_TRIM = 2
LEVEL_SHED_ALL = 3


# ----------------------------------------------------------------------
# Token buckets (per-tenant admission quota)
# ----------------------------------------------------------------------
class TokenBucket:
    """Classic token bucket on an injectable clock.

    ``rate`` tokens/second refill up to ``burst`` capacity; a take
    that cannot be covered fails immediately with a
    :meth:`retry_after` hint (no sleeping, no background thread --
    refill is computed lazily from clock deltas, so tests drive it
    with a fake clock).
    """

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._level = float(burst)
        self._stamp = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float):
        if now > self._stamp:
            self._level = min(
                self.burst, self._level + (now - self._stamp) * self.rate)
        self._stamp = now

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill(self._clock())
            if self._level >= n:
                self._level -= n
                return True
            return False

    def available(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._level

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until a take of ``n`` could succeed."""
        with self._lock:
            self._refill(self._clock())
            short = n - self._level
            if short <= 0:
                return 0.0
            if self.rate <= 0:
                return float("inf")
            return short / self.rate


# ----------------------------------------------------------------------
# Load estimation (queue depth + latency p95 -> expected wait)
# ----------------------------------------------------------------------
@dataclasses.dataclass
class LoadSnapshot:
    """What the shed decision sees: backlog and service speed."""
    queue_depth: int = 0
    n_slots: int = 1
    p95_secs: Optional[float] = None
    #: optional per-priority-class backlog (priority int -> waiting
    #: count); lets the wait estimate honor the admission queue's
    #: strict class ordering. None = only the total is known.
    depth_by_class: Optional[Dict[int, int]] = None

    def depth_ahead(self, priority: Optional[int] = None) -> int:
        """Backlog an arrival of ``priority`` actually waits behind:
        the admission queue serves classes strictly in order, so an
        interactive request jumps every batch entry. Without
        per-class depths, the total is the conservative answer."""
        if priority is None or self.depth_by_class is None:
            return self.queue_depth
        return sum(n for p, n in self.depth_by_class.items()
                   if p <= priority)

    def estimated_wait(self, priority: Optional[int] = None) -> float:
        """Expected queue-to-done seconds for a NEW arrival of
        ``priority`` (None = worst case): the backlog ahead of it
        drains ``n_slots`` wide at ~p95 per sequence, plus the
        request's own service time."""
        p95 = self.p95_secs if self.p95_secs else DEFAULT_SERVICE_SECS
        return p95 * (self.depth_ahead(priority)
                      / max(1, self.n_slots) + 1.0)


class RouterLoadProbe:
    """LoadSnapshot from a router's ``/metrics`` endpoint (the PR 13
    telemetry plane): queue depth from the ``router_pending`` /
    ``router_inflight`` gauges, p95 from the
    ``router_latency_seconds`` histogram buckets -- exactly what a
    real Prometheus would compute. ``fetch`` returns the exposition
    text (or None); results are cached for ``cache_secs`` so a
    request storm does not turn into a scrape storm."""

    def __init__(self, fetch: Callable[[], Optional[str]], *,
                 n_slots: int = 1, cache_secs: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self._fetch = fetch
        self.n_slots = max(1, n_slots)
        self._cache_secs = cache_secs
        self._clock = clock
        self._cached = LoadSnapshot(n_slots=self.n_slots)
        self._stamp: Optional[float] = None
        self._lock = threading.Lock()

    def __call__(self) -> LoadSnapshot:
        now = self._clock()
        with self._lock:
            if self._stamp is not None \
                    and now - self._stamp < self._cache_secs:
                return self._cached
            self._stamp = now
        try:
            text = self._fetch()
        except Exception as e:  # noqa: BLE001 - a failed scrape must
            # not fail admission; the stale snapshot is still sane
            logger.warning("Gateway load probe failed: %r", e)
            text = None
        if text is None:
            return self._cached
        fams = parse_prometheus_text(text)
        depth = prom_scalar(fams, "router_pending", agg="last") \
            + prom_scalar(fams, "router_inflight", agg="last")
        snap = LoadSnapshot(
            queue_depth=int(depth), n_slots=self.n_slots,
            p95_secs=prom_histogram_quantile(
                fams, "router_latency_seconds", 0.95))
        with self._lock:
            self._cached = snap
        return snap


# ----------------------------------------------------------------------
# Brownout ladder
# ----------------------------------------------------------------------
class BrownoutLadder:
    """Hysteretic overload ladder: pressure (estimated wait over the
    interactive SLO) sustained above ``up_pressure`` for
    ``sustain_secs`` climbs one rung; pressure below
    ``down_pressure`` for ``cool_secs`` descends one. The rungs
    (module constants): 0 normal, 1 shed batch, 2 also trim
    ``max_tokens``, 3 shed interactive too -- the last resort.
    Injectable clock, no threads."""

    def __init__(self, *, up_pressure: float = 1.0,
                 down_pressure: float = 0.5,
                 sustain_secs: float = 1.0, cool_secs: float = 3.0,
                 max_level: int = LEVEL_SHED_ALL,
                 clock: Callable[[], float] = time.monotonic):
        self.up_pressure = up_pressure
        self.down_pressure = down_pressure
        self.sustain_secs = sustain_secs
        self.cool_secs = cool_secs
        self.max_level = max_level
        self._clock = clock
        self._lock = threading.Lock()
        self.level = LEVEL_NORMAL
        self._hot_since: Optional[float] = None
        self._cool_since: Optional[float] = None

    def observe(self, pressure: float) -> int:
        """Feed one pressure sample; returns the (possibly new)
        level. Climbing re-arms the sustain timer so each rung needs
        its own sustained evidence."""
        now = self._clock()
        with self._lock:
            if pressure > self.up_pressure:
                self._cool_since = None
                if self._hot_since is None:
                    self._hot_since = now
                elif now - self._hot_since >= self.sustain_secs \
                        and self.level < self.max_level:
                    self.level += 1
                    self._hot_since = now
                    logger.warning(
                        "Gateway brownout escalated to level %d "
                        "(pressure %.2f).", self.level, pressure)
            elif pressure < self.down_pressure:
                self._hot_since = None
                if self._cool_since is None:
                    self._cool_since = now
                elif now - self._cool_since >= self.cool_secs \
                        and self.level > LEVEL_NORMAL:
                    self.level -= 1
                    self._cool_since = now
                    logger.info("Gateway brownout eased to level %d.",
                                self.level)
            else:
                self._hot_since = None
                self._cool_since = None
            obs_metrics.set_gauge("serving_gateway_brownout_level",
                                  self.level)
            return self.level


# ----------------------------------------------------------------------
# Admission policy (quota -> brownout -> deadline feasibility)
# ----------------------------------------------------------------------
@dataclasses.dataclass
class GatewayVerdict:
    """One admission decision; mirrors the queue's AdmissionVerdict
    with the gateway's extra outputs (priority, trimmed budget,
    resolved absolute deadline)."""
    accepted: bool
    reason: str = ""
    retry_after: Optional[float] = None
    priority: int = 1
    max_new_tokens: Optional[int] = None
    deadline: Optional[float] = None


class GatewayPolicy:
    """The front door's brain: per-tenant token buckets, SLO-class
    mapping, brownout ladder, and deadline-aware shedding, all on one
    injectable clock. ``load_probe`` is any zero-arg callable
    returning a :class:`LoadSnapshot` (:class:`RouterLoadProbe` in
    production, a stub in tests/benches)."""

    def __init__(self, *, tenants: Optional[Dict[str, Dict]] = None,
                 default_rate: float = 50.0,
                 default_burst: float = 100.0,
                 interactive_slo_secs: float = 2.0,
                 batch_slo_secs: float = 30.0,
                 trim_max_new_tokens: int = 32,
                 load_probe: Optional[Callable[[], LoadSnapshot]] = None,
                 brownout: Optional[BrownoutLadder] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._tenant_cfg = dict(tenants or {})
        self.default_rate = default_rate
        self.default_burst = default_burst
        self.interactive_slo_secs = interactive_slo_secs
        self.batch_slo_secs = batch_slo_secs
        self.trim_max_new_tokens = trim_max_new_tokens
        self._load_probe = load_probe
        self._clock = clock
        self.brownout = brownout or BrownoutLadder(clock=clock)
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self.stats = dict(admitted=0, shed=0, trimmed=0)

    # -- tenants -------------------------------------------------------
    def bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                cfg = self._tenant_cfg.get(tenant, {})
                b = self._buckets[tenant] = TokenBucket(
                    rate=float(cfg.get("rate", self.default_rate)),
                    burst=float(cfg.get("burst", self.default_burst)),
                    clock=self._clock)
            return b

    def tenants_snapshot(self) -> Dict[str, Dict]:
        """The per-tenant quota surface (``GET /gateway/tenants``)."""
        with self._lock:
            buckets = dict(self._buckets)
        return {t: dict(rate=b.rate, burst=b.burst,
                        available=round(b.available(), 3))
                for t, b in sorted(buckets.items())}

    # -- decision ------------------------------------------------------
    def load(self) -> LoadSnapshot:
        if self._load_probe is None:
            return LoadSnapshot()
        return self._load_probe()

    def slo_budget(self, slo: str) -> float:
        if slo == protocol.GATEWAY_SLO_INTERACTIVE:
            return self.interactive_slo_secs
        return self.batch_slo_secs

    def admit(self, tenant: str, slo: str, *,
              deadline: Optional[float] = None,
              max_new_tokens: Optional[int] = None,
              cost: float = 1.0) -> GatewayVerdict:
        """Decide one request. Gate order: tenant quota (a flooding
        tenant is turned away even when the fleet is idle), brownout
        ladder (global overload sheds whole classes), deadline
        feasibility (queue depth x p95 says the answer would arrive
        too late). Shedding happens BEFORE any token reaches the
        router."""
        now = self._clock()
        priority = protocol.GATEWAY_SLO_CLASSES[slo]
        obs_metrics.inc("serving_gateway_requests_total",
                        tenant=tenant, slo=slo)
        snap = self.load()
        # the ladder keys on SYSTEM pressure (total backlog vs the
        # interactive budget); feasibility keys on the CLASS-aware
        # wait -- an interactive arrival jumps the batch backlog in
        # the admission queue, so only same-or-higher-class entries
        # delay it. Without that split, pure deadline shedding would
        # invert the SLO order and starve the tight class first.
        est_total = snap.estimated_wait()
        est_wait = snap.estimated_wait(priority)
        level = self.brownout.observe(
            est_total / max(1e-6, self.interactive_slo_secs))
        if deadline is None:
            deadline = now + self.slo_budget(slo)

        bucket = self.bucket(tenant)
        if not bucket.take(cost):
            return self._shed(tenant, slo, protocol.REASON_QUOTA,
                              retry_after=bucket.retry_after(cost))
        obs_metrics.set_gauge("tenant_quota_remaining",
                              bucket.available(), tenant=tenant)

        if level >= LEVEL_SHED_BATCH and priority > 0:
            return self._shed(tenant, slo, protocol.REASON_BROWNOUT,
                              retry_after=est_wait)
        if level >= LEVEL_SHED_ALL:
            return self._shed(tenant, slo, protocol.REASON_BROWNOUT,
                              retry_after=est_wait)

        if max_new_tokens is not None and level >= LEVEL_TRIM \
                and max_new_tokens > self.trim_max_new_tokens:
            max_new_tokens = self.trim_max_new_tokens
            self.stats["trimmed"] += 1
            obs_metrics.inc("serving_gateway_trimmed_total")

        if now + est_wait > deadline:
            return self._shed(
                tenant, slo, protocol.REASON_DEADLINE_UNMEETABLE,
                retry_after=max(0.05, est_wait))

        self.stats["admitted"] += 1
        return GatewayVerdict(True, priority=priority,
                              max_new_tokens=max_new_tokens,
                              deadline=deadline)

    def _shed(self, tenant: str, slo: str, reason: str, *,
              retry_after: Optional[float]) -> GatewayVerdict:
        self.stats["shed"] += 1
        obs_metrics.inc("serving_gateway_shed_total",
                        slo=slo, reason=reason)
        obs_metrics.inc("tenant_shed_total",
                        tenant=tenant, reason=reason)
        return GatewayVerdict(
            False, reason=reason, retry_after=retry_after,
            priority=protocol.GATEWAY_SLO_CLASSES[slo])


# ----------------------------------------------------------------------
# SSE framing
# ----------------------------------------------------------------------
SSE_DONE_SENTINEL = b"data: [DONE]\n\n"


def _json_default(obj):
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return str(obj)


def sse_format(event: str, data: Dict) -> bytes:
    """One SSE frame: ``event: <kind>`` + one JSON ``data:`` line."""
    payload = json.dumps(data, separators=(",", ":"),
                         default=_json_default)
    return f"event: {event}\ndata: {payload}\n\n".encode()


def sse_parse(text: str) -> List[Tuple[str, object]]:
    """Parse an SSE stream back into ``(event, data)`` pairs -- the
    round-trip counterpart of :func:`sse_format`, used by the tests,
    the bench harness, and any Python consumer. JSON data decodes to
    its object; non-JSON data (the OpenAI ``[DONE]`` sentinel) comes
    back as the raw string with an empty event name."""
    out: List[Tuple[str, object]] = []
    event = ""
    data_lines: List[str] = []
    for line in list(text.splitlines()) + [""]:
        if line == "":
            if data_lines:
                raw = "\n".join(data_lines)
                try:
                    payload = json.loads(raw)
                except ValueError:
                    payload = raw
                out.append((event, payload))
            event, data_lines = "", []
        elif line.startswith("event:"):
            event = line[len("event:"):].strip()
        elif line.startswith("data:"):
            data_lines.append(line[len("data:"):].strip())
        # comment / id / retry fields are ignored
    return out


# ----------------------------------------------------------------------
# The HTTP server
# ----------------------------------------------------------------------
class GatewayServer:
    """OpenAI-compatible completions ingress (module doc).

    ``client_factory`` builds one RolloutClient-shaped object
    (``submit/stream/abandon/close``) per concurrent request; clients
    are pooled and reused serially across handler threads (checkout /
    checkin around each request -- a ZMQ DEALER socket tolerates
    serial cross-thread use under a lock's memory barrier, never
    concurrent use).

    Endpoints: ``POST /v1/completions`` (SSE when ``stream`` is true,
    one JSON body otherwise), ``GET /gateway/tenants`` (quota
    surface), ``GET /gateway/stats``, ``GET /healthz``.
    """

    def __init__(self, client_factory: Callable[[], object], *,
                 policy: Optional[GatewayPolicy] = None,
                 port: int = 0, host: str = "",
                 process_name: str = "gateway",
                 encode: Optional[Callable[[str], np.ndarray]] = None,
                 stream_timeout: float = 120.0,
                 model_name: str = "realhf-tpu",
                 clock: Callable[[], float] = time.monotonic):
        self._client_factory = client_factory
        self.policy = policy or GatewayPolicy(clock=clock)
        self.process_name = process_name
        self._requested_port = port
        self._host = host
        self._encode = encode or _byte_level_encode
        self.stream_timeout = stream_timeout
        self.model_name = model_name
        self._clock = clock
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._pool: List[object] = []
        self._pool_lock = threading.Lock()
        self._draining = False
        self.stats = dict(http_requests=0, streams=0, terminals=0)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "GatewayServer":
        server = self

        class Handler(BoundedRequestHandler):
            # the front door serves users, not scrapers: slightly
            # longer patience for slow readers of long SSE streams
            timeout = 60.0

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                server._safe(self, server._route_get)

            def do_POST(self):
                server._safe(self, server._route_post)

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"gateway[{self.process_name}]", daemon=True)
        self._thread.start()
        logger.info("Gateway %s serving /v1/completions on port %d.",
                    self.process_name, self.port)
        return self

    @property
    def port(self) -> int:
        if self._httpd is None:
            return 0
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        from realhf_tpu.base import network
        return f"{network.gethostip()}:{self.port}"

    def start_drain(self):
        """Refuse all future admissions (503 draining); in-flight
        streams run to their terminals."""
        self._draining = True

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for client in pool:
            try:
                client.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass

    # -- client pool ----------------------------------------------------
    def _checkout(self):
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return self._client_factory()

    def _checkin(self, client):
        with self._pool_lock:
            self._pool.append(client)

    # -- plumbing -------------------------------------------------------
    def _safe(self, handler, route):
        self.stats["http_requests"] += 1
        try:
            route(handler)
        except BrokenPipeError:
            pass  # user hung up mid-stream
        except Exception as e:  # noqa: BLE001 - one bad request must
            # never take the front door down
            logger.error("Gateway handler error: %r", e)
            try:
                self._error(handler, 500, "internal",
                            reason="internal_error", detail=repr(e))
            except Exception:  # noqa: BLE001
                pass

    def _respond(self, handler, code: int, content_type: str,
                 body: bytes, extra_headers: Tuple = ()):
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)

    def _json(self, handler, payload: Dict, code: int = 200,
              extra_headers: Tuple = ()):
        self._respond(handler, code, "application/json",
                      (json.dumps(payload, default=_json_default)
                       + "\n").encode(), extra_headers)

    def _error(self, handler, code: int, err_type: str, *,
               reason: str = "", retry_after: Optional[float] = None,
               detail: str = ""):
        headers: List[Tuple[str, str]] = []
        if code in protocol.GATEWAY_RETRYABLE_STATUS \
                and retry_after is not None \
                and retry_after != float("inf"):
            headers.append(("Retry-After",
                            str(max(1, int(-(-retry_after // 1))))))
        body = dict(error=dict(type=err_type, reason=reason,
                               retry_after=retry_after))
        if detail:
            body["error"]["detail"] = detail
        self._json(handler, body, code=code,
                   extra_headers=tuple(headers))

    # -- routing --------------------------------------------------------
    def _route_get(self, handler):
        path = handler.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/healthz":
            state = "DRAINING" if self._draining else "RUNNING"
            self._json(handler, dict(state=state,
                                     process=self.process_name),
                       code=503 if self._draining else 200)
        elif path == "/gateway/tenants":
            self._json(handler, self.policy.tenants_snapshot())
        elif path == "/gateway/stats":
            self._json(handler, dict(
                gateway=dict(self.stats),
                policy=dict(self.policy.stats),
                brownout_level=self.policy.brownout.level))
        else:
            self._respond(handler, 404, "text/plain",
                          b"unknown path (have: /v1/completions "
                          b"/gateway/tenants /gateway/stats "
                          b"/healthz)\n")

    def _route_post(self, handler):
        path = handler.path.split("?", 1)[0].rstrip("/")
        if path != "/v1/completions":
            self._respond(handler, 404, "text/plain",
                          b"unknown path (POST /v1/completions)\n")
            return
        self._handle_completion(handler)

    # -- the completions endpoint --------------------------------------
    def _read_body(self, handler) -> Optional[Dict]:
        try:
            length = int(handler.headers.get("Content-Length", 0) or 0)
        except ValueError:
            length = -1
        if length <= 0:
            self._error(handler, 400, "invalid_request",
                        reason="missing_body")
            return None
        if length > MAX_BODY_BYTES:
            self._error(handler, 413, "invalid_request",
                        reason="body_too_large")
            return None
        raw = handler.rfile.read(length)
        try:
            body = json.loads(raw)
        except ValueError:
            self._error(handler, 400, "invalid_request",
                        reason="malformed_json")
            return None
        if not isinstance(body, dict):
            self._error(handler, 400, "invalid_request",
                        reason="malformed_json")
            return None
        return body

    def _prompt_tokens(self, handler,
                       body: Dict) -> Optional[np.ndarray]:
        prompt = body.get("prompt")
        if isinstance(prompt, str) and prompt:
            return self._encode(prompt)
        if isinstance(prompt, list) and prompt \
                and all(isinstance(t, int) for t in prompt):
            return np.asarray(prompt, np.int32)
        self._error(handler, 400, "invalid_request",
                    reason="missing_prompt",
                    detail="prompt must be a non-empty string or a "
                           "list of token ids")
        return None

    def _handle_completion(self, handler):
        body = self._read_body(handler)
        if body is None:
            return
        tenant = str(body.get("user")
                     or handler.headers.get("X-Tenant") or "anon")
        slo = str(body.get("slo") or protocol.GATEWAY_SLO_INTERACTIVE)
        if slo not in protocol.GATEWAY_SLO_CLASSES:
            self._error(handler, 400, "invalid_request",
                        reason="unknown_slo_class",
                        detail=f"have: {sorted(protocol.GATEWAY_SLO_CLASSES)}")
            return
        prompt = self._prompt_tokens(handler, body)
        if prompt is None:
            return
        if self._draining:
            self._error(
                handler,
                protocol.gateway_status(protocol.REJECTED,
                                        protocol.REASON_DRAINING),
                "overloaded", reason=protocol.REASON_DRAINING,
                retry_after=30.0)
            return
        max_new = body.get("max_tokens")
        max_new = int(max_new) if max_new is not None else None
        deadline_secs = body.get("deadline_secs")
        now = self._clock()
        deadline = (now + float(deadline_secs)
                    if deadline_secs is not None else None)

        verdict = self.policy.admit(tenant, slo, deadline=deadline,
                                    max_new_tokens=max_new)
        if not verdict.accepted:
            # the shed reply is this request's exactly-once terminal:
            # nothing was submitted, nothing else will ever answer it
            self._error(
                handler,
                protocol.gateway_status(protocol.REJECTED,
                                        verdict.reason),
                "overloaded", reason=verdict.reason,
                retry_after=verdict.retry_after)
            return

        ttl = None
        if verdict.deadline is not None:
            ttl = max(0.001, verdict.deadline - now)
        client = self._checkout()
        try:
            from realhf_tpu.serving.request_queue import Priority
            rid = client.submit(prompt,
                                priority=Priority(verdict.priority),
                                ttl=ttl)
            if bool(body.get("stream", True)):
                self._stream_response(handler, client, rid, tenant,
                                      slo, now)
            else:
                self._json_response(handler, client, rid, tenant,
                                    slo, now, prompt)
        finally:
            self._checkin(client)

    # -- response paths -------------------------------------------------
    def _sse_event(self, wfile, kind: str, data: Dict):
        wfile.write(sse_format(kind, data))

    def _event_stream(self, client, rid: str):
        """``(kind, data)`` events up to the terminal.
        ``RolloutClient.stream`` when the client has one; a
        terminal-only client (``ShardedRolloutClient``) degrades to a
        single terminal event -- the SSE contract (one declared
        terminal, then ``[DONE]``) holds either way."""
        stream = getattr(client, "stream", None)
        if stream is not None:
            yield from stream(rid, timeout=self.stream_timeout)
            return
        result = client.result(rid, timeout=self.stream_timeout)
        yield result.status, result.data

    @staticmethod
    def _abandon(client, rid: str):
        getattr(client, "abandon", client.cancel)(rid)

    def _account_terminal(self, tenant: str, slo: str, kind: str,
                          started: float):
        self.stats["terminals"] += 1
        obs_metrics.inc("serving_gateway_terminals_total", kind=kind)
        obs_metrics.observe_hist("serving_gateway_latency_seconds",
                                 self._clock() - started, slo=slo)

    def _stream_response(self, handler, client, rid: str, tenant: str,
                         slo: str, started: float):
        self.stats["streams"] += 1
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-store")
        # no Content-Length: connection close delimits the stream
        handler.end_headers()
        terminal = None
        try:
            for kind, data in self._event_stream(client, rid):
                self._sse_event(handler.wfile, kind, data)
                if kind in protocol.TERMINAL_KINDS:
                    terminal = kind
        except TimeoutError:
            # the wire went quiet past the stream budget: close the
            # request with an explicit declared terminal instead of a
            # socket that silently vanishes
            self._abandon(client, rid)
            terminal = protocol.EXPIRED
            self._sse_event(handler.wfile, protocol.EXPIRED, {})
        except BrokenPipeError:
            # user hung up: cancel server-side work and suppress late
            # events; the HTTP stream needs no terminal (no reader)
            self._abandon(client, rid)
            self._account_terminal(tenant, slo, protocol.CANCELLED,
                                   started)
            raise
        handler.wfile.write(SSE_DONE_SENTINEL)
        handler.close_connection = True
        self._account_terminal(tenant, slo,
                               terminal or protocol.EXPIRED, started)

    def _json_response(self, handler, client, rid: str, tenant: str,
                       slo: str, started: float,
                       prompt: np.ndarray):
        try:
            result = client.result(rid, timeout=self.stream_timeout)
            kind, data = result.status, result.data
        except TimeoutError:
            self._abandon(client, rid)
            kind, data = protocol.EXPIRED, {}
        status = protocol.gateway_status(kind, data.get("reason"))
        self._account_terminal(tenant, slo, kind, started)
        if kind != protocol.DONE:
            self._error(handler, status, "terminal", reason=str(
                data.get("reason") or kind),
                retry_after=data.get("retry_after"))
            return
        tokens = list(np.asarray(data.get("tokens", ())).tolist())
        self._json(handler, dict(
            id=rid, object="text_completion", model=self.model_name,
            choices=[dict(
                index=0, tokens=tokens,
                finish_reason="length" if data.get("no_eos")
                else "stop")],
            usage=dict(prompt_tokens=int(len(prompt)),
                       completion_tokens=len(tokens),
                       total_tokens=int(len(prompt)) + len(tokens)),
            weight_version=data.get("weight_version"),
        ), code=status)


def _byte_level_encode(text: str) -> np.ndarray:
    """Tokenizer-free prompt encoding: UTF-8 bytes as token ids.
    Deployments with a real tokenizer inject their own ``encode``."""
    return np.frombuffer(text.encode("utf-8"),
                         dtype=np.uint8).astype(np.int32)


# ----------------------------------------------------------------------
# Deployment helpers (apps/main.run_serve wiring)
# ----------------------------------------------------------------------
def gateway_http_key(experiment_name: str, trial_name: str,
                     name: str = "gateway/0") -> str:
    """name_resolve key the gateway's HTTP address is published
    under (the front-door analog of ``rollout_server_key``)."""
    from realhf_tpu.base import names
    return (names.trial_root(experiment_name, trial_name)
            + f"/gateway_http/{name}")


def telemetry_metrics_fetch(experiment_name: str, trial_name: str,
                            worker_name: str,
                            timeout: float = 5.0
                            ) -> Callable[[], Optional[str]]:
    """A :class:`RouterLoadProbe` fetcher reading ``worker_name``'s
    ``/metrics`` telemetry endpoint through ``names.telemetry`` --
    the same path the run_serve autoscaler scrapes."""
    def fetch() -> Optional[str]:
        import urllib.request

        from realhf_tpu.base import name_resolve, names
        addr = name_resolve.get(names.telemetry(
            experiment_name, trial_name, worker_name))
        with urllib.request.urlopen(f"http://{addr}/metrics",
                                    timeout=timeout) as r:
            return r.read().decode("utf-8", "replace")
    return fetch
