"""Fleet membership: leased replica registry with fencing epochs.

The rendezvous layer of the resilient serving fleet (docs/serving.md
"Fleet, failover & circuit breakers"). Every ``RolloutServer`` replica
registers itself under a name_resolve subtree with a ``keepalive_ttl``
lease and renews it from its serve loop; the ``FleetRouter`` reads the
subtree to discover live replicas. A replica that dies, hangs, or is
partitioned away stops renewing, its lease expires, and it simply
vanishes from the registry -- the router's loss signal needs no extra
protocol.

Fencing: each registration bumps a per-replica *epoch* (persistent --
it survives lease expiry, see
``name_resolve.NameRecordRepository.register_with_epoch``). The
stored value embeds the epoch (``"<epoch>:<address>"``), so one
subtree read gives the router a consistent (address, epoch) pair. A
zombie replica that lost its lease and re-registers gets a HIGHER
epoch; consumers pin the highest epoch seen per name and fence out
anything older.
"""

import dataclasses
import time
from typing import Callable, Dict, List, Optional

from realhf_tpu.base import logging, name_resolve, names

logger = logging.getLogger("serving.fleet", "system")


class LeaseLostError(RuntimeError):
    """A replica's lease expired (or was never held) when it tried to
    renew: the holder is fenced out and must re-register, obtaining a
    new fencing epoch, before serving again."""


def fleet_root(experiment_name: str, trial_name: str) -> str:
    return (names.trial_root(experiment_name, trial_name)
            + "/serving_fleet")


@dataclasses.dataclass(frozen=True)
class ReplicaInfo:
    """One live fleet member, as read from the registry."""
    name: str
    address: str
    epoch: int
    #: deliberately draining toward a scale-down/shutdown: consumers
    #: stop dispatching NEW work here, and when the lease finally
    #: disappears they treat it as a planned departure (no breaker
    #: trip, no failover storm) instead of a loss
    retiring: bool = False


class FleetRegistry:
    """Leased replica membership over one name_resolve repository.

    ``repo`` defaults to the process-wide name_resolve default; drills
    and tests pass a private ``MemoryNameRecordRepository`` (with an
    injectable clock, making lease expiry deterministic).
    """

    def __init__(self, experiment_name: str, trial_name: str, *,
                 lease_ttl: float = 5.0,
                 repo: Optional[name_resolve.NameRecordRepository] = None,
                 clock: Callable[[], float] = time.monotonic):
        if lease_ttl <= 0:
            raise ValueError(f"lease_ttl must be > 0, got {lease_ttl}")
        self.lease_ttl = lease_ttl
        self._root = fleet_root(experiment_name, trial_name)
        self._repo = repo if repo is not None else name_resolve.default()
        self._clock = clock
        #: retiring keys first observed orphaned (replica lease gone)
        #: at this clock time -- gc_retiring sweeps them past a grace
        self._retiring_orphaned: Dict[str, float] = {}

    # -- key layout ----------------------------------------------------
    # replicas/ holds the leased entries; epochs/ the persistent
    # fencing counters. Separate subtrees so a replica listing never
    # mixes in epoch bookkeeping.
    def _replica_key(self, name: str) -> str:
        return f"{self._root}/replicas/{name}"

    def _epoch_key(self, name: str) -> str:
        return f"{self._root}/epochs/{name}"

    def _retiring_key(self, name: str) -> str:
        return f"{self._root}/retiring/{name}"

    # routers/ + router_epochs/: the sharded router plane's own leased
    # membership (serving/router_shard.py), same value format as
    # replicas/ so one parser serves both subtrees
    def _router_key(self, name: str) -> str:
        return f"{self._root}/routers/{name}"

    def _router_epoch_key(self, name: str) -> str:
        return f"{self._root}/router_epochs/{name}"

    # journal/: per-rid re-dispatch records a router shard writes on
    # admission and clears on terminal delivery; survivors adopt a
    # dead shard's entries (docs/serving.md "Sharded router plane")
    def _journal_key(self, rid: str) -> str:
        return f"{self._root}/journal/{rid}"

    # ------------------------------------------------------------------
    def register(self, name: str, address: str) -> int:
        """(Re-)register a replica; returns its NEW fencing epoch. A
        fresh registration is never retiring -- a revived replica of a
        previously drained name starts clean."""
        self.clear_retiring(name)
        epoch = self._repo.register_with_epoch(
            self._replica_key(name),
            lambda e: f"{e}:{address}",
            epoch_name=self._epoch_key(name),
            keepalive_ttl=self.lease_ttl)
        logger.info("Fleet replica %s registered at %s (epoch %d, "
                    "lease %.1fs).", name, address, epoch,
                    self.lease_ttl)
        return epoch

    # -- deliberate scale-down (docs/serving.md "Autoscaling") ---------
    def mark_retiring(self, name: str):
        """Flag a replica as deliberately draining (scale-down /
        graceful shutdown). The flag is never renewed like a lease --
        it must survive the replica's own deregistration so a consumer
        polling after the lease vanished still classifies the
        departure as planned -- but it does carry a generous TTL
        (many lease TTLs) as a backstop: autoscaling never reuses
        replica names, so without expiry a long-running trial would
        accumulate retiring/ keys in every :meth:`replicas` scan. It
        is cleared earlier by whichever comes first: the consumer
        observing the departure (``FleetRouter._retire_replica``) or
        a :meth:`register` of the same name."""
        self._repo.add(self._retiring_key(name), "1", replace=True,
                       keepalive_ttl=max(300.0, 20.0 * self.lease_ttl))
        logger.info("Fleet replica %s marked retiring.", name)

    def clear_retiring(self, name: str):
        try:
            self._repo.delete(self._retiring_key(name))
        except name_resolve.NameEntryNotFoundError:
            pass

    def is_retiring(self, name: str) -> bool:
        try:
            self._repo.get(self._retiring_key(name))
            return True
        except name_resolve.NameEntryNotFoundError:
            return False

    def renew(self, name: str):
        """Refresh the replica's lease. Raises LeaseLostError when the
        lease already expired -- the caller is fenced and must
        ``register`` again (new epoch) before serving."""
        try:
            self._repo.touch(self._replica_key(name))
        except name_resolve.NameEntryNotFoundError:
            raise LeaseLostError(
                f"Replica {name}: lease expired (ttl="
                f"{self.lease_ttl:.1f}s); re-register for a new "
                "fencing epoch before serving.") from None

    def deregister(self, name: str):
        """Graceful departure (drain/exit): drop the lease now instead
        of letting it time out. The epoch counter stays."""
        try:
            self._repo.delete(self._replica_key(name))
        except name_resolve.NameEntryNotFoundError:
            pass

    # ------------------------------------------------------------------
    def replicas(self) -> Dict[str, ReplicaInfo]:
        """Live (unexpired) replicas as {name: ReplicaInfo}."""
        root = f"{self._root}/replicas"
        rroot = f"{self._root}/retiring"
        retiring = {k[len(rroot) + 1:] for k in
                    self._repo.find_subtree(rroot)
                    if k.startswith(rroot + "/")}
        out: Dict[str, ReplicaInfo] = {}
        for key in self._repo.find_subtree(root):
            name = key[len(root) + 1:] if key.startswith(root + "/") \
                else key
            try:
                raw = self._repo.get(key)
            except name_resolve.NameEntryNotFoundError:
                continue  # expired between walk and read
            try:
                epoch_s, address = str(raw).split(":", 1)
                out[name] = ReplicaInfo(name=name, address=address,
                                        epoch=int(epoch_s),
                                        retiring=name in retiring)
            except ValueError:
                logger.warning("Fleet registry: malformed replica "
                               "entry %s=%r ignored.", key, raw)
        return out

    def epoch_of(self, name: str) -> Optional[int]:
        """Current fencing epoch counter for a replica name (None if
        it never registered). Advances only on registration, so a
        holder can cheaply verify it is still the newest registrant."""
        try:
            return int(self._repo.get(self._epoch_key(name)))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None

    # -- router plane (docs/serving.md "Sharded router plane") ---------
    # Router shards are fleet members too: same leased registration,
    # same persistent fencing epochs, a separate subtree so replica
    # listings and router listings never mix.
    def register_router(self, name: str, address: str) -> int:
        """(Re-)register a router shard; returns its NEW fencing
        epoch. Clients and peer routers derive the consistent-hash
        ring (serving/ring.py) from the live routers/ subtree."""
        epoch = self._repo.register_with_epoch(
            self._router_key(name),
            lambda e: f"{e}:{address}",
            epoch_name=self._router_epoch_key(name),
            keepalive_ttl=self.lease_ttl)
        logger.info("Router shard %s registered at %s (epoch %d, "
                    "lease %.1fs).", name, address, epoch,
                    self.lease_ttl)
        return epoch

    def renew_router(self, name: str):
        """Refresh a router shard's lease; raises LeaseLostError when
        it already expired (the shard is fenced: survivors are
        adopting its hash range, so it must flush undelivered state
        and re-register before routing again)."""
        try:
            self._repo.touch(self._router_key(name))
        except name_resolve.NameEntryNotFoundError:
            raise LeaseLostError(
                f"Router {name}: lease expired (ttl="
                f"{self.lease_ttl:.1f}s); flush and re-register for "
                "a new fencing epoch before routing.") from None

    def deregister_router(self, name: str):
        try:
            self._repo.delete(self._router_key(name))
        except name_resolve.NameEntryNotFoundError:
            pass

    def routers(self) -> Dict[str, ReplicaInfo]:
        """Live (unexpired) router shards as {name: ReplicaInfo}."""
        root = f"{self._root}/routers"
        out: Dict[str, ReplicaInfo] = {}
        for key in self._repo.find_subtree(root):
            name = key[len(root) + 1:] if key.startswith(root + "/") \
                else key
            try:
                raw = self._repo.get(key)
            except name_resolve.NameEntryNotFoundError:
                continue  # expired between walk and read
            try:
                epoch_s, address = str(raw).split(":", 1)
                out[name] = ReplicaInfo(name=name, address=address,
                                        epoch=int(epoch_s))
            except ValueError:
                logger.warning("Fleet registry: malformed router "
                               "entry %s=%r ignored.", key, raw)
        return out

    def router_epoch_of(self, name: str) -> Optional[int]:
        try:
            return int(self._repo.get(self._router_epoch_key(name)))
        except (name_resolve.NameEntryNotFoundError, ValueError):
            return None

    # -- in-flight rid journal -----------------------------------------
    def journal_rid(self, rid: str, payload: str):
        """Record an admitted rid's re-dispatch envelope. The TTL is a
        backstop only (a request outliving it merely loses journal
        coverage -- the client's own resubmission still recovers it);
        the owning shard clears the entry on terminal delivery."""
        self._repo.add(self._journal_key(rid), payload, replace=True,
                       keepalive_ttl=max(60.0, 20.0 * self.lease_ttl))

    def clear_rid(self, rid: str):
        try:
            self._repo.delete(self._journal_key(rid))
        except name_resolve.NameEntryNotFoundError:
            pass

    def journal(self) -> Dict[str, str]:
        """All live journal entries as {rid: payload}."""
        root = f"{self._root}/journal"
        out: Dict[str, str] = {}
        for key in self._repo.find_subtree(root):
            rid = key[len(root) + 1:] if key.startswith(root + "/") \
                else key
            try:
                out[rid] = str(self._repo.get(key))
            except name_resolve.NameEntryNotFoundError:
                continue
        return out

    # ------------------------------------------------------------------
    def gc_retiring(self, grace: Optional[float] = None) -> List[str]:
        """Sweep retiring/ markers whose replica has already departed.

        A retiring key is normally cleared by whichever router
        observes the departure; when NO router ever does (routerless
        autoscale, or the router died first), the key used to linger
        until its generous TTL backstop. This sweep deletes markers
        whose replica lease has been gone for at least ``grace``
        (default ``2 * lease_ttl`` -- long enough that every consumer
        polling on the lease cadence has classified the departure as
        planned). Wired into ``AutoscaleController.step`` so repeated
        scale-down cycles never accumulate keys. Returns the swept
        names."""
        grace = 2.0 * self.lease_ttl if grace is None else grace
        now = self._clock()
        live = set(self.replicas())
        rroot = f"{self._root}/retiring"
        present = set()
        swept: List[str] = []
        for key in self._repo.find_subtree(rroot):
            name = key[len(rroot) + 1:] if key.startswith(rroot + "/") \
                else key
            present.add(name)
            if name in live:
                # still draining: not orphaned, reset any observation
                self._retiring_orphaned.pop(name, None)
                continue
            first = self._retiring_orphaned.setdefault(name, now)
            if now - first >= grace:
                self.clear_retiring(name)
                self._retiring_orphaned.pop(name, None)
                swept.append(name)
        # drop observations for keys that vanished on their own
        for name in list(self._retiring_orphaned):
            if name not in present:
                self._retiring_orphaned.pop(name, None)
        if swept:
            logger.info("Fleet registry: swept %d consumed retiring "
                        "marker(s): %s.", len(swept), swept)
        return swept
