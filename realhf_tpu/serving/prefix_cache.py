"""Radix-tree prefix/KV-cache reuse across serving requests.

RadixAttention-style (SGLang, Zheng et al. 2023) sharing of prefill
work: templated traffic (system prompts, few-shot preambles,
multi-turn conversations) re-sends the same token prefix over and
over, and the KV rows of a prefix depend only on the prefix itself --
so the KV a finished sequence computed can seed the next request that
shares its opening tokens. This module is the host-side index:

- a **radix tree** over token-id sequences; every non-root node owns
  an edge label (a token span) and the **KV block** for exactly those
  positions (``k``/``v``: ``[n_layers, n_kv_heads, len, head_dim]``
  numpy arrays, host memory -- HBM is never charged for cold cache),
- :meth:`match` walks a new prompt down the tree and returns the
  longest cached prefix as one concatenated donor KV view plus a
  **pin handle**: every node on the path is ref-counted until
  :meth:`release`, so eviction can never free a block an admission
  currently copies from,
- :meth:`insert` publishes a finished sequence's KV back, splitting
  edges at divergence points and storing only the *new* suffix,
- a **byte budget** (``capacity_bytes``) enforced by LRU eviction of
  unpinned leaves at insert time (``last_access`` is a logical tick,
  not wall clock -- deterministic under test clocks).

The tree stores *values*, not devices: the scheduler copies the donor
view into a decode slot's cache rows at fill time
(``InflightBatchingGenerator.fill_slot(cached_len=..., prefix_kv=...)``)
and the block is released immediately after -- pins live for the
match->fill window only. Because match results are numpy views,
eviction after release only drops the tree's reference; an in-flight
copy keeps its data alive via ordinary refcounting.

Correctness notes:

- KV rows are a function of (tokens, weights): the scheduler flushes
  the whole tree on every weight hot-swap
  (:meth:`ContinuousScheduler.poll_weights`), so a donor never mixes
  weight versions into a sequence.
- Rotary embeddings bind KV to absolute positions; a radix *prefix*
  match reuses rows at the same positions they were computed for, so
  position-dependent caches stay exact.
- Child traversal never iterates an unsorted dict: lookup is by first
  edge token (exact key), and maintenance walks use sorted child keys
  (graft-lint det-unsorted-iter discipline).
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.obs import flight
from realhf_tpu.obs import metrics as obs_metrics

logger = logging.getLogger("serving.prefix_cache")

#: flight event fired when pinned blocks hold the budget more than 2x
#: over ``capacity_bytes`` (satellite: overcommit used to be invisible)
OVERCOMMIT_EVENT = "prefix_cache_overcommit"


class _Node:
    """One radix-tree node: an edge label plus the KV block covering
    exactly the label's positions. The root is the only node with an
    empty label and no KV."""

    __slots__ = ("tokens", "kv_k", "kv_v", "children", "parent", "ref",
                 "last_access")

    def __init__(self, tokens: np.ndarray,
                 kv_k: Optional[np.ndarray], kv_v: Optional[np.ndarray],
                 parent: Optional["_Node"]):
        self.tokens = tokens            # [L] int64/int32 edge label
        self.kv_k = kv_k                # [nl, nkv, L, hd] or None (root)
        self.kv_v = kv_v
        self.children: Dict[int, "_Node"] = {}  # first edge token -> node
        self.parent = parent
        self.ref = 0                    # outstanding pins (match handles)
        self.last_access = 0            # logical LRU tick

    @property
    def nbytes(self) -> int:
        if self.kv_k is None:
            return 0
        return self.kv_k.nbytes + self.kv_v.nbytes


@dataclasses.dataclass
class PrefixMatch:
    """Result of :meth:`RadixPrefixCache.match`. ``cached_len`` tokens
    of the prompt are covered by ``k``/``v`` (``[nl, nkv, cached_len,
    hd]`` views); pass them to ``fill_slot`` and then :meth:`release
    <RadixPrefixCache.release>` the ``handle``. A miss has
    ``cached_len == 0`` and an empty handle."""
    cached_len: int
    k: Optional[np.ndarray]
    v: Optional[np.ndarray]
    handle: List[_Node]


class RadixPrefixCache:
    """Byte-budgeted radix tree of reusable KV prefixes (module doc)."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 0:
            raise ValueError("capacity_bytes must be >= 0")
        self.capacity_bytes = capacity_bytes
        self._root = _Node(np.zeros((0,), np.int64), None, None, None)
        self._tick = 0
        self.bytes_used = 0
        self._overcommit_alarmed = False
        self.stats = dict(hits=0, misses=0, tokens_saved=0, inserts=0,
                          insert_skipped=0, evictions=0,
                          evicted_bytes=0, flushes=0,
                          overcommit_events=0)

    # ------------------------------------------------------------------
    def _touch(self, node: _Node):
        self._tick += 1
        node.last_access = self._tick

    # ------------------------------------------------------------------
    def match(self, tokens: np.ndarray,
              max_len: Optional[int] = None) -> PrefixMatch:
        """Longest cached prefix of ``tokens`` (optionally capped at
        ``max_len`` -- admission caps at ``len(prompt) - 1`` because
        the model still needs >= 1 real token to prefill a hidden
        state). Pins every node on the matched path; the caller MUST
        :meth:`release` the handle after copying the donor view."""
        tokens = np.asarray(tokens).reshape(-1)
        cap = len(tokens) if max_len is None else min(max_len,
                                                     len(tokens))
        node = self._root
        matched = 0
        ks: List[np.ndarray] = []
        vs: List[np.ndarray] = []
        handle: List[_Node] = []
        while matched < cap:
            child = node.children.get(int(tokens[matched]))
            if child is None:
                break
            span = child.tokens
            lim = min(len(span), cap - matched)
            # length of agreement within this edge
            eq = np.flatnonzero(
                span[:lim] != tokens[matched:matched + lim])
            take = int(eq[0]) if len(eq) else lim
            if take == 0:
                break
            child.ref += 1
            self._touch(child)
            handle.append(child)
            ks.append(child.kv_k[:, :, :take, :])
            vs.append(child.kv_v[:, :, :take, :])
            matched += take
            if take < len(span):
                break  # diverged (or capped) mid-edge
            node = child
        if matched == 0:
            self.stats["misses"] += 1
            return PrefixMatch(0, None, None, handle)
        self.stats["hits"] += 1
        self.stats["tokens_saved"] += matched
        k = ks[0] if len(ks) == 1 else np.concatenate(ks, axis=2)
        v = vs[0] if len(vs) == 1 else np.concatenate(vs, axis=2)
        return PrefixMatch(matched, k, v, handle)

    def release(self, handle: List[_Node]):
        """Unpin a match handle (idempotence is the caller's job)."""
        for node in handle:
            node.ref = max(0, node.ref - 1)
        if self.bytes_used > self.capacity_bytes:
            # pins were the only thing blocking eviction: retry, then
            # refresh the overcommit surface either way
            self._evict_to_budget()
        else:
            self._note_overcommit()

    def _note_overcommit(self):
        """Budget can only be transiently exceeded while pins are
        outstanding -- which used to be invisible. Surface it as a
        gauge, and as a flight event once the overcommit exceeds 2x
        ``capacity_bytes`` (re-armed when pressure drops back)."""
        over = max(0, self.bytes_used - self.capacity_bytes)
        obs_metrics.set_gauge("serving_prefix_overcommit_bytes", over)
        if over > 2 * self.capacity_bytes:
            if not self._overcommit_alarmed:
                self._overcommit_alarmed = True
                self.stats["overcommit_events"] += 1
                flight.record(OVERCOMMIT_EVENT,
                              overcommit_bytes=int(over),
                              bytes_used=int(self.bytes_used),
                              capacity_bytes=int(self.capacity_bytes))
                logger.warning(
                    "prefix cache overcommitted %d bytes (> 2x the "
                    "%d-byte budget) -- pinned blocks are blocking "
                    "eviction.", over, self.capacity_bytes)
        else:
            self._overcommit_alarmed = False

    # ------------------------------------------------------------------
    def insert(self, tokens: np.ndarray, k: np.ndarray,
               v: np.ndarray) -> int:
        """Publish a sequence's KV. ``k``/``v``: ``[nl, nkv, len(tokens),
        hd]``. Only the suffix not already in the tree is stored (the
        shared prefix stays shared). Returns the number of NEW tokens
        stored (0 when fully covered, skipped, or over budget)."""
        tokens = np.asarray(tokens).reshape(-1)
        if len(tokens) == 0:
            return 0
        if k.shape[2] != len(tokens) or v.shape[2] != len(tokens):
            logger.warning(
                "prefix_cache.insert: kv rows (%d/%d) != token count "
                "%d; skipping.", k.shape[2], v.shape[2], len(tokens))
            self.stats["insert_skipped"] += 1
            return 0
        node = self._root
        matched = 0
        while matched < len(tokens):
            child = node.children.get(int(tokens[matched]))
            if child is None:
                break
            span = child.tokens
            lim = min(len(span), len(tokens) - matched)
            eq = np.flatnonzero(
                span[:lim] != tokens[matched:matched + lim])
            take = int(eq[0]) if len(eq) else lim
            if take < len(span):
                # the new sequence diverges (or ends) mid-edge: split
                # the edge at `take`. A pinned node is never split --
                # an outstanding handle references its full block --
                # so a best-effort insert simply stops here.
                if child.ref > 0:
                    self.stats["insert_skipped"] += 1
                    return 0
                if take == 0:
                    break
                self._split(child, take)
            self._touch(child)
            matched += take
            node = child
        new = len(tokens) - matched
        if new == 0:
            self.stats["inserts"] += 1
            return 0  # fully covered already
        blk_k = np.ascontiguousarray(k[:, :, matched:, :])
        blk_v = np.ascontiguousarray(v[:, :, matched:, :])
        blk_bytes = blk_k.nbytes + blk_v.nbytes
        if blk_bytes > self.capacity_bytes:
            self.stats["insert_skipped"] += 1
            return 0  # the block alone busts the budget
        leaf = _Node(tokens[matched:].copy(), blk_k, blk_v, node)
        node.children[int(tokens[matched])] = leaf
        self._touch(leaf)
        self.bytes_used += blk_bytes
        self.stats["inserts"] += 1
        self._evict_to_budget(protect=leaf)
        return new

    def _split(self, node: _Node, at: int):
        """Split ``node``'s edge at ``at``: the existing object keeps
        the prefix part (so any external reference stays valid) and a
        new child inherits the tail + subtree."""
        tail = _Node(node.tokens[at:].copy(),
                     np.ascontiguousarray(node.kv_k[:, :, at:, :]),
                     np.ascontiguousarray(node.kv_v[:, :, at:, :]),
                     node)
        tail.children = node.children
        for c in tail.children.values():
            c.parent = tail
        tail.last_access = node.last_access
        node.kv_k = np.ascontiguousarray(node.kv_k[:, :, :at, :])
        node.kv_v = np.ascontiguousarray(node.kv_v[:, :, :at, :])
        node.tokens = node.tokens[:at].copy()
        node.children = {int(tail.tokens[0]): tail}

    # ------------------------------------------------------------------
    def _leaves(self) -> List[_Node]:
        """Evictable candidates: leaf nodes, deterministic order
        (sorted child walk -- never raw dict iteration)."""
        out: List[_Node] = []
        stack = [self._root]
        while stack:
            n = stack.pop()
            kids = [n.children[t] for t in sorted(n.children)]
            if not kids and n is not self._root:
                out.append(n)
            stack.extend(kids)
        return out

    def _evict_to_budget(self, protect: Optional[_Node] = None):
        """LRU-evict unpinned leaves until ``bytes_used`` fits the
        budget. A pinned (ref > 0) block is NEVER freed -- the budget
        may be transiently exceeded while pins are outstanding."""
        while self.bytes_used > self.capacity_bytes:
            cands = [n for n in self._leaves()
                     if n.ref == 0 and n is not protect]
            if not cands:
                break  # everything left is pinned (or the new block)
            victim = min(cands, key=lambda n: n.last_access)
            self._remove(victim)
        self._note_overcommit()

    def _remove(self, node: _Node):
        self.bytes_used -= node.nbytes
        self.stats["evictions"] += 1
        self.stats["evicted_bytes"] += node.nbytes
        parent = node.parent
        if parent is not None:
            parent.children.pop(int(node.tokens[0]), None)
        node.parent = None

    # ------------------------------------------------------------------
    def clear(self) -> int:
        """Drop every unpinned block (weight hot-swap: stale KV must
        never seed a sequence under new weights). Returns blocks
        dropped. Pinned nodes survive with their ancestor chain; they
        are released momentarily and evicted by the next insert."""
        dropped = 0
        # bottom-up: removing leaves exposes their parents
        while True:
            cands = [n for n in self._leaves() if n.ref == 0]
            if not cands:
                break
            for n in cands:
                self._remove(n)
                dropped += 1
        self.stats["flushes"] += 1
        return dropped

    # ------------------------------------------------------------------
    @property
    def n_nodes(self) -> int:
        n = 0
        stack = [self._root]
        while stack:
            cur = stack.pop()
            n += 1
            stack.extend(cur.children[t] for t in sorted(cur.children))
        return n - 1  # root excluded

    def snapshot(self) -> dict:
        return dict(self.stats, bytes=self.bytes_used,
                    capacity_bytes=self.capacity_bytes,
                    nodes=self.n_nodes,
                    overcommit_bytes=max(
                        0, self.bytes_used - self.capacity_bytes))


# ----------------------------------------------------------------------
# Pooled radix cache: nodes hold KV-POOL BLOCK IDS (ISSUE 14)
# ----------------------------------------------------------------------
class _PNode:
    """Radix node over a paged KV pool: an edge label (token span at
    absolute positions ``[start, start + len)``) plus the POOL BLOCKS
    covering exactly those rows -- no private host copy. Because every
    sequence compacts its window from position 0, token ``p`` sits at
    offset ``p % block_len`` of its covering block in EVERY sequence,
    so adjacent nodes can share a boundary block (each holding its own
    pool reference) and a matched path resolves to one block per
    absolute block index with plain bookkeeping."""

    __slots__ = ("tokens", "start", "blocks", "children", "parent",
                 "ref", "last_access")

    def __init__(self, tokens: np.ndarray, start: int,
                 blocks: Tuple[int, ...], parent: Optional["_PNode"]):
        self.tokens = tokens
        self.start = start
        self.blocks = tuple(int(b) for b in blocks)
        self.children: Dict[int, "_PNode"] = {}
        self.parent = parent
        self.ref = 0
        self.last_access = 0


@dataclasses.dataclass
class PooledMatch:
    """Result of :meth:`PooledPrefixCache.match`: ``cached_len``
    (trimmed to a whole-block multiple -- partial tail blocks would be
    appended into by the new sequence and corrupt the shared copy) and
    the pool blocks covering rows ``[0, cached_len)``, to be ALIASED
    into the new slot's block table
    (``fill_slot(cached_len=..., cached_blocks=...)``). Release the
    ``handle`` after the fill, exactly like the host-cache flow."""
    cached_len: int
    blocks: Tuple[int, ...]
    handle: List[_PNode]


class PooledPrefixCache:
    """Radix prefix index over :class:`~realhf_tpu.engine.kv_pool.
    KVPool` blocks: publication and prefix-hit prefill are refcount
    bookkeeping (zero KV copy for full-block spans), and eviction
    returns blocks straight to the pool both tenants share. Byte
    accounting is per-node (``len(node.blocks) * block_bytes``; a
    boundary block shared by two nodes counts twice -- the bound is on
    references held, which is what eviction can actually release)."""

    is_pooled = True

    def __init__(self, pool, capacity_bytes: int):
        if capacity_bytes < 0:
            raise ValueError("capacity_bytes must be >= 0")
        self.pool = pool
        self.capacity_bytes = capacity_bytes
        self._root = _PNode(np.zeros((0,), np.int64), 0, (), None)
        self._tick = 0
        self.bytes_used = 0
        self.rows = 0  # token rows indexed (frag-ratio numerator)
        self._overcommit_alarmed = False
        self.stats = dict(hits=0, misses=0, tokens_saved=0, inserts=0,
                          insert_skipped=0, evictions=0,
                          evicted_bytes=0, flushes=0,
                          overcommit_events=0)

    # shared helpers (identical semantics to the host-copy cache)
    _touch = RadixPrefixCache._touch
    _note_overcommit = RadixPrefixCache._note_overcommit

    @property
    def _blen(self) -> int:
        return self.pool.block_len

    # ------------------------------------------------------------------
    def match(self, tokens: np.ndarray,
              max_len: Optional[int] = None) -> PooledMatch:
        """Longest cached prefix, trimmed to a whole-block multiple.
        Pins every node on the path until :meth:`release` -- a pinned
        node's blocks can never be evicted, so the returned ids stay
        valid through the match->fill window."""
        tokens = np.asarray(tokens).reshape(-1)
        cap = len(tokens) if max_len is None else min(max_len,
                                                     len(tokens))
        node = self._root
        matched = 0
        handle: List[_PNode] = []
        blockmap: Dict[int, int] = {}
        while matched < cap:
            child = node.children.get(int(tokens[matched]))
            if child is None:
                break
            span = child.tokens
            lim = min(len(span), cap - matched)
            eq = np.flatnonzero(
                span[:lim] != tokens[matched:matched + lim])
            take = int(eq[0]) if len(eq) else lim
            if take == 0:
                break
            child.ref += 1
            self._touch(child)
            handle.append(child)
            # deepest node covering an absolute block wins: a child
            # recomputed its donor's partial tail block itself, so its
            # copy extends further than the parent's
            ab0 = child.start // self._blen
            for i, b in enumerate(child.blocks):
                blockmap[ab0 + i] = b
            matched += take
            if take < len(span):
                break
            node = child
        c = matched - matched % self._blen
        if c == 0:
            self.stats["misses"] += 1
            return PooledMatch(0, (), handle)
        self.stats["hits"] += 1
        self.stats["tokens_saved"] += c
        chain = tuple(blockmap[i] for i in range(c // self._blen))
        return PooledMatch(c, chain, handle)

    def release(self, handle: List[_PNode]):
        for node in handle:
            node.ref = max(0, node.ref - 1)
        if self.bytes_used > self.capacity_bytes:
            self._evict_to_budget()
        else:
            self._note_overcommit()

    # ------------------------------------------------------------------
    def insert(self, tokens: np.ndarray, blocks=None) -> int:
        """Publish a finished sequence: ``blocks`` is its pool chain
        covering rows ``[0, len(tokens))`` (from ``harvest(
        export_blocks=True)``). Only the uncovered suffix is indexed;
        the cache increfs exactly the blocks its new node references.
        The caller keeps ownership of its own references (free them
        after this returns). Returns the number of NEW tokens
        indexed."""
        tokens = np.asarray(tokens).reshape(-1)
        L = len(tokens)
        if L == 0 or blocks is None:
            return 0
        blocks = [int(b) for b in blocks]
        if len(blocks) < -(-L // self._blen):
            logger.warning(
                "pooled prefix insert: chain of %d block(s) cannot "
                "cover %d tokens; skipping.", len(blocks), L)
            self.stats["insert_skipped"] += 1
            return 0
        node = self._root
        matched = 0
        while matched < L:
            child = node.children.get(int(tokens[matched]))
            if child is None:
                break
            span = child.tokens
            lim = min(len(span), L - matched)
            eq = np.flatnonzero(
                span[:lim] != tokens[matched:matched + lim])
            take = int(eq[0]) if len(eq) else lim
            if take < len(span):
                if child.ref > 0:
                    self.stats["insert_skipped"] += 1
                    return 0  # never split a pinned node
                if take == 0:
                    break
                self._split(child, take)
            self._touch(child)
            matched += take
            node = child
        new = L - matched
        if new == 0:
            self.stats["inserts"] += 1
            return 0
        leaf_blocks = tuple(
            blocks[matched // self._blen: -(-L // self._blen)])
        blk_bytes = len(leaf_blocks) * self.pool.block_bytes
        if blk_bytes > self.capacity_bytes:
            self.stats["insert_skipped"] += 1
            return 0
        leaf = _PNode(tokens[matched:].copy(), matched, leaf_blocks,
                      node)
        self.pool.incref(leaf_blocks)
        node.children[int(tokens[matched])] = leaf
        self._touch(leaf)
        self.bytes_used += blk_bytes
        self.rows += new
        self.stats["inserts"] += 1
        self._evict_to_budget(protect=leaf)
        return new

    def _split(self, node: _PNode, at: int):
        """Split an edge at ``at`` (absolute position ``node.start +
        at``). Both halves reference the boundary block when the split
        is not block-aligned -- one extra pool reference, counted in
        the per-node byte accounting."""
        blen = self._blen
        split_abs = node.start + at
        tail_b0 = split_abs // blen - node.start // blen
        head_nb = (split_abs - 1) // blen - node.start // blen + 1
        tail = _PNode(node.tokens[at:].copy(), split_abs,
                      node.blocks[tail_b0:], node)
        tail.children = node.children
        for c in tail.children.values():
            c.parent = tail
        tail.last_access = node.last_access
        shared = head_nb > tail_b0  # boundary block in both halves
        if shared:
            self.pool.incref(node.blocks[tail_b0:tail_b0 + 1])
            self.bytes_used += self.pool.block_bytes
        node.blocks = node.blocks[:head_nb]
        node.tokens = node.tokens[:at].copy()
        node.children = {int(tail.tokens[0]): tail}

    # ------------------------------------------------------------------
    def _leaves(self) -> List[_PNode]:
        out: List[_PNode] = []
        stack = [self._root]
        while stack:
            n = stack.pop()
            kids = [n.children[t] for t in sorted(n.children)]
            if not kids and n is not self._root:
                out.append(n)
            stack.extend(kids)
        return out

    def _node_bytes(self, node: _PNode) -> int:
        return len(node.blocks) * self.pool.block_bytes

    def _evict_to_budget(self, protect: Optional[_PNode] = None):
        while self.bytes_used > self.capacity_bytes:
            cands = [n for n in self._leaves()
                     if n.ref == 0 and n is not protect]
            if not cands:
                break
            self._remove(min(cands, key=lambda n: n.last_access))
        self._note_overcommit()

    def _remove(self, node: _PNode):
        nb = self._node_bytes(node)
        self.bytes_used -= nb
        self.rows -= len(node.tokens)
        self.stats["evictions"] += 1
        self.stats["evicted_bytes"] += nb
        self.pool.free(node.blocks)
        parent = node.parent
        if parent is not None:
            parent.children.pop(int(node.tokens[0]), None)
        node.parent = None

    def evict_blocks(self, n: int) -> int:
        """Relieve pool pressure: LRU-evict unpinned leaves until at
        least ``n`` pool blocks actually returned to the free list (a
        shared block only returns when its last reference drops).
        Returns blocks freed -- the scheduler's evict-to-pool step on
        decode/admission OOM."""
        free0 = self.pool.n_free
        while self.pool.n_free - free0 < n:
            cands = [x for x in self._leaves() if x.ref == 0]
            if not cands:
                break
            self._remove(min(cands, key=lambda x: x.last_access))
        self._note_overcommit()
        return self.pool.n_free - free0

    # ------------------------------------------------------------------
    def clear(self) -> int:
        """Drop every unpinned node (weight hot-swap); their blocks
        return to the pool."""
        dropped = 0
        while True:
            cands = [n for n in self._leaves() if n.ref == 0]
            if not cands:
                break
            for n in cands:
                self._remove(n)
                dropped += 1
        self.stats["flushes"] += 1
        return dropped

    # ------------------------------------------------------------------
    @property
    def n_nodes(self) -> int:
        n = 0
        stack = [self._root]
        while stack:
            cur = stack.pop()
            n += 1
            stack.extend(cur.children[t] for t in sorted(cur.children))
        return n - 1

    def snapshot(self) -> dict:
        return dict(self.stats, bytes=self.bytes_used,
                    capacity_bytes=self.capacity_bytes,
                    nodes=self.n_nodes, rows=self.rows, pooled=True,
                    overcommit_bytes=max(
                        0, self.bytes_used - self.capacity_bytes))
