"""ZMQ rollout server/client: streaming generation as a service.

The delivery layer of the serving subsystem (docs/serving.md). One
:class:`RolloutServer` owns a ROUTER socket (address rendezvoused
through name_resolve, same convention as
``system/request_reply_stream.py``), an admission
:class:`~realhf_tpu.serving.request_queue.RequestQueue`, and a
:class:`~realhf_tpu.serving.scheduler.ContinuousScheduler` over a slot
backend (``engine.inflight.InflightBatchingGenerator``). Clients hold
a DEALER socket; every request streams back incrementally::

    client                          server
      submit(rid, prompt, ...) ->
                                 <- accepted | rejected(reason, retry_after)
                                 <- started(weight_version)
                                 <- tokens(delta) ...        [streaming]
                                 <- done(result) | stale | expired
      cancel(rid)              ->
                                 <- cancelled

Payloads are pickled tuples ``(kind, rid, data)`` -- metadata plus
token id arrays, never model weights (those move through
:class:`~realhf_tpu.serving.weight_sync.WeightSync` on the host).

Graceful drain: ``drain()`` stops admission, bounces queued requests
back to their clients (``draining``), lets in-flight slots finish (or
cancels them past the timeout), and leaves no orphaned queue entries.
"""

import collections
import dataclasses
import pickle
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

import numpy as np
import zmq

from realhf_tpu.base import fault_injection, logging, name_resolve, \
    names, network
from realhf_tpu.obs import metrics, tracing
from realhf_tpu.serving import protocol
from realhf_tpu.serving.protocol import TERMINAL_KINDS  # noqa: F401
# ^ re-exported for compatibility: the kinds, frame schemas, and
# state machines are declared in serving/protocol.py (normative;
# enforced by the `wire` checker in analysis/wire.py)
from realhf_tpu.serving.request_queue import (
    AdmissionVerdict,
    GenRequest,
    Priority,
    RequestQueue,
)
from realhf_tpu.serving.scheduler import ContinuousScheduler, ServeEvent
from realhf_tpu.serving.weight_sync import WeightSync

logger = logging.getLogger("serving.server", "system")


def rollout_server_key(experiment_name: str, trial_name: str,
                       server_name: str) -> str:
    return (names.trial_root(experiment_name, trial_name)
            + f"/rollout_server/{server_name}")


class RolloutServer:
    """Continuous-batching generation service over one slot backend.

    Single-threaded serve loop: ``serve_step`` pumps the socket, runs
    one scheduler iteration, and routes events -- call it from a
    worker's poll loop (``GenServerWorker``) or spin
    ``serve_forever`` in a dedicated thread. ``weight_sync.push`` is
    the only entry point other threads should touch.
    """

    def __init__(self, backend, *,
                 experiment_name: Optional[str] = None,
                 trial_name: Optional[str] = None,
                 server_name: str = "rollout/0",
                 queue: Optional[RequestQueue] = None,
                 weight_sync: Optional[WeightSync] = None,
                 max_staleness: Optional[int] = None,
                 stream_tokens: bool = True,
                 prefix_cache=None,
                 seed: int = 0,
                 fleet=None,
                 chaos: Optional[fault_injection.NetChaos] = None,
                 grow_advisor=None,
                 drain_deadline_secs: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.server_name = server_name
        self._clock = clock
        #: hard cap on how long any drain may wait for in-flight work
        #: before force-fencing it with explicit terminals
        self.drain_deadline_secs = drain_deadline_secs
        # RequestQueue.__bool__ is True even when empty, so `or` no
        # longer swallows a caller-provided empty queue
        self.queue = queue or RequestQueue(
            n_slots=getattr(backend, "n_slots", 1))
        if self.queue.max_prompt_len is None:
            # oversized prompts must be rejected at admission -- past
            # this point they only surface as a fill_slot failure deep
            # inside the scheduler
            self.queue.max_prompt_len = getattr(
                backend, "max_prompt_len", None)
        self.weight_sync = weight_sync or WeightSync()
        self.scheduler = ContinuousScheduler(
            backend, self.queue, self.weight_sync,
            max_staleness=max_staleness, stream_tokens=stream_tokens,
            prefix_cache=prefix_cache, clock=clock)
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        port = self._sock.bind_to_random_port("tcp://*")
        self.address = f"tcp://{network.gethostip()}:{port}"
        if experiment_name is not None and trial_name is not None:
            name_resolve.add(
                rollout_server_key(experiment_name, trial_name,
                                   server_name),
                self.address, replace=True)
        # rid -> client identity. Guarded by _routes_lock: drain() and
        # stats() may run from a supervising thread while the serve
        # loop spins in another (serve_forever). The lock covers ONLY
        # route-table reads/mutations -- pickling and socket sends
        # happen outside it (conc-lock-blocking: a stalled peer must
        # not stall every thread contending for the table).
        self._routes: Dict[str, bytes] = {}
        self._routes_lock = threading.Lock()
        # rid -> open request span (obs/tracing.py), parented to the
        # context the client injected into its submit envelope;
        # finished when the terminal event for the rid is delivered.
        # Touched only from the serve-loop thread.
        self._request_spans: Dict[str, tracing.Span] = {}
        import jax
        self._key = jax.random.PRNGKey(seed)
        self._draining = False
        self._closed = False
        # network chaos shim (docs/serving.md "Chaos drills"): None in
        # production unless REALHF_TPU_FAULTS carries net_* specs
        self._chaos = chaos if chaos is not None \
            else fault_injection.default_net_chaos()
        # fleet membership (serving/fleet.py): register under a
        # keepalive lease and renew it from the serve loop; losing the
        # lease fences this replica out until it re-registers (and the
        # router reconnects at the new epoch)
        self._fleet = fleet
        self._grow_advisor = grow_advisor
        self.fencing_epoch: Optional[int] = None
        self._lease_renewed_at = self._clock()
        #: set (from any thread) when a renewal found the lease gone;
        #: the serve loop turns it into fence-flush + re-register
        self._lease_lost = False
        if fleet is not None:
            self.fencing_epoch = fleet.register(server_name,
                                                self.address)
        logger.info("Rollout server %s listening on %s.", server_name,
                    self.address)

    # ------------------------------------------------------------------
    def serve_step(self, poll_timeout: float = 0.0) -> int:
        """One serve iteration: pump the socket (waiting up to
        ``poll_timeout`` seconds for the first message when idle), run
        the scheduler, deliver events. Returns how many client
        messages were handled."""
        # lease upkeep FIRST: a fenced-out replica must discard its
        # pre-fence work before it pumps or serves anything
        self._renew_lease()
        handled = self._pump_socket(poll_timeout)
        metrics.set_gauge("serving_queue_depth", len(self.queue),
                          server=self.server_name)
        metrics.set_gauge("serving_live_slots", self.scheduler.n_live,
                          server=self.server_name)
        if self._grow_advisor is not None:
            # autoscaling advisory (system/elastic.py GrowAdvisor):
            # sustained queue depth above threshold -> log-only
            # ElasticPlanner grow suggestion
            self._grow_advisor.observe(len(self.queue),
                                       server=self.server_name)
        if self.scheduler.n_live or len(self.queue):
            import jax
            self._key, sub = jax.random.split(self._key)
            events = self.scheduler.step(sub, admit=not self._draining)
            self._deliver(events)
        else:
            # install pushed weights even with no traffic (no decode
            # chunk is in flight, so swapping is safe): otherwise a
            # client insisting on min_weight_version is rejected
            # "weights_behind" forever -- the rejection enqueues
            # nothing, so no scheduler step would ever run the poll
            self.scheduler.poll_weights()
        for req in self.queue.take_expired():
            self._send(req.rid, protocol.EXPIRED, {})
        return handled

    def serve_forever(self, stop_event, poll_timeout: float = 0.02,
                      drain_timeout: float = 30.0):
        """Loop until ``stop_event`` is set, then drain gracefully."""
        while not stop_event.is_set():
            self.serve_step(poll_timeout=poll_timeout)
        self.drain(timeout=drain_timeout)

    # ------------------------------------------------------------------
    def lease_beat(self):
        """One fleet-lease renewal, safe from ANY thread -- meant to
        ride the worker's heartbeat beacon
        (``WorkerServer.add_beat_hook``) so the lease keeps beating
        while the serve loop sits in a multi-minute jit compile or a
        long decode chunk. Renewal failure only RECORDS the loss; the
        serve loop owns the fence-flush + re-registration (scheduler
        state is confined to it)."""
        if self._fleet is None or self._lease_lost or self._draining:
            return
        if self._chaos is not None \
                and self._chaos.partitioned(self.server_name):
            return  # registry invisible: lease keeps decaying
        from realhf_tpu.serving.fleet import LeaseLostError
        try:
            self._fleet.renew(self.server_name)
            self._lease_renewed_at = self._clock()
        except LeaseLostError:
            self._lease_lost = True

    def _renew_lease(self):
        """Fleet-mode lease upkeep, called from the serve loop. Renews
        on a ttl/3 cadence (on top of any heartbeat-thread
        ``lease_beat``); a lost lease means this replica is FENCED:
        its in-flight work was (or is being) failed over by the
        router, so it drops everything un-delivered and re-registers
        for a fresh fencing epoch before serving again. During a
        ``partition`` chaos window the registry is unreachable, so the
        lease decays exactly as it would on a real network split."""
        if self._fleet is None:
            return
        if not self._lease_lost:
            now = self._clock()
            if now - self._lease_renewed_at \
                    < self._fleet.lease_ttl / 3.0:
                return
            self.lease_beat()
            if not self._lease_lost:
                return
        # fenced: discard pre-fence work, then rejoin under a new epoch
        dropped = self._flush_fenced()
        self.fencing_epoch = self._fleet.register(self.server_name,
                                                  self.address)
        self._lease_renewed_at = self._clock()
        self._lease_lost = False
        metrics.inc("serving_fenced_total", server=self.server_name)
        logger.warning(
            "Rollout server %s lost its fleet lease: %d in-flight/"
            "queued request(s) dropped (already failed over); "
            "re-registered with fencing epoch %d.", self.server_name,
            dropped, self.fencing_epoch)

    def _flush_fenced(self) -> int:
        """Drop every queued and in-flight request WITHOUT sending
        terminal events: a fenced-out replica must serve nothing --
        the router has already failed this work over, and a late
        terminal from here would be a duplicate delivery."""
        dropped = 0
        while True:
            req = self.queue.pop()
            if req is None:
                break
            dropped += 1
        dropped += len(self.queue.take_expired())
        for rid in self.scheduler.active_rids():
            # evicts immediately and emits no event -- nothing from
            # before the fence may leave this replica
            self.scheduler.cancel(rid)
            dropped += 1
        with self._routes_lock:
            # deliberate terminal-less retirement: a FENCED replica
            # must deliver nothing -- the router already failed this
            # work over, and a late terminal from here would be a
            # duplicate (docs/serving.md "Fleet, failover & circuit
            # breakers")
            self._routes.clear()  # graft-lint: disable=proto-missing-terminal
        for sp in self._request_spans.values():
            sp.set_attribute("outcome", "fenced")
            sp.finish()
        self._request_spans.clear()
        metrics.inc("serving_fenced_dropped_total", amount=dropped,
                    server=self.server_name)
        return dropped

    # ------------------------------------------------------------------
    def _pump_socket(self, poll_timeout: float) -> int:
        n = 0
        while self._sock.poll(poll_timeout * 1000 if n == 0 else 0):
            ident, raw = self._sock.recv_multipart()
            if self._chaos is not None and self._chaos.check(
                    self.server_name, "recv") == "drop":
                n += 1
                continue
            try:
                msg = pickle.loads(raw)
                self._handle(ident, msg)
            except Exception as e:  # noqa: BLE001 - a malformed client
                # message must not kill the serve loop
                logger.error("Bad client message: %r", e)
            n += 1
        return n

    def _handle(self, ident: bytes, msg: tuple):
        kind = msg[0]
        if kind == protocol.SUBMIT:
            # 7th element (optional, newer clients): trace-context
            # carrier injected by RolloutClient.submit -- the serving
            # request span parents there, so the client's timeline and
            # the server's line up in one merged trace
            _, rid, prompt, priority, ttl, min_wv = msg[:6]
            trace_ctx = msg[6] if len(msg) > 6 else None
            now = self._clock()
            if self._draining:
                self._reply(ident, protocol.REJECTED, rid,
                            dict(reason=protocol.REASON_DRAINING,
                                 retry_after=None))
                return
            with self._routes_lock:
                known = rid in self._routes
                if known:
                    # duplicate submit of a rid still queued/serving
                    # here: a router-shard failover re-dispatch (the
                    # adopting shard re-sends rids its dead peer had
                    # in flight). Re-attach the delivery route to the
                    # newest submitter instead of double-queueing --
                    # the work continues once and its terminal flows
                    # to the live shard (docs/serving.md "Sharded
                    # router plane").
                    self._routes[rid] = ident
            if known:
                metrics.inc("serving_reattached_total",
                            server=self.server_name)
                self._reply(ident, protocol.ACCEPTED, rid,
                            dict(reattached=True,
                                 queue_depth=len(self.queue)))
                return
            req = GenRequest(
                rid=rid, prompt=np.asarray(prompt, np.int32),
                priority=Priority(priority),
                deadline=None if ttl is None else now + ttl,
                submitted_at=now, min_weight_version=min_wv)
            verdict: AdmissionVerdict = self.queue.submit(
                req, current_weight_version=self.weight_sync.version)
            if verdict.accepted:
                with self._routes_lock:
                    self._routes[rid] = ident
                if tracing.enabled():
                    self._request_spans[rid] = tracing.start_span(
                        "serve:request",
                        parent=tracing.extract(trace_ctx),
                        rid=rid, server=self.server_name,
                        priority=int(priority),
                        prompt_len=len(req.prompt))
                self._reply(ident, protocol.ACCEPTED, rid,
                            dict(queue_depth=len(self.queue)))
            else:
                metrics.inc("serving_rejections_total",
                            reason=verdict.reason or "unknown")
                self._reply(ident, protocol.REJECTED, rid,
                            dict(reason=verdict.reason,
                                 retry_after=verdict.retry_after))
        elif kind == protocol.CANCEL:
            rid = msg[1]
            if self.queue.cancel(rid) or self.scheduler.cancel(rid):
                self._send(rid, protocol.CANCELLED, {})
        elif kind == protocol.PING:
            self._reply(ident, protocol.PONG, "", {})
        else:
            logger.warning("Unknown client message kind %r.", kind)

    # ------------------------------------------------------------------
    def _deliver(self, events: List[ServeEvent]):
        for ev in events:
            data = ev.data
            if ev.kind == protocol.DONE:
                r = data["result"]
                # replica-side end-to-end latency (queue wait +
                # serve), bucketed so a /metrics scrape yields
                # per-replica quantiles (docs/observability.md)
                metrics.observe_hist(
                    "serve_request_seconds",
                    float(r.queued_secs or 0.0)
                    + float(r.serve_secs or 0.0),
                    server=self.server_name)
                data = dict(tokens=r.tokens, logprobs=r.logprobs,
                            no_eos=r.no_eos,
                            weight_version=r.weight_version,
                            weight_version_final=r.weight_version_final,
                            queued_secs=r.queued_secs,
                            serve_secs=r.serve_secs,
                            spec_proposed=r.spec_proposed,
                            spec_accepted=r.spec_accepted)
            self._send(ev.rid, ev.kind, data)

    def _send(self, rid: str, kind: str, data: dict):
        with self._routes_lock:
            ident = self._routes.get(rid)
        if ident is None:
            return
        if self._chaos is not None and self._chaos.check(
                self.server_name, f"send.{kind}") == "drop":
            # the wire ate it; same contract as a zmq send failure:
            # the route survives so a later terminal can still close
            # the stream (and the router's timeouts drive failover)
            metrics.inc("serving_chaos_dropped_total",
                        server=self.server_name)
            return
        # pickle + send OUTSIDE the lock: serialization of token
        # arrays and a blocking peer must not hold up other threads'
        # route lookups
        payload = pickle.dumps((kind, rid, data))
        try:
            self._sock.send_multipart([ident, payload])
        except zmq.ZMQError as e:
            # keep the route: a terminal event dropped here would
            # otherwise be lost for good, blocking the client until
            # its own timeout; with the route intact a later terminal
            # event (e.g. drain-time cancel) can still reach it
            logger.warning("Dropping %s for %s (route kept): %s",
                           kind, rid, e)
            return
        if kind in TERMINAL_KINDS:
            # drop only AFTER the send succeeded (PR-2 semantics)
            with self._routes_lock:
                self._routes.pop(rid, None)
            sp = self._request_spans.pop(rid, None)
            if sp is not None:
                sp.set_attribute("outcome", kind)
                sp.finish()

    def _reply(self, ident: bytes, kind: str, rid: str, data: dict):
        if self._chaos is not None and self._chaos.check(
                self.server_name, f"send.{kind}") == "drop":
            metrics.inc("serving_chaos_dropped_total",
                        server=self.server_name)
            return
        payload = pickle.dumps((kind, rid, data))
        self._sock.send_multipart([ident, payload])
        if kind in TERMINAL_KINDS:
            with self._routes_lock:
                self._routes.pop(rid, None)

    # ------------------------------------------------------------------
    def begin_drain(self) -> int:
        """Start a graceful drain WITHOUT blocking: mark this replica
        retiring in the fleet registry (the router stops dispatching
        here but keeps pumping our in-flight work -- and treats our
        eventual departure as planned, not LOST), refuse new work, and
        bounce queued requests back to their clients as ``draining``.
        In-flight sequences keep finishing through subsequent
        ``serve_step`` calls; callers end with :meth:`finish_drain`.
        Returns how many queued requests were bounced."""
        if self._draining:
            return 0
        self._draining = True
        if self._fleet is not None:
            self._fleet.mark_retiring(self.server_name)
        bounced = self.queue.start_drain()
        # a request parked on KV-pool backpressure is queued work too
        bounced += self.scheduler.take_parked()
        for req in bounced:
            self._send(req.rid, protocol.DRAINING, {})
        return len(bounced)

    def finish_drain(self, force: bool = False) -> List[str]:
        """Close out a drain: with ``force``, any sequence still in
        flight (the drain exceeded its hard deadline) is force-fenced
        with an EXPLICIT ``cancelled(reason=drain_deadline)`` terminal
        -- never silent loss -- and a flight event names the abandoned
        rids; a fronting router shops those requests to survivors.
        Finally the fleet lease is released so the router sees a
        planned departure. Returns the abandoned rids."""
        abandoned: List[str] = []
        if force:
            for rid in self.scheduler.active_rids():
                self.scheduler.cancel(rid)
                self._send(rid, protocol.CANCELLED,
                           dict(reason=protocol.REASON_DRAIN_DEADLINE))
                abandoned.append(rid)
            if abandoned:
                from realhf_tpu.obs import flight
                metrics.inc("serving_drain_abandoned_total",
                            amount=len(abandoned),
                            server=self.server_name)
                flight.record("serving_drain_abandoned",
                              server=self.server_name,
                              rids=sorted(abandoned),
                              n=len(abandoned))
                logger.warning(
                    "Rollout server %s: drain deadline exceeded; %d "
                    "in-flight request(s) force-fenced with explicit "
                    "terminals: %s.", self.server_name,
                    len(abandoned), sorted(abandoned))
        if self._fleet is not None:
            # leave the fleet NOW instead of letting the lease decay:
            # the router stops dispatching here immediately
            self._fleet.deregister(self.server_name)
        return abandoned

    def drain(self, timeout: float = 30.0):
        """Graceful shutdown: refuse new work, bounce queued requests,
        finish in-flight sequences, leave nothing orphaned. In-flight
        work past the hard deadline (``min(timeout,
        drain_deadline_secs)``) is force-fenced with explicit
        terminals (:meth:`finish_drain`), never silently dropped."""
        if self.drain_deadline_secs is not None:
            timeout = min(timeout, self.drain_deadline_secs)
        # re-runnable: a drain after an earlier begin_drain() (e.g. a
        # `drain` worker command followed by the exit hook) must still
        # wait out in-flight work and release the lease
        bounced = self.begin_drain()
        deadline = self._clock() + timeout
        while self.scheduler.n_live and self._clock() < deadline:
            self.serve_step(poll_timeout=0.0)
        self.finish_drain(force=True)
        logger.info(
            "Rollout server %s drained: %d queued bounced, stats=%s.",
            self.server_name, bounced, self.stats())

    def close(self):
        if not self._closed:
            self._closed = True
            if self._fleet is not None and not self._draining:
                self._fleet.deregister(self.server_name)
            self._sock.close(0)

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        out = dict(self.scheduler.stats,
                   queue_depth=len(self.queue),
                   queue_by_class=self.queue.depth_by_class(),
                   queue_stats=dict(self.queue.stats),
                   n_live=self.scheduler.n_live,
                   weight_version=self.weight_sync.version,
                   fencing_epoch=self.fencing_epoch,
                   draining=self._draining)
        if self.scheduler.prefix_cache is not None:
            out["prefix_cache"] = self.scheduler.prefix_cache.snapshot()
        if self.scheduler.last_pool_stats is not None:
            out["kv_pool"] = dict(self.scheduler.last_pool_stats)
        return out


# ----------------------------------------------------------------------
@dataclasses.dataclass
class RolloutResult:
    """Terminal outcome of one request, as seen by the client."""
    rid: str
    status: str                 # done | rejected | stale | expired | ...
    data: dict

    @property
    def ok(self) -> bool:
        return self.status == protocol.DONE

    @property
    def tokens(self) -> Optional[np.ndarray]:
        return self.data.get("tokens") if self.ok else None

    @property
    def weight_version(self) -> Optional[int]:
        return self.data.get("weight_version")


class RolloutClient:
    """DEALER-side client: submit/stream/cancel against one server.

    Not thread-safe (one socket); use one client per thread. Many
    requests may be in flight on one client -- replies demultiplex by
    rid into per-request event queues.
    """

    def __init__(self, address: Optional[str] = None, *,
                 experiment_name: Optional[str] = None,
                 trial_name: Optional[str] = None,
                 server_name: str = "rollout/0",
                 resolve_timeout: float = 60.0):
        if address is None:
            address = name_resolve.wait(
                rollout_server_key(experiment_name, trial_name,
                                   server_name),
                timeout=resolve_timeout)
        self.address = address
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.connect(address)
        self._events: Dict[str, List[tuple]] = {}
        # rids abandoned mid-stream (cancel + forget): late events for
        # them are dropped instead of resurrecting an _events entry
        # nobody will ever read. Bounded: a tombstone retires when its
        # terminal event arrives, or FIFO past the cap (a terminal
        # lost on the wire must not pin the tombstone forever).
        self._abandoned: "collections.OrderedDict[str, bool]" = \
            collections.OrderedDict()
        self._abandoned_cap = 4096

    # ------------------------------------------------------------------
    def submit(self, prompt, priority: Priority = Priority.BATCH,
               ttl: Optional[float] = None, rid: Optional[str] = None,
               min_weight_version: int = 0) -> str:
        rid = rid or uuid.uuid4().hex
        self._abandoned.pop(rid, None)  # rid reuse revives the stream
        self._events.setdefault(rid, [])
        # trailing trace-context carrier (None when tracing is off):
        # the server parents its serve:request span there, stitching
        # client and server into one timeline
        self._sock.send(pickle.dumps(
            (protocol.SUBMIT, rid, np.asarray(prompt, np.int32),
             int(priority), ttl, min_weight_version,
             tracing.inject())))
        return rid

    def cancel(self, rid: str):
        self._sock.send(pickle.dumps((protocol.CANCEL, rid)))

    def abandon(self, rid: str):
        """Cancel AND forget: drop the request's local event state and
        suppress its late replies (mid-episode drop path, see
        ``agentic/episode.py``). Unlike plain ``cancel`` -- whose
        ``cancelled`` terminal the caller is expected to consume --
        nobody will ever read this rid's stream again, so without the
        tombstone a late token/terminal event would silently re-create
        ``_events[rid]`` and leak it forever."""
        self._events.pop(rid, None)
        self._abandoned[rid] = True
        while len(self._abandoned) > self._abandoned_cap:
            self._abandoned.popitem(last=False)
        self._sock.send(pickle.dumps((protocol.CANCEL, rid)))

    def ping(self, timeout: float = 10.0) -> bool:
        self._sock.send(pickle.dumps((protocol.PING,)))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self._pump(deadline - time.monotonic()):
                break
            q = self._events.get("", [])
            if any(k == protocol.PONG for k, _ in q):
                q.clear()
                return True
        return False

    # ------------------------------------------------------------------
    def _pump(self, timeout: float) -> bool:
        """Receive every available reply (waiting up to ``timeout``
        for the first); returns whether anything arrived."""
        got = False
        while self._sock.poll(0 if got else max(0.0, timeout) * 1000):
            kind, rid, data = pickle.loads(self._sock.recv())
            got = True
            if rid in self._abandoned:
                if kind in TERMINAL_KINDS:
                    # stream closed server-side: tombstone retires
                    self._abandoned.pop(rid, None)
                continue
            self._events.setdefault(rid, []).append((kind, data))
        return got

    def next_event(self, rid: str, timeout: float = 60.0) -> tuple:
        """Next ``(kind, data)`` for ``rid``; raises TimeoutError."""
        deadline = time.monotonic() + timeout
        while True:
            q = self._events.get(rid)
            if q:
                return q.pop(0)
            if not self._pump(deadline - time.monotonic()) \
                    and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"No event for request {rid} within {timeout}s.")

    def stream(self, rid: str, timeout: float = 60.0):
        """Yield ``(kind, data)`` events up to and including the
        terminal one."""
        while True:
            kind, data = self.next_event(rid, timeout=timeout)
            yield kind, data
            if kind in TERMINAL_KINDS:
                return

    def result(self, rid: str, timeout: float = 60.0) -> RolloutResult:
        """Block until the request reaches a terminal state."""
        for kind, data in self.stream(rid, timeout=timeout):
            if kind in TERMINAL_KINDS:
                return RolloutResult(rid=rid, status=kind, data=data)
        raise AssertionError("stream ended without a terminal event")

    def poll_results(self, timeout: float = 0.0) -> List[RolloutResult]:
        """Non-blocking harvest (waiting up to ``timeout`` for the
        first reply): every request that has reached a terminal state,
        in arrival order. Intermediate events (accepted / started /
        token deltas) of harvested requests are discarded -- this is
        the fire-hose surface the ``RolloutController`` drains to keep
        training fed; use ``stream``/``next_event`` when the
        incremental events matter."""
        self._pump(timeout)
        out: List[RolloutResult] = []
        for rid in list(self._events):
            terminal = next(
                ((k, d) for k, d in self._events[rid]
                 if k in TERMINAL_KINDS), None)
            if terminal is not None:
                del self._events[rid]
                out.append(RolloutResult(
                    rid=rid, status=terminal[0], data=terminal[1]))
        return out

    def close(self):
        self._sock.close(0)
