"""FleetRouter: health-aware routing in front of N rollout replicas.

The resilience layer of the serving fleet (docs/serving.md "Fleet,
failover & circuit breakers"). Clients speak the ordinary
``RolloutClient`` wire protocol to the router's front ROUTER socket;
the router holds one DEALER per live replica (discovered through the
:class:`~realhf_tpu.serving.fleet.FleetRegistry` lease subtree) and
keeps the fleet correct and available while replicas die, hang, and
partition underneath it:

- **Health-aware least-loaded dispatch**: new requests go to the
  healthy replica with the fewest router-tracked in-flight requests.
- **Per-replica circuit breakers**: consecutive failures/timeouts open
  the breaker; after a cooldown it half-opens and a single in-loop
  ping probe decides between closing and re-opening.
- **Idempotent request ids**: the client's rid travels unchanged to
  every replica that ever works on it, so hedged duplicates and
  failover re-dispatches are safe -- duplicate terminal events are
  deduped at the router and the client sees exactly one (at-most-once
  delivery).
- **In-flight failover**: when a lease expires (or a watchdog calls
  :meth:`notify_lost`), the LOST replica's un-harvested requests are
  re-dispatched to healthy replicas with a ``retried_from`` stamp
  instead of vanishing. A streaming client is told via a ``retrying``
  event that its token stream restarts.
- **Hedging**: a request that has not started within ``hedge_delay``
  is speculatively dispatched to a second replica; the first terminal
  event wins and the loser is cancelled.
- **Fencing**: each replica connection is pinned to the fencing epoch
  it rendezvoused at. A re-registration (new epoch) atomically swaps
  the connection; the old socket is closed, so a zombie incarnation
  cannot deliver anything through the router.

Single-threaded like ``RolloutServer``: drive :meth:`route_step` from
a worker poll loop (``RouterWorker``) or a dedicated thread. The only
blocking entry point is :meth:`probe`, a hedged health check meant for
startup/ops use outside the serve loop.
"""

import dataclasses
import enum
import pickle
import time
from typing import Callable, Dict, List, Optional, Set

import numpy as np
import zmq

from realhf_tpu.base import fault_injection, logging, name_resolve, \
    network, retry
from realhf_tpu.obs import metrics
from realhf_tpu.serving import protocol
from realhf_tpu.serving.fleet import FleetRegistry, ReplicaInfo
from realhf_tpu.serving.protocol import TERMINAL_KINDS
from realhf_tpu.serving.server import rollout_server_key

logger = logging.getLogger("serving.router", "system")


class BreakerState(enum.Enum):
    CLOSED = 0
    HALF_OPEN = 1
    OPEN = 2


class CircuitBreaker:
    """Per-replica failure gate: ``failure_threshold`` consecutive
    failures open it; after ``cooldown`` seconds it may half-open for
    exactly one probe, whose outcome closes or re-opens it. Successes
    in any state reset the failure count and close."""

    def __init__(self, failure_threshold: int = 3, cooldown: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None):
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self._clock = clock
        self._on_transition = on_transition
        self.state = BreakerState.CLOSED
        self.failures = 0
        self.opened_at: Optional[float] = None

    def _to(self, state: BreakerState):
        if state is self.state:
            return
        prev, self.state = self.state, state
        if self._on_transition is not None:
            self._on_transition(prev, state)

    def record_success(self):
        self.failures = 0
        self._to(BreakerState.CLOSED)

    def record_failure(self):
        self.failures += 1
        if self.state is BreakerState.HALF_OPEN \
                or self.failures >= self.failure_threshold:
            self._to(BreakerState.OPEN)
            self.opened_at = self._clock()

    def force_open(self):
        """Immediate open (lease expiry / watchdog LOST): no need to
        accumulate failures against a replica known dead."""
        self.failures = max(self.failures, self.failure_threshold)
        self._to(BreakerState.OPEN)
        self.opened_at = self._clock()

    def allow(self) -> bool:
        return self.state is BreakerState.CLOSED

    def ready_to_probe(self) -> bool:
        return (self.state is BreakerState.OPEN
                and self.opened_at is not None
                and self._clock() - self.opened_at >= self.cooldown)

    def half_open(self):
        if self.state is BreakerState.OPEN:
            self._to(BreakerState.HALF_OPEN)


@dataclasses.dataclass
class _Replica:
    name: str
    address: str
    epoch: int
    sock: object
    breaker: CircuitBreaker
    inflight: Set[str] = dataclasses.field(default_factory=set)
    lost: bool = False
    #: deliberately draining (FleetRegistry retiring flag): no NEW
    #: dispatch, but its socket keeps pumping so in-flight work still
    #: delivers; its eventual disappearance is a planned departure
    retiring: bool = False
    probe_sent_at: Optional[float] = None


@dataclasses.dataclass
class _RouterRequest:
    rid: str
    ident: bytes
    prompt: np.ndarray
    priority: int
    min_weight_version: int
    trace: Optional[dict]
    created_at: float
    deadline: Optional[float]
    #: replica -> dispatch time, for every dispatch still outstanding
    assigned: Dict[str, float] = dataclasses.field(default_factory=dict)
    accepted: Set[str] = dataclasses.field(default_factory=set)
    #: replicas excluded from further dispatch of THIS rid
    failed: Set[str] = dataclasses.field(default_factory=set)
    #: hedge losers we cancelled (their `cancelled` terminal is
    #: bookkeeping, not the client's outcome)
    losers: Set[str] = dataclasses.field(default_factory=set)
    owner: Optional[str] = None
    primary: Optional[str] = None
    retried_from: List[str] = dataclasses.field(default_factory=list)
    hedged: bool = False
    accepted_fwd: bool = False
    started_fwd: bool = False
    last_event_at: float = 0.0
    client_cancelled: bool = False


_BREAKER_GAUGE = {BreakerState.CLOSED: 0, BreakerState.HALF_OPEN: 1,
                  BreakerState.OPEN: 2}


class FleetRouter:
    """Front a fleet of ``RolloutServer`` replicas (module doc)."""

    def __init__(self, registry: FleetRegistry, *,
                 router_name: str = "router/0",
                 experiment_name: Optional[str] = None,
                 trial_name: Optional[str] = None,
                 publish_name: str = "router",
                 max_pending: int = 1024,
                 dispatch_timeout: float = 10.0,
                 response_timeout: Optional[float] = 60.0,
                 pending_timeout: float = 60.0,
                 hedge_delay: Optional[float] = None,
                 max_hedges: int = 1,
                 breaker_failures: int = 3,
                 breaker_cooldown: float = 5.0,
                 probe_timeout: float = 2.0,
                 fleet_poll_interval: float = 0.5,
                 affinity_prefix_len: int = 16,
                 chaos: Optional[fault_injection.NetChaos] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.router_name = router_name
        self.registry = registry
        self.max_pending = max_pending
        self.dispatch_timeout = dispatch_timeout
        self.response_timeout = response_timeout
        self.pending_timeout = pending_timeout
        self.hedge_delay = hedge_delay
        self.max_hedges = max_hedges
        self.breaker_failures = breaker_failures
        self.breaker_cooldown = breaker_cooldown
        self.probe_timeout = probe_timeout
        self.fleet_poll_interval = fleet_poll_interval
        # prefix-affinity dispatch: requests whose first
        # `affinity_prefix_len` tokens hash alike prefer the replica
        # that last served that hash, concentrating that replica's
        # radix prefix-cache hits (serving/prefix_cache.py). Strictly
        # a PREFERENCE among healthy candidates -- lost/fenced/open-
        # breaker replicas are filtered before affinity looks, and a
        # cold hash falls back to least-loaded. 0 disables.
        self.affinity_prefix_len = affinity_prefix_len
        #: prefix hash -> replica that last served it (bounded,
        #: insertion-ordered for cheap oldest-first trimming)
        self._affinity: Dict[int, str] = {}
        self._affinity_cap = 8192
        self._clock = clock
        self._chaos = chaos if chaos is not None \
            else fault_injection.default_net_chaos()
        self._ctx = zmq.Context.instance()
        self._front = self._ctx.socket(zmq.ROUTER)
        port = self._front.bind_to_random_port("tcp://*")
        self.address = f"tcp://{network.gethostip()}:{port}"
        if experiment_name is not None and trial_name is not None:
            # clients rendezvous exactly as they would with a single
            # server: RolloutClient(..., server_name="router")
            name_resolve.add(
                rollout_server_key(experiment_name, trial_name,
                                   publish_name),
                self.address, replace=True)
        self._replicas: Dict[str, _Replica] = {}
        self._requests: Dict[str, _RouterRequest] = {}
        self._pending: List[str] = []      # rids awaiting a replica
        #: recently-finished rids for duplicate-terminal dedupe,
        #: bounded so a long-lived router cannot grow without limit
        self._done: Dict[str, str] = {}    # rid -> outcome kind
        self._done_cap = 8192
        self._last_fleet_poll = -1e9
        self._draining = False
        self._closed = False
        self.stats_counters = dict(
            requests=0, dispatches=0, failovers=0, hedges=0,
            hedge_wins=0, duplicate_terminals=0, stale_events=0,
            fenced_reconnects=0, affinity_hits=0, rejections=0,
            retired=0, retire_redispatches=0)
        #: EWMA of done-request end-to-end latency (autoscale signal)
        self.latency_ewma_secs: Optional[float] = None
        logger.info("Fleet router %s listening on %s.", router_name,
                    self.address)

    # -- fleet membership ----------------------------------------------
    def _set_breaker_gauge(self, name: str, state: BreakerState):
        metrics.set_gauge("router_breaker_state",
                          _BREAKER_GAUGE[state], replica=name)

    def _make_breaker(self, name: str) -> CircuitBreaker:
        def on_transition(prev, new, _name=name):
            metrics.inc("router_breaker_transitions_total",
                        replica=_name, to=new.name.lower())
            self._set_breaker_gauge(_name, new)
            logger.info("Router breaker for %s: %s -> %s.", _name,
                        prev.name, new.name)

        br = CircuitBreaker(self.breaker_failures, self.breaker_cooldown,
                            clock=self._clock,
                            on_transition=on_transition)
        self._set_breaker_gauge(name, br.state)
        return br

    def _connect(self, info: ReplicaInfo) -> object:
        sock = self._ctx.socket(zmq.DEALER)
        try:
            sock.connect(info.address)
        except BaseException:
            # a malformed replica address must not leak the socket
            # (graft-lint lifecycle-leak-on-raise)
            sock.close(0)
            raise
        return sock

    def _refresh_fleet(self, force: bool = False):
        now = self._clock()
        if not force and now - self._last_fleet_poll \
                < self.fleet_poll_interval:
            return
        self._last_fleet_poll = now
        live = self.registry.replicas()
        for name, info in live.items():
            rep = self._replicas.get(name)
            if rep is None:
                self._replicas[name] = _Replica(
                    name=name, address=info.address, epoch=info.epoch,
                    sock=self._connect(info),
                    breaker=self._make_breaker(name),
                    retiring=info.retiring)
                logger.info("Router: replica %s joined (epoch %d, "
                            "%s).", name, info.epoch, info.address)
                continue
            if info.retiring and not rep.retiring:
                logger.info("Router: replica %s retiring (%d in "
                            "flight finish there; no new dispatch).",
                            name, len(rep.inflight))
            rep.retiring = info.retiring
            if info.epoch != rep.epoch or info.address != rep.address:
                # re-registration: the old connection belongs to a
                # fenced-out incarnation -- swap it atomically so the
                # zombie cannot deliver anything, and fail over work
                # that was riding on it
                logger.warning(
                    "Router: replica %s re-registered (epoch %d -> "
                    "%d); fencing the old connection.", name,
                    rep.epoch, info.epoch)
                self.stats_counters["fenced_reconnects"] += 1
                metrics.inc("router_fenced_reconnects_total",
                            replica=name)
                self._failover_replica(rep, why=protocol.WHY_REREGISTERED)
                rep.sock.close(0)
                rep.sock = self._connect(info)
                rep.address, rep.epoch = info.address, info.epoch
                rep.lost = False
            elif rep.lost:
                # lease reappeared with the SAME epoch: renewals
                # resumed before expiry was observed consistently
                rep.lost = False
        for name, rep in list(self._replicas.items()):
            if name not in live and not rep.lost:
                if rep.retiring or self.registry.is_retiring(name):
                    # deliberate departure (scale-down drain finished
                    # and the lease was released): NOT a loss -- no
                    # breaker trip, no failover accounting
                    self._retire_replica(rep)
                else:
                    self._mark_lost(rep, why=protocol.WHY_LEASE_EXPIRED)
        n_healthy = sum(1 for r in self._replicas.values()
                        if not r.lost and not r.retiring
                        and r.breaker.allow())
        metrics.set_gauge("router_replicas", len(live), state="live")
        metrics.set_gauge("router_replicas", n_healthy, state="healthy")

    def notify_lost(self, name: str):
        """Watchdog hook: mark a replica LOST now, without waiting for
        its lease to expire (``Watchdog(on_lost=router.notify_lost)``
        when both live in one process). A replica mid-retire is exempt
        -- its drain already stopped the heartbeat-adjacent work the
        watchdog keys on, and :meth:`_retire_replica` (or the lease
        fallback) recovers anything it leaves behind."""
        rep = self._replicas.get(name)
        if rep is not None and not rep.lost and not rep.retiring:
            self._mark_lost(rep, why=protocol.WHY_WATCHDOG_LOST)

    def _retire_replica(self, rep: _Replica):
        """Planned departure (docs/serving.md "Autoscaling"): the
        replica drained and released its lease. No breaker
        transition, no failover counter -- a clean scale-down is
        indistinguishable from nothing having happened, except that
        any request the drain abandoned past its hard deadline is
        quietly re-dispatched (``retire_redispatches``) so nothing is
        ever orphaned by a scale-down."""
        leftovers = sorted(rep.inflight)
        logger.info("Router: replica %s retired cleanly (%d leftover "
                    "request(s) re-dispatched).", rep.name,
                    len(leftovers))
        self.stats_counters["retired"] += 1
        metrics.inc("router_replicas_retired_total", replica=rep.name)
        for rid in leftovers:
            req = self._requests.get(rid)
            if req is None:
                continue
            self._fail_assignment(req, rep.name,
                                  why=protocol.WHY_RETIRED,
                                  counter="retire_redispatches")
        rep.inflight.clear()
        rep.sock.close(0)
        self._replicas.pop(rep.name, None)
        # the departure has been consumed: drop the leaseless
        # retiring/ marker so a long-running trial that never reuses
        # replica names does not accumulate them (its TTL is only the
        # backstop for routerless consumers)
        self.registry.clear_retiring(rep.name)

    def _mark_lost(self, rep: _Replica, why: str):
        logger.warning("Router: replica %s LOST (%s); failing over "
                       "%d in-flight request(s).", rep.name, why,
                       len(rep.inflight))
        rep.lost = True
        rep.breaker.force_open()
        # close the socket NOW: anything the dead/zombie incarnation
        # still emits must not reach the router (fencing)
        rep.sock.close(0)
        self._failover_replica(rep, why=why)

    def _failover_replica(self, rep: _Replica, why: str):
        for rid in sorted(rep.inflight):
            req = self._requests.get(rid)
            if req is None:
                continue
            self._fail_assignment(req, rep.name, why=why)
        rep.inflight.clear()

    # -- client side ---------------------------------------------------
    def route_step(self, poll_timeout: float = 0.0) -> int:
        """One router iteration: refresh membership, pump the client
        socket (waiting up to ``poll_timeout`` seconds when idle) and
        every replica socket, then run dispatch/hedge/timeout/probe
        maintenance. Returns how many client messages were handled."""
        self._refresh_fleet()
        handled = self._pump_front(poll_timeout)
        self._pump_replicas()
        now = self._clock()
        self._check_timeouts(now)
        self._maybe_hedge(now)
        self._dispatch_pending()
        self._probe_breakers(now)
        metrics.set_gauge("router_pending", len(self._pending))
        metrics.set_gauge("router_inflight", len(self._requests))
        return handled

    def _pump_front(self, poll_timeout: float) -> int:
        n = 0
        while self._front.poll(poll_timeout * 1000 if n == 0 else 0):
            ident, raw = self._front.recv_multipart()
            if self._chaos is not None and self._chaos.check(
                    self.router_name, "recv") == "drop":
                continue
            try:
                self._handle_client(ident, pickle.loads(raw))
            except Exception as e:  # noqa: BLE001 - a malformed client
                # message must not kill the routing loop
                logger.error("Router: bad client message: %r", e)
            n += 1
        return n

    def _handle_client(self, ident: bytes, msg: tuple):
        kind = msg[0]
        if kind == protocol.SUBMIT:
            _, rid, prompt, priority, ttl, min_wv = msg[:6]
            trace = msg[6] if len(msg) > 6 else None
            now = self._clock()
            if rid in self._requests or rid in self._done:
                # idempotency: a duplicate submit of a known rid is
                # dropped, never double-dispatched
                self.stats_counters["stale_events"] += 1
                return
            if self._draining:
                self.stats_counters["rejections"] += 1
                self._reply(ident, protocol.REJECTED, rid,
                            dict(reason=protocol.REASON_DRAINING,
                                 retry_after=None))
                return
            if len(self._requests) >= self.max_pending:
                self.stats_counters["rejections"] += 1
                metrics.inc("router_rejections_total",
                            reason="backpressure")
                self._reply(ident, protocol.REJECTED, rid,
                            dict(reason=protocol.REASON_BACKPRESSURE,
                                 retry_after=1.0))
                return
            req = _RouterRequest(
                rid=rid, ident=ident,
                prompt=np.asarray(prompt, np.int32),
                priority=int(priority),
                min_weight_version=min_wv, trace=trace,
                created_at=now,
                deadline=None if ttl is None else now + ttl,
                last_event_at=now)
            self._requests[rid] = req
            self._pending.append(rid)
            self.stats_counters["requests"] += 1
            metrics.inc("router_requests_total")
        elif kind == protocol.CANCEL:
            rid = msg[1]
            req = self._requests.get(rid)
            if req is None:
                return
            req.client_cancelled = True
            if not req.assigned:
                self._finish(req, protocol.CANCELLED, {}, from_replica=None)
            else:
                for rname in list(req.assigned):
                    self._send_replica(rname, (protocol.CANCEL, rid))
        elif kind == protocol.PING:
            self._reply(ident, protocol.PONG, "", {})
        else:
            logger.warning("Router: unknown client message kind %r.",
                           kind)

    # -- replica side --------------------------------------------------
    def _pump_replicas(self):
        for rep in list(self._replicas.values()):
            if rep.lost:
                continue
            try:
                while rep.sock.poll(0):
                    raw = rep.sock.recv()
                    try:
                        kind, rid, data = pickle.loads(raw)
                    except Exception as e:  # noqa: BLE001
                        logger.error("Router: bad replica message "
                                     "from %s: %r", rep.name, e)
                        continue
                    self._on_replica_event(rep, kind, rid, data)
            except zmq.ZMQError as e:
                logger.warning("Router: recv from %s failed: %s.",
                               rep.name, e)
                rep.breaker.record_failure()

    def _on_replica_event(self, rep: _Replica, kind: str, rid: str,
                          data: dict):
        # any traffic proves the replica's serve loop is alive
        rep.breaker.record_success()
        rep.probe_sent_at = None
        if kind == protocol.PONG:
            return
        req = self._requests.get(rid)
        if req is None:
            rep.inflight.discard(rid)
            if rid in self._done and kind in TERMINAL_KINDS:
                # the hedge/failover twin already delivered: dedupe
                self.stats_counters["duplicate_terminals"] += 1
                metrics.inc("router_duplicate_terminals_total",
                            replica=rep.name)
            else:
                self.stats_counters["stale_events"] += 1
                metrics.inc("router_stale_events_total",
                            replica=rep.name)
            return
        req.last_event_at = self._clock()
        if kind == protocol.ACCEPTED:
            req.accepted.add(rep.name)
            if not req.accepted_fwd:
                req.accepted_fwd = True
                self._forward(req, kind, data)
            return
        if kind == protocol.STARTED:
            if req.owner is None:
                req.owner = rep.name
                if not req.started_fwd:
                    req.started_fwd = True
                    self._forward(req, kind, data)
            elif req.owner != rep.name:
                # hedge race: someone else leads; cancel this copy
                req.losers.add(rep.name)
                self._send_replica(rep.name, (protocol.CANCEL, rid))
            return
        if kind == protocol.TOKENS:
            if req.owner is None:
                req.owner = rep.name
            if req.owner == rep.name:
                self._forward(req, kind, data)
            return
        if kind in TERMINAL_KINDS:
            rep.inflight.discard(rid)
            req.assigned.pop(rep.name, None)
            if kind == protocol.CANCELLED and rep.name in req.losers \
                    and not req.client_cancelled:
                return  # a hedge loser acking our cancel: bookkeeping
            if kind == protocol.CANCELLED \
                    and data.get("reason") \
                    == protocol.REASON_DRAIN_DEADLINE \
                    and not req.client_cancelled:
                if req.owner not in (None, rep.name):
                    # a live hedge twin owns the client's stream; the
                    # victim's copy going away is pure bookkeeping
                    return
                # the replica's drain hit its hard deadline and
                # force-fenced this request (explicit terminal, never
                # silent). The victim had the request in flight and
                # may already own the client's stream (its `started`
                # was forwarded), so the bounce must go through the
                # failover bookkeeping -- owner cleared, `retrying`
                # emitted so a streaming client resets, rid parked in
                # _pending when no candidate is free right now --
                # otherwise the survivor's `started` would be
                # mistaken for a hedge race and cancelled, orphaning
                # the rid until its client-side TTL
                self._fail_assignment(
                    req, rep.name,
                    why=protocol.REASON_DRAIN_DEADLINE,
                    counter="retire_redispatches")
                return
            if kind in (protocol.REJECTED, protocol.DRAINING) \
                    and not req.client_cancelled:
                self._on_replica_reject(rep, req, kind, data)
                return
            self._finish(req, kind, data, from_replica=rep.name)
            return
        # unknown event kinds pass through to the owner's client
        if req.owner in (None, rep.name):
            self._forward(req, kind, data)

    def _on_replica_reject(self, rep: _Replica, req: _RouterRequest,
                           kind: str, data: dict):
        reason = data.get("reason", kind)
        if reason in protocol.DETERMINISTIC_REJECT_REASONS:
            # deterministic verdicts every replica would agree on:
            # forward, do not shop around
            self._finish(req,
                         protocol.REJECTED if kind == protocol.REJECTED
                         else kind,
                         data, from_replica=rep.name)
            return
        # transient (backpressure / draining / weights_behind): try
        # another replica; only when nobody is left does the client
        # see the rejection
        req.failed.add(rep.name)
        if self._dispatch(req):
            return
        if req.assigned:
            return  # a hedge twin is still working on it
        self._finish(req, kind, data, from_replica=rep.name)

    # -- dispatch ------------------------------------------------------
    def _candidates(self, req: _RouterRequest) -> List[_Replica]:
        out = [r for r in self._replicas.values()
               if not r.lost and not r.retiring and r.breaker.allow()
               and r.name not in req.assigned
               and r.name not in req.failed]
        # least-loaded, name as the deterministic tie-break
        out.sort(key=lambda r: (len(r.inflight), r.name))
        return out

    def _prefix_hash(self, req: _RouterRequest) -> Optional[int]:
        if self.affinity_prefix_len <= 0 or len(req.prompt) == 0:
            return None
        return hash(req.prompt[:self.affinity_prefix_len].tobytes())

    def _dispatch(self, req: _RouterRequest) -> bool:
        cands = self._candidates(req)
        if not cands:
            return False
        rep = cands[0]
        # prefix affinity: prefer the replica that last served this
        # prompt's leading tokens, IF it survived the health filters
        h = self._prefix_hash(req)
        if h is not None:
            preferred = self._affinity.get(h)
            match = [r for r in cands if r.name == preferred] \
                if preferred is not None else []
            if match:
                rep = match[0]
                self.stats_counters["affinity_hits"] += 1
                metrics.inc("router_affinity_hits_total",
                            replica=rep.name)
        now = self._clock()
        ttl = None if req.deadline is None \
            else max(0.05, req.deadline - now)
        env = (protocol.SUBMIT, req.rid, req.prompt, req.priority,
               ttl, req.min_weight_version, req.trace)
        if not self._send_replica(rep.name, env):
            return False
        req.assigned[rep.name] = now
        req.last_event_at = now
        if req.primary is None:
            req.primary = rep.name
        rep.inflight.add(req.rid)
        if h is not None:
            # last-served wins (re-insert refreshes recency); bounded
            # so a long-lived router's table cannot grow without limit
            self._affinity.pop(h, None)
            self._affinity[h] = rep.name
            while len(self._affinity) > self._affinity_cap:
                self._affinity.pop(next(iter(self._affinity)))
        self.stats_counters["dispatches"] += 1
        metrics.inc("router_dispatches_total", replica=rep.name)
        return True

    def _dispatch_pending(self):
        still: List[str] = []
        now = self._clock()
        for rid in self._pending:
            req = self._requests.get(rid)
            if req is None:
                continue
            if req.assigned or self._dispatch(req):
                continue
            if now - req.created_at > self.pending_timeout:
                metrics.inc("router_rejections_total",
                            reason="no_healthy_replica")
                self._finish(req, protocol.REJECTED,
                             dict(reason=
                                  protocol.REASON_NO_HEALTHY_REPLICA,
                                  retry_after=self.breaker_cooldown),
                             from_replica=None)
                continue
            still.append(rid)
        self._pending = still

    def _send_replica(self, rname: str, envelope: tuple) -> bool:
        rep = self._replicas.get(rname)
        if rep is None or rep.lost:
            return False
        if self._chaos is not None and self._chaos.check(
                self.router_name,
                f"dispatch.{envelope[0]}") == "drop":
            return True  # the wire ate it; timeouts must recover
        try:
            rep.sock.send(pickle.dumps(envelope))
            return True
        except zmq.ZMQError as e:
            logger.warning("Router: send to %s failed: %s.", rname, e)
            rep.breaker.record_failure()
            return False

    def _fail_assignment(self, req: _RouterRequest, rname: str,
                         why: str, counter: str = "failovers"):
        """One replica's copy of a request is gone (loss, stall,
        dispatch timeout -- or, with ``counter="retire_redispatches"``,
        a planned retire): exclude the replica for this rid and
        re-dispatch unless a twin is still live."""
        req.assigned.pop(rname, None)
        req.failed.add(rname)
        if req.owner == rname:
            req.owner = None
        if req.rid in self._done or req.client_cancelled:
            return
        req.retried_from.append(rname)
        self.stats_counters[counter] += 1
        metrics.inc(f"router_{counter}_total", replica=rname)
        if req.started_fwd:
            # a streaming client must reset its token accumulation:
            # the replacement replica re-generates from the prompt,
            # and its own `started` is forwarded again
            req.started_fwd = False
            self._forward(req, protocol.RETRYING,
                          dict(retried_from=list(req.retried_from),
                               reason=why))
        if not self._dispatch(req) and not req.assigned \
                and req.rid not in self._pending:
            self._pending.append(req.rid)

    # -- maintenance ---------------------------------------------------
    def _check_timeouts(self, now: float):
        for req in list(self._requests.values()):
            if req.deadline is not None and now >= req.deadline:
                for rname in list(req.assigned):
                    self._send_replica(rname, (protocol.CANCEL, req.rid))
                metrics.inc("router_expired_total")
                self._finish(req, protocol.EXPIRED, {}, from_replica=None)
                continue
            for rname, at in list(req.assigned.items()):
                if rname not in req.accepted \
                        and now - at > self.dispatch_timeout:
                    rep = self._replicas.get(rname)
                    if rep is not None:
                        rep.breaker.record_failure()
                        rep.inflight.discard(req.rid)
                    self._fail_assignment(
                        req, rname,
                        why=protocol.WHY_DISPATCH_TIMEOUT)
            if (self.response_timeout is not None and req.assigned
                    and now - req.last_event_at > self.response_timeout):
                # accepted but gone quiet (e.g. a dropped terminal
                # send): treat the laggard copies as failed
                for rname in list(req.assigned):
                    rep = self._replicas.get(rname)
                    if rep is not None:
                        rep.breaker.record_failure()
                        rep.inflight.discard(req.rid)
                    self._send_replica(rname, (protocol.CANCEL, req.rid))
                    self._fail_assignment(
                        req, rname,
                        why=protocol.WHY_RESPONSE_TIMEOUT)

    def _maybe_hedge(self, now: float):
        if self.hedge_delay is None:
            return
        for req in list(self._requests.values()):
            if req.owner is not None or not req.assigned \
                    or req.client_cancelled:
                continue
            n_extra = len(req.assigned) - 1
            if n_extra >= self.max_hedges:
                continue
            first_at = min(req.assigned.values())
            if now - first_at < self.hedge_delay * (n_extra + 1):
                continue
            if self._dispatch(req):
                req.hedged = True
                self.stats_counters["hedges"] += 1
                metrics.inc("router_hedges_total")

    def _probe_breakers(self, now: float):
        for rep in self._replicas.values():
            if rep.lost or rep.retiring:
                continue
            br = rep.breaker
            if br.ready_to_probe():
                br.half_open()
                rep.probe_sent_at = now
                self._send_replica(rep.name, (protocol.PING,))
            elif (br.state is BreakerState.HALF_OPEN
                  and rep.probe_sent_at is not None
                  and now - rep.probe_sent_at > self.probe_timeout):
                rep.probe_sent_at = None
                br.record_failure()  # probe unanswered: re-open

    # -- delivery ------------------------------------------------------
    def _forward(self, req: _RouterRequest, kind: str, data: dict):
        self._send_ident(req.ident, kind, req.rid, data)

    def _send_ident(self, ident: bytes, kind: str, rid: str,
                    data: dict):
        if self._chaos is not None and self._chaos.check(
                self.router_name, f"send.{kind}") == "drop":
            return
        payload = pickle.dumps((kind, rid, data))
        try:
            self._front.send_multipart([ident, payload])
        except zmq.ZMQError as e:
            logger.warning("Router: dropping %s for %s: %s", kind,
                           rid, e)

    def _reply(self, ident: bytes, kind: str, rid: str, data: dict):
        self._send_ident(ident, kind, rid, data)

    def _finish(self, req: _RouterRequest, kind: str, data: dict,
                from_replica: Optional[str]):
        """Deliver THE terminal event for a request (at-most-once) and
        retire its state; twins still running are cancelled and their
        later terminals dedupe against ``_done``."""
        if req.rid in self._done:
            return
        data = dict(data or {})
        if req.retried_from:
            data["retried_from"] = list(req.retried_from)
        if req.hedged and from_replica is not None \
                and from_replica != req.primary:
            self.stats_counters["hedge_wins"] += 1
            metrics.inc("router_hedge_wins_total")
        if kind == protocol.REJECTED:
            self.stats_counters["rejections"] += 1
        elif kind == protocol.DONE:
            # end-to-end latency EWMA: the autoscale policy's
            # latency signal (docs/serving.md "Autoscaling")
            lat = max(0.0, self._clock() - req.created_at)
            self.latency_ewma_secs = lat \
                if self.latency_ewma_secs is None \
                else 0.2 * lat + 0.8 * self.latency_ewma_secs
            metrics.set_gauge("router_latency_ewma_secs",
                              self.latency_ewma_secs)
            # bucketed companion: p50/p95 for stats()/the autoscale
            # policy, and the histogram a Prometheus scrape of
            # /metrics turns into histogram_quantile()
            metrics.observe_hist("router_latency_seconds", lat)
        self._forward(req, kind, data)
        metrics.inc("router_terminals_total", kind=kind)
        self._done[req.rid] = kind
        while len(self._done) > self._done_cap:
            self._done.pop(next(iter(self._done)))
        for rname in list(req.assigned):
            if rname != from_replica:
                self._send_replica(rname, (protocol.CANCEL, req.rid))
            rep = self._replicas.get(rname)
            if rep is not None:
                rep.inflight.discard(req.rid)
        self._requests.pop(req.rid, None)
        if req.rid in self._pending:
            self._pending.remove(req.rid)

    # -- blocking health probe (startup / ops, not the serve loop) -----
    def probe(self, name: str, timeout: float = 2.0,
              max_hedges: int = 1) -> bool:
        """Hedged blocking health check of one replica: each attempt
        opens a fresh DEALER (attempts must not share a socket across
        threads), pings, and waits for the pong; the first pong wins
        and the losers are cancelled (``base.retry.hedged``). Returns
        False when no attempt succeeds within ``timeout``."""
        info = self.registry.replicas().get(name)
        if info is None:
            return False

        def attempt(att: retry.HedgeAttempt) -> bool:
            sock = self._ctx.socket(zmq.DEALER)
            try:
                sock.connect(info.address)
                sock.send(pickle.dumps((protocol.PING,)))
                while not att.cancelled.is_set():
                    if att.deadline is not None \
                            and time.monotonic() >= att.deadline:
                        raise TimeoutError(f"probe {name}: deadline")
                    if sock.poll(25):
                        kind = pickle.loads(sock.recv())[0]
                        if kind == protocol.PONG:
                            return True
                raise TimeoutError(f"probe {name}: cancelled")
            finally:
                sock.close(0)

        try:
            return bool(retry.hedged(
                attempt, delay=timeout / (1 + max_hedges),
                max_hedges=max_hedges, max_elapsed=timeout,
                what=f"probe:{name}"))
        except Exception:  # noqa: BLE001 - a failed probe is an answer
            return False

    # -- lifecycle -----------------------------------------------------
    def drain(self, timeout: float = 30.0):
        """Stop admitting, give in-flight requests ``timeout`` seconds
        to finish, then expire what remains (clients always get a
        terminal event)."""
        if self._draining:
            return
        self._draining = True
        deadline = self._clock() + timeout
        while self._requests and self._clock() < deadline:
            self.route_step(poll_timeout=0.01)
        for req in list(self._requests.values()):
            for rname in list(req.assigned):
                self._send_replica(rname, (protocol.CANCEL, req.rid))
            self._finish(req, protocol.EXPIRED,
                         dict(reason=protocol.REASON_ROUTER_DRAIN),
                         from_replica=None)

    def close(self):
        if self._closed:
            return
        self._closed = True
        for rep in self._replicas.values():
            if not rep.lost:
                rep.sock.close(0)
        self._front.close(0)

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        # quantiles from the router_latency_seconds histogram (None
        # until the first completed request): the autoscale policy can
        # key on tail latency instead of the EWMA
        hist = metrics.default_registry().histogram(
            "router_latency_seconds")
        return dict(
            self.stats_counters,
            pending=len(self._pending),
            inflight=len(self._requests),
            draining=self._draining,
            latency_ewma_secs=self.latency_ewma_secs,
            latency_p50=hist.quantile(0.5),
            latency_p95=hist.quantile(0.95),
            replicas={
                name: dict(epoch=rep.epoch, lost=rep.lost,
                           retiring=rep.retiring,
                           breaker=rep.breaker.state.name,
                           inflight=len(rep.inflight))
                for name, rep in sorted(self._replicas.items())})
