"""Weight hot-swap handle for long-running generation services.

A trainer (or a worker command handler) pushes fresh parameters with a
monotonically increasing version from any thread; the serving
scheduler installs them between decode iterations -- never mid-chunk,
so every decode step runs under exactly one weight version and every
sequence can be stamped with the versions it was generated under
(AReaL-style bounded-staleness rollouts; see docs/serving.md).
"""

import threading
from typing import Callable, Optional

from realhf_tpu.base import logging

logger = logging.getLogger("serving.weight_sync")


def _snapshot_tree(params):
    """Deep-copy every array leaf of a param tree (lazy jax import so
    the mailbox stays importable without an accelerator stack). A
    jax.Array leaf is immutable but may be DONATED by the caller's
    next jitted step, invalidating its buffer; ``jnp.array(x,
    copy=True)`` pins our own buffer either way."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def snap(x):
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        if isinstance(x, jnp.ndarray):
            return jnp.array(x, copy=True)
        return x  # scalars / static aux data

    return jax.tree.map(snap, params)


class WeightSync:
    """Thread-safe pending-weights mailbox. At most one pending swap is
    held: a newer push overwrites an older one that was never
    installed (the scheduler only ever wants the freshest weights)."""

    def __init__(self, version: int = 0):
        self._lock = threading.Lock()
        self._version = version
        self._pending: Optional[tuple] = None  # (version, params)
        self.swaps_installed = 0

    @property
    def version(self) -> int:
        """Version of the weights currently INSTALLED in the backend
        (pending pushes don't count until the scheduler swaps them)."""
        with self._lock:
            return self._version

    @property
    def pending_version(self) -> Optional[int]:
        with self._lock:
            return self._pending[0] if self._pending else None

    def push(self, params, version: int, copy: bool = True):
        """Offer new weights. ``version`` must exceed both the
        installed and any pending version (monotonic -- a stale push
        indicates a reordered delivery and is refused loudly).

        Ownership contract: with ``copy=True`` (the default) the
        mailbox snapshots every leaf, so the caller remains free to
        mutate -- or hand to a donating jit -- its own tree right
        after ``push`` returns; the pending swap cannot be corrupted
        underneath the scheduler. Pass ``copy=False`` ONLY when the
        caller transfers ownership of freshly materialized arrays it
        will never touch again (e.g. ``ChunkedWeightReceiver``, whose
        decode step already allocates new buffers)."""
        if copy:
            params = _snapshot_tree(params)
        with self._lock:
            floor = max(self._version,
                        self._pending[0] if self._pending else -1)
            if version <= floor:
                raise ValueError(
                    f"WeightSync.push: version {version} is not newer "
                    f"than {floor} (pushes must be monotonic).")
            self._pending = (version, params)

    def poll(self, install: Callable[[object], None]) -> Optional[int]:
        """Install pending weights, if any, via ``install(params)``
        (e.g. ``backend.swap_params``). Returns the new version or
        None. Called by the scheduler between decode iterations."""
        with self._lock:
            if self._pending is None:
                return None
            version, params = self._pending
            self._pending = None
        # install OUTSIDE the lock: it may device_put a large tree and
        # must not block concurrent pushes
        install(params)
        with self._lock:
            self._version = version
            self.swaps_installed += 1
        logger.info("Installed weights v%d.", version)
        return version
