"""Admission-controlled request queue for the rollout service.

The front door of the serving subsystem (docs/serving.md): every
incoming generation request passes through :class:`RequestQueue`,
which enforces a bounded queue depth (backpressure: reject with a
``retry_after`` hint instead of growing until host OOM), per-request
deadlines (expired entries never reach a decode slot), and priority
classes (interactive traffic overtakes batch rollouts at admission,
Orca/vLLM-style). The queue itself is policy-free about WHAT runs --
the :class:`~realhf_tpu.serving.scheduler.ContinuousScheduler` pops
from it whenever a decode slot frees up.

Thread-safe: the server's socket pump and a worker's command thread
may submit/cancel while the scheduler thread pops.
"""

import dataclasses
import enum
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from realhf_tpu.base import logging
from realhf_tpu.obs import metrics as obs_metrics
from realhf_tpu.serving import protocol

logger = logging.getLogger("serving.request_queue")


def count_expired(req: "GenRequest", n: int = 1):
    """Bump the per-class deadline-expiry counter
    (``serving_expired_total{class}``): every path that turns a
    request into the declared ``expired`` terminal -- queue shunt
    here, parked/active eviction in the scheduler -- attributes the
    loss to its admission class, so an SLO dashboard can tell
    interactive misses from batch absorption."""
    obs_metrics.inc("serving_expired_total", n,
                    **{"class": Priority(req.priority).name})


class Priority(enum.IntEnum):
    """Admission classes, served strictly in ascending order (FIFO
    within a class). ROLLOUT is the async-RLHF producer traffic that
    must never starve INTERACTIVE users."""
    INTERACTIVE = 0
    BATCH = 1
    ROLLOUT = 2


@dataclasses.dataclass
class GenRequest:
    """One queued generation request."""
    rid: str
    prompt: np.ndarray                    # [len] int32 token ids
    priority: Priority = Priority.BATCH
    #: absolute deadline on the queue's clock; None = no deadline.
    deadline: Optional[float] = None
    submitted_at: float = 0.0
    #: reject at admission unless the server's weights are at least
    #: this fresh (a trainer-side client can insist on post-update
    #: rollouts).
    min_weight_version: int = 0
    #: filled by the scheduler when the request enters a slot
    started_at: Optional[float] = None


@dataclasses.dataclass
class AdmissionVerdict:
    accepted: bool
    reason: str = ""
    #: backpressure hint (seconds) for rejected requests; the client
    #: should resubmit no sooner than this.
    retry_after: Optional[float] = None


class RequestQueue:
    """Bounded, deadline- and priority-aware admission queue.

    ``n_slots`` sizes the ``retry_after`` estimate: with a service-time
    EMA of ``s`` seconds per sequence and ``d`` requests queued, a new
    arrival would wait roughly ``s * (d + 1) / n_slots``.
    """

    def __init__(self, max_depth: int = 256, n_slots: int = 1,
                 max_prompt_len: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_depth = max_depth
        self.n_slots = max(1, n_slots)
        #: longest admissible prompt (tokens); None = unchecked. The
        #: server fills this from the backend so oversized prompts are
        #: rejected at admission instead of tripping a fill_slot error
        #: deep inside the scheduler.
        self.max_prompt_len = max_prompt_len
        self._clock = clock
        self._lock = threading.Lock()
        self._by_class: Dict[Priority, List[GenRequest]] = {
            p: [] for p in Priority}
        self._expired: List[GenRequest] = []
        self._draining = False
        # EMA of observed per-sequence service seconds (queue->done);
        # seeds at 1s so the very first backpressure hint is sane.
        self._service_ema = 1.0
        self.stats = dict(submitted=0, rejected=0, expired=0,
                          cancelled=0, popped=0)

    # -- admission -----------------------------------------------------
    def submit(self, req: GenRequest,
               current_weight_version: int = 0) -> AdmissionVerdict:
        now = self._clock()
        req.submitted_at = req.submitted_at or now
        with self._lock:
            if self._draining:
                self.stats["rejected"] += 1
                return AdmissionVerdict(
                    False, reason=protocol.REASON_DRAINING)
            if req.deadline is not None and req.deadline <= now:
                self.stats["rejected"] += 1
                return AdmissionVerdict(
                    False, reason=protocol.REASON_EXPIRED)
            if (self.max_prompt_len is not None
                    and len(req.prompt) > self.max_prompt_len):
                self.stats["rejected"] += 1
                return AdmissionVerdict(
                    False, reason=protocol.REASON_PROMPT_TOO_LONG)
            if req.min_weight_version > current_weight_version:
                self.stats["rejected"] += 1
                return AdmissionVerdict(
                    False, reason=protocol.REASON_WEIGHTS_BEHIND,
                    retry_after=self._service_ema)
            depth = sum(len(q) for q in self._by_class.values())
            if depth >= self.max_depth:
                self.stats["rejected"] += 1
                return AdmissionVerdict(
                    False, reason=protocol.REASON_BACKPRESSURE,
                    retry_after=self._retry_after(depth))
            self._by_class[Priority(req.priority)].append(req)
            self.stats["submitted"] += 1
            return AdmissionVerdict(True)

    def _retry_after(self, depth: int) -> float:
        return max(0.05, self._service_ema * (depth + 1) / self.n_slots)

    def note_service_time(self, secs: float):
        """Feed one completed request's queue->done wall span into the
        backpressure estimator."""
        with self._lock:
            self._service_ema = 0.8 * self._service_ema + 0.2 * max(
                1e-3, secs)

    # -- consumption ---------------------------------------------------
    def pop(self) -> Optional[GenRequest]:
        """Highest-priority non-expired request (FIFO within class);
        entries whose deadline passed are shunted to the expired list
        (``take_expired``) instead of wasting a prefill."""
        now = self._clock()
        with self._lock:
            for p in Priority:
                q = self._by_class[p]
                while q:
                    req = q.pop(0)
                    if req.deadline is not None and req.deadline <= now:
                        self._expired.append(req)
                        self.stats["expired"] += 1
                        count_expired(req)
                        continue
                    self.stats["popped"] += 1
                    return req
            return None

    def take_expired(self) -> List[GenRequest]:
        """Requests that expired while queued since the last call (the
        server turns these into client notifications)."""
        with self._lock:
            out, self._expired = self._expired, []
            return out

    def cancel(self, rid: str) -> bool:
        with self._lock:
            for q in self._by_class.values():
                for i, req in enumerate(q):
                    if req.rid == rid:
                        del q[i]
                        self.stats["cancelled"] += 1
                        return True
            return False

    # -- shutdown ------------------------------------------------------
    def start_drain(self) -> List[GenRequest]:
        """Refuse all future admissions and return (removing) every
        still-queued request so the server can bounce them to their
        clients -- graceful shutdown leaves no orphaned entries."""
        with self._lock:
            self._draining = True
            out: List[GenRequest] = []
            for p in Priority:
                out.extend(self._by_class[p])
                self._by_class[p] = []
            return out

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def __len__(self):
        with self._lock:
            return sum(len(q) for q in self._by_class.values())

    def __bool__(self):
        # an EMPTY queue is still a queue: without this, __len__ makes
        # `queue or default` silently replace a caller-provided empty
        # queue (the PR-2 `queue if queue is not None` footgun)
        return True

    def depth_by_class(self) -> Dict[str, int]:
        with self._lock:
            return {p.name: len(q) for p, q in self._by_class.items()}
